"""TierScheduler: the master's lifecycle-tiering loop.

Leader-only, like the RepairScheduler it is modeled on: every
`interval` it scans the EC shard registry, asks each holder for its
local tier state (/tier/status — shard mtimes give the age signal),
reads the volume's access temperature out of the telemetry rings
(`weed_volume_read_total`), and drives /tier/move POSTs at holders
whose shards the rules classify cold (out) or hot again (in).

Each holder tiers its OWN shards — the move verb streams that node's
local shard files to the backend through the bandwidth arbiter's
"tier" claimant, so a scan that surfaces many cold volumes cannot
stampede the cluster: the arbiter paces every holder independently
and yields to foreground serving.

Every move hop carries X-Weed-Deadline (one whole-move budget — a
wedged backend costs a bounded failed attempt, not a parked slot) and
X-Weed-Trace (plane=tier, so tier traffic competing with serving is
attributable in trace dumps).
"""

from __future__ import annotations

import threading
import time
import urllib.request
from collections import deque

from seaweedfs_tpu import trace
from seaweedfs_tpu.tier.rules import TierRules, tier_enabled
from seaweedfs_tpu.util import deadline as _deadline
from seaweedfs_tpu.util import wlog


class TierScheduler:
    def __init__(
        self,
        master,
        interval: float = 60.0,
        rules: TierRules | None = None,
        concurrency: int = 2,
        move_deadline_s: float = 600.0,
        cooldown_s: float = 120.0,
        temperature_window_s: float = 120.0,
    ):
        self.master = master
        self.interval = interval
        # None = re-read the env-backed rules every scan (operators
        # retune without a restart; tests inject a fixed TierRules)
        self.rules = rules
        self.concurrency = concurrency
        self.move_deadline_s = move_deadline_s
        self.cooldown_s = cooldown_s
        self.temperature_window_s = temperature_window_s
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._active = 0
        # (holder, vid) → unix time before which no new move launches
        self._cooling: dict[tuple[str, int], float] = {}
        self.history: deque = deque(maxlen=50)
        self.moves_started = 0
        self.moves_failed = 0
        self.last_scan_unix = 0.0

    # ------------------------------------------------------------------
    def start(self) -> None:
        # check+spawn under one hold: two concurrent start() calls must
        # not both see None and double-spawn the loop (weedlint v4
        # race-check-then-act, PR 19 round)
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="tier-scheduler"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def trigger(self) -> None:
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            if not getattr(self.master, "is_leader", True):
                continue
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 — the scheduler must survive
                import traceback

                wlog.warning(
                    "tier: scan crashed: %s", traceback.format_exc()
                )

    # ------------------------------------------------------------------
    # signals
    def read_rate(self, vid: int) -> float:
        """Telemetry-observed reads/s for this volume, summed across
        every scraped node; 0.0 (cold) with the collector off."""
        tel = getattr(self.master, "telemetry", None)
        if tel is None:
            return 0.0
        now = time.time()
        want = str(vid)
        with tel._targets_lock:
            targets = list(tel.targets.values())
        total = 0.0
        for ts in targets:
            total += ts.rate_sum(
                "weed_volume_read_total",
                self.temperature_window_s,
                now,
                label_filter=lambda l: l.get("volume") == want,
            )
        return total

    def _holder_urls(self, vid: int) -> list[str]:
        urls: set[str] = set()
        locs = self.master.topology.ec_shard_map.get(vid)
        if locs is None:
            return []
        for holders in locs.locations:
            for dn in holders:
                urls.add(dn.url)
        return sorted(urls)

    # ------------------------------------------------------------------
    def _http_json(self, method: str, url: str, timeout: float) -> dict:
        import json as _json

        req = urllib.request.Request(
            url, method=method, data=b"" if method == "POST" else None
        )
        # deadline plane: the whole move runs under one budget the
        # holder inherits (its backend IO derives timeouts from it)
        dl = _deadline.current()
        if dl is not None:
            req.add_header(_deadline.DEADLINE_HEADER, dl.header_value())
        tv = trace.header_value()
        if tv:
            req.add_header("X-Weed-Trace", tv)
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return _json.loads(r.read())

    def _run_move(self, holder: str, vid: int, direction: str, backend: str) -> None:
        t0 = time.time()
        err = ""
        try:
            with trace.span(f"tier.{direction}", plane="tier") as sp, \
                    _deadline.scope(
                        _deadline.Deadline.after(self.move_deadline_s)
                    ):
                if sp:
                    sp.annotate("vid", vid)
                qs = f"volumeId={vid}&direction={direction}"
                if direction == "out":
                    qs += f"&destination={backend}"
                self._http_json(
                    "POST",
                    f"http://{holder}/tier/move?{qs}",
                    timeout=self.move_deadline_s,
                )
        except Exception as e:  # noqa: BLE001 — recorded, retried next scan
            err = str(e)[:300]
            with self._lock:
                self.moves_failed += 1
            wlog.warning(
                "tier: %s vid %d @ %s failed: %s", direction, vid, holder, e
            )
        with self._lock:
            self._active -= 1
            self._cooling[(holder, vid)] = time.time() + self.cooldown_s
            self.history.append(
                {
                    "VolumeId": vid,
                    "Holder": holder,
                    "Direction": direction,
                    "FinishedUnix": round(time.time(), 3),
                    "Seconds": round(time.time() - t0, 3),
                    "Error": err,
                }
            )

    # ------------------------------------------------------------------
    def scan_once(self) -> int:
        """One scan over the EC registry; returns moves launched. Also
        the synchronous seam tests drive."""
        self.last_scan_unix = time.time()
        if not tier_enabled():
            return 0
        rules = self.rules or TierRules.from_env()
        if not rules.backend:
            return 0
        now = time.time()
        launched = 0
        status_cache: dict[str, dict] = {}
        for vid in list(self.master.topology.ec_shard_map):
            rate = self.read_rate(vid)
            for holder in self._holder_urls(vid):
                with self._lock:
                    if self._active + launched >= self.concurrency:
                        return launched
                    if now < self._cooling.get((holder, vid), 0.0):
                        continue
                st = status_cache.get(holder)
                if st is None:
                    try:
                        st = self._http_json(
                            "GET", f"http://{holder}/tier/status", timeout=10
                        )
                    except OSError as e:
                        wlog.info("tier: %s unreachable: %s", holder, e)
                        st = {}
                    status_cache[holder] = st
                row = st.get(str(vid))
                if row is None:
                    continue
                tiered = bool(row.get("Tiered"))
                mtime = float(row.get("NewestShardMtime") or 0.0)
                age = (now - mtime) if mtime > 0 else float("inf")
                direction = rules.decide(age, rate, tiered)
                if direction is None:
                    continue
                with self._lock:
                    # re-validate the cap inside the hold that takes
                    # the slot: the earlier check released the lock
                    # across the /tier/status fetch, and a concurrent
                    # scan (admin-triggered scan_once next to the loop)
                    # could have filled the budget in between
                    if self._active >= self.concurrency:
                        return launched
                    self._active += 1
                    self.moves_started += 1
                launched += 1
                threading.Thread(
                    target=self._run_move,
                    args=(holder, vid, direction, rules.backend),
                    daemon=True,
                    name=f"tier-{direction}-{vid}",
                ).start()
        return launched

    # ------------------------------------------------------------------
    def status_snapshot(self) -> dict:
        rules = self.rules or TierRules.from_env()
        with self._lock:
            return {
                "Enabled": tier_enabled(),
                "Rules": rules.to_dict(),
                "IntervalSeconds": self.interval,
                "Active": self._active,
                "MovesStarted": self.moves_started,
                "MovesFailed": self.moves_failed,
                "LastScanUnix": round(self.last_scan_unix, 3),
                "History": list(self.history),
            }
