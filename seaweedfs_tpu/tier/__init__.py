"""weedtier: lifecycle tiering of cold EC volumes to object storage.

Three pieces (docs/TIERING.md):

  * rules.py — the lifecycle policy: per-volume age + access
    temperature decide cold (tier out) vs hot (tier back in), with
    every threshold an env knob so operators tune without redeploys;
  * ec_tier.py — the volume-server engine: stream a sealed EC
    volume's shards to a configured `storage/backend`, publish the
    `.evf` attachment sidecar, and recall them with `.ecc` CRC
    verification on the way back;
  * scheduler.py — the master-side TierScheduler: leader-only scan
    over the EC registry, temperature fed from the telemetry rings,
    HTTP fan-out to the shard holders (every hop carries
    X-Weed-Deadline + X-Weed-Trace).

`WEED_TIER=0` disables the whole plane: the scheduler idles and the
volume servers refuse /tier/move — already-tiered volumes keep
serving (turning the switch off must never strand data remotely).
"""

from seaweedfs_tpu.tier.rules import TierRules, tier_enabled
from seaweedfs_tpu.tier.scheduler import TierScheduler

__all__ = ["TierRules", "TierScheduler", "tier_enabled"]
