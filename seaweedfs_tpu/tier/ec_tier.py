"""Volume-server tier engine: move a sealed EC volume's local shards
to an object-store backend and back (docs/TIERING.md).

Tier-out ordering (crash-safe without a journal):

  1. upload every local shard via `backend.copy_file` (each upload is
     itself atomic on the backend side — .part then rename for the
     dir backend, single PUT for s3), charging the bandwidth arbiter's
     "tier" claimant as the bytes stream;
  2. durably publish the `.evf` attachment sidecar
     (EcVolume.attach_remote — write tmp, fsync, rename, dirsync);
  3. delete the local shard files.

A crash before (2) leaves local data intact and the uploads as
re-uploadable orphans; after (2) both copies exist and local wins.
The `.ecx`/`.ecj`/`.ecc` sidecars always stay local — needle lookup
and delete-journal replay never touch the backend.

Tier-in downloads each shard to a temp name, verifies its whole-file
CRC-32C against the `.ecc` scrub sidecar when one exists (a backend
that rotted or truncated a shard is caught BEFORE the bytes are
trusted locally), durably publishes it at the shard path, mounts it,
then detaches the `.evf` and best-effort deletes the remote keys.
"""

from __future__ import annotations

import os
import threading

from seaweedfs_tpu.ec import ec_files
from seaweedfs_tpu.ec.ec_volume import RemoteEcAttachment
from seaweedfs_tpu.ec.ecc_sidecar import load_sidecar
from seaweedfs_tpu.scrub.arbiter import get_arbiter
from seaweedfs_tpu.storage import backend as bk
from seaweedfs_tpu.util import durable, wlog
from seaweedfs_tpu.util.crc import crc32c

_READ_CHUNK = 4 << 20


def _arbiter_progress(stop: threading.Event | None):
    """progress(done, pct) callback that charges the "tier" claimant
    for each new chunk the backend copy reports."""
    arb = get_arbiter()
    last = [0]

    def progress(done: int, pct: float) -> None:
        delta = done - last[0]
        last[0] = done
        if delta > 0:
            arb.take("tier", delta, stop=stop)

    return progress


def _file_crc32c(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_READ_CHUNK)
            if not chunk:
                break
            crc = crc32c(chunk, crc)
    return crc


def tiered_volume_count(store) -> int:
    n = 0
    for loc in store.locations:
        for ev in list(loc.ec_volumes.values()):
            if getattr(ev, "remote", None) is not None:
                n += 1
    return n


def _update_tiered_gauge(store) -> None:
    from seaweedfs_tpu.stats.metrics import TIERED_VOLUMES

    TIERED_VOLUMES.set(tiered_volume_count(store), store.node_label)


def tier_status(store) -> dict:
    """Per-EC-volume tier state on this server — the /tier/status
    surface the master's TierScheduler polls (shard mtimes feed the
    age signal; Tiered feeds the direction decision)."""
    out: dict[str, dict] = {}
    for loc in store.locations:
        for vid, ev in list(loc.ec_volumes.items()):
            newest_mtime = 0.0
            local = sorted(ev.shards)
            for sid in local:
                try:
                    newest_mtime = max(
                        newest_mtime, os.path.getmtime(ev.shards[sid].path)
                    )
                except (OSError, KeyError):
                    continue
            remote = getattr(ev, "remote", None)
            out[str(vid)] = {
                "Collection": ev.collection,
                "LocalShards": local,
                "Tiered": remote is not None,
                "Backend": remote.backend_name if remote else "",
                "RemoteShards": sorted(remote.shards) if remote else [],
                "NewestShardMtime": newest_mtime,
            }
    return out


def tier_out_ec(
    store, vid: int, backend_name: str, stop: threading.Event | None = None
) -> dict:
    """Move every local shard of EC volume `vid` to `backend_name`.
    Returns a summary dict; raises on any failure (uploaded orphans
    are best-effort deleted so a retry starts clean)."""
    from seaweedfs_tpu.stats.metrics import TIER_BYTES, TIER_MOVES

    ev = store.find_ec_volume(vid)
    if ev is None:
        raise ValueError(f"ec volume {vid} not found on this server")
    if ev.remote is not None:
        return {"VolumeId": vid, "AlreadyTiered": True}
    bk.ensure_builtin_factories()
    backend = bk.get_backend(backend_name)
    if backend is None:
        raise ValueError(f"backend {backend_name!r} not configured")
    progress = _arbiter_progress(stop)
    shards: dict[int, dict] = {}
    shard_size = 0
    moved_bytes = 0
    try:
        for sid in ev.shard_ids():
            path = ev.shards[sid].path
            size = os.path.getsize(path)
            key, copied = backend.copy_file(
                path, {"ext": ec_files.to_ext(sid)}, progress
            )
            shards[sid] = {"key": key, "size": copied}
            shard_size = max(shard_size, copied)
            moved_bytes += copied
            progress = _arbiter_progress(stop)  # fresh delta per shard
    except Exception:
        TIER_MOVES.labels("out", "error").inc()
        # undo the partial upload so a retry doesn't leak keys
        for info in shards.values():
            try:
                backend.delete_file(info["key"])
            except OSError:
                pass
        raise
    # the durable .evf publish is the commit point: from here the
    # remote copies are authoritative enough to delete local bytes
    ev.attach_remote(
        RemoteEcAttachment(backend.name, shard_size, shards)
    )
    for sid in list(ev.shards):
        shard = ev.shards.pop(sid)
        shard.close()
        try:
            os.remove(shard.path)
        except OSError as e:
            wlog.warning("tier-out vid %d: remove %s: %s", vid, shard.path, e)
    TIER_MOVES.labels("out", "ok").inc()
    TIER_BYTES.labels("out").inc(moved_bytes)
    _update_tiered_gauge(store)
    store.notify_change()
    wlog.warning(
        "tier: vid %d out to %s (%d shard(s), %d bytes)",
        vid, backend.name, len(shards), moved_bytes,
    )
    return {
        "VolumeId": vid,
        "Backend": backend.name,
        "Shards": sorted(shards),
        "Bytes": moved_bytes,
    }


def tier_in_ec(store, vid: int, stop: threading.Event | None = None) -> dict:
    """Recall EC volume `vid` from its backend: download, CRC-verify
    against the .ecc sidecar, durably publish, mount, detach."""
    from seaweedfs_tpu.stats.metrics import TIER_BYTES, TIER_MOVES

    ev = store.find_ec_volume(vid)
    if ev is None:
        raise ValueError(f"ec volume {vid} not found on this server")
    remote = ev.remote
    if remote is None:
        return {"VolumeId": vid, "NotTiered": True}
    bk.ensure_builtin_factories()
    backend = bk.get_backend(remote.backend_name)
    if backend is None:
        raise ValueError(
            f"backend {remote.backend_name!r} not configured on this "
            f"server (load storage config before recalling)"
        )
    ecc = load_sidecar(ev.base_name)
    moved_bytes = 0
    restored: list[int] = []
    try:
        for sid in sorted(remote.shards):
            if sid in ev.shards:
                continue  # kept local (keep_local tier-out, or partial)
            info = remote.shards[sid]
            dst = ev.base_name + ec_files.to_ext(sid)
            tmp = dst + ".tierin"
            backend.download_file(tmp, info["key"], _arbiter_progress(stop))
            got = os.path.getsize(tmp)
            if got != info["size"]:
                raise IOError(
                    f"shard {sid}: backend returned {got} of "
                    f"{info['size']} bytes"
                )
            if ecc is not None:
                want = ecc["shards"].get(str(sid))
                if want is not None and _file_crc32c(tmp) != want["crc"]:
                    raise IOError(
                        f"shard {sid}: CRC mismatch against .ecc sidecar "
                        f"— backend copy is corrupt; keeping remote "
                        f"attachment"
                    )
            durable.publish(tmp, dst)
            ev.mount_shard(sid)
            restored.append(sid)
            moved_bytes += got
    except Exception:
        TIER_MOVES.labels("in", "error").inc()
        # partial recall is fine: local shards win on reads, the .evf
        # still covers the rest — retry resumes where this stopped
        raise
    finally:
        for sid in sorted(remote.shards):
            tmp = ev.base_name + ec_files.to_ext(sid) + ".tierin"
            try:
                os.remove(tmp)
            except OSError:
                pass
    ev.detach_remote()
    for info in remote.shards.values():
        try:
            backend.delete_file(info["key"])
        except OSError as e:
            wlog.warning("tier-in vid %d: delete remote key: %s", vid, e)
    TIER_MOVES.labels("in", "ok").inc()
    TIER_BYTES.labels("in").inc(moved_bytes)
    _update_tiered_gauge(store)
    store.notify_change()
    wlog.warning(
        "tier: vid %d recalled from %s (%d shard(s), %d bytes)",
        vid, remote.backend_name, len(restored), moved_bytes,
    )
    return {
        "VolumeId": vid,
        "Backend": remote.backend_name,
        "Shards": restored,
        "Bytes": moved_bytes,
    }
