"""Lifecycle rules: when is an EC volume cold enough to tier out, and
when is a tiered one hot enough to recall?

Two signals per volume:

  * age — seconds since the newest shard file's mtime on its holder
    (EC volumes are sealed at encode time, so shard mtime IS the seal
    time; a rebuild refreshes it, which conveniently also restarts
    the cold clock on a volume the repair plane just touched);
  * temperature — the read rate the telemetry plane observed for the
    volume (`weed_volume_read_total` summed across holders over the
    collector window). With telemetry off the rate reads 0.0, i.e.
    cold — age alone then gates tiering, which is the conservative
    failure mode (an untelemetered cluster still tiers, and recall is
    driven by the holders' own counters when the collector returns).

Hysteresis: the recall threshold sits above the tier-out threshold so
a volume flapping around one rate doesn't ping-pong shards through
the backend.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


def tier_enabled() -> bool:
    """`WEED_TIER=0` kills the tiering plane wholesale: the scheduler
    idles and /tier/move refuses. Already-tiered volumes keep serving
    (disabling the plane must never strand data remotely)."""
    return os.environ.get("WEED_TIER", "1") != "0"


def _float(raw: str | None, default: float) -> float:
    # callers pass os.environ.get("WEED_...") inline so the weedlint
    # contract-env rule can see which knob each read belongs to
    try:
        return float(raw or default)
    except ValueError:
        return default


@dataclass
class TierRules:
    """The policy knobs, env-overridable (OPERATIONS.md):

    WEED_TIER_BACKEND      destination backend name ("type.id"); empty
                           disables the scheduler (no destination)
    WEED_TIER_MIN_AGE_S    a volume younger than this never tiers out
    WEED_TIER_COLD_RPS     read rate at/below which a volume is cold
    WEED_TIER_HOT_RPS      read rate above which a tiered volume is
                           recalled (> COLD_RPS for hysteresis)
    """

    backend: str = ""
    min_age_s: float = 3600.0
    cold_reads_per_s: float = 0.05
    hot_reads_per_s: float = 1.0

    @classmethod
    def from_env(cls) -> "TierRules":
        return cls(
            backend=os.environ.get("WEED_TIER_BACKEND", ""),
            min_age_s=_float(os.environ.get("WEED_TIER_MIN_AGE_S"), 3600.0),
            cold_reads_per_s=_float(os.environ.get("WEED_TIER_COLD_RPS"), 0.05),
            hot_reads_per_s=_float(os.environ.get("WEED_TIER_HOT_RPS"), 1.0),
        )

    def decide(
        self, age_s: float, reads_per_s: float, tiered: bool
    ) -> str | None:
        """"out", "in", or None (leave it where it is)."""
        if tiered:
            if reads_per_s > self.hot_reads_per_s:
                return "in"
            return None
        if age_s >= self.min_age_s and reads_per_s <= self.cold_reads_per_s:
            return "out"
        return None

    def to_dict(self) -> dict:
        return {
            "Backend": self.backend,
            "MinAgeSeconds": self.min_age_s,
            "ColdReadsPerSec": self.cold_reads_per_s,
            "HotReadsPerSec": self.hot_reads_per_s,
        }
