"""Experimental select-from-files query engine (reference weed/query/)."""

from seaweedfs_tpu.query.json_query import Query, query_json  # noqa: F401
