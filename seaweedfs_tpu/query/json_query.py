"""JSON-lines filter + projection.

Behavioral match of reference weed/query/json/query_json.go:18-105
(gjson-based): a Query(field, op, value) filters each JSON line by the
field's *runtime type* — string ops compare lexically, number ops
numerically, booleans have the reference's quirky ordering table — and
projections pull dotted-path fields from passing lines. The `%` / `!%`
ops are glob matches (tidwall/match → fnmatch)."""

from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass
from typing import Any

_MISSING = object()


@dataclass
class Query:
    field: str = ""
    op: str = ""
    value: str = ""


def get_path(obj: Any, path: str):
    """Dotted-path lookup ("a.b.2.c"); integer segments index arrays.
    Returns _MISSING when any segment is absent (gjson.Get role)."""
    cur = obj
    for seg in path.split("."):
        if isinstance(cur, dict):
            if seg not in cur:
                return _MISSING
            cur = cur[seg]
        elif isinstance(cur, list):
            try:
                cur = cur[int(seg)]
            except (ValueError, IndexError):
                return _MISSING
        else:
            return _MISSING
    return cur


def _filter(doc: Any, q: Query) -> bool:
    if not q.field:
        return True  # no filter: projection-only select passes all
    value = get_path(doc, q.field)
    if value is _MISSING:
        return False
    if q.op == "":
        return True  # existence check
    rpv = q.value
    if isinstance(value, str):
        table = {
            "=": value == rpv,
            "!=": value != rpv,
            "<": value < rpv,
            "<=": value <= rpv,
            ">": value > rpv,
            ">=": value >= rpv,
            "%": fnmatch.fnmatchcase(value, rpv),
            "!%": not fnmatch.fnmatchcase(value, rpv),
        }
        return table.get(q.op, False)
    if isinstance(value, bool):
        # gjson True/False tables (query_json.go:81-104)
        if value:
            return {
                "=": rpv == "true",
                "!=": rpv != "true",
                ">": rpv == "false",
                ">=": True,
            }.get(q.op, False)
        return {
            "=": rpv == "false",
            "!=": rpv != "false",
            "<": rpv == "true",
            "<=": True,
        }.get(q.op, False)
    if isinstance(value, (int, float)):
        try:
            rpvn = float(rpv)
        except ValueError:
            rpvn = 0.0
        table = {
            "=": value == rpvn,
            "!=": value != rpvn,
            "<": value < rpvn,
            "<=": value <= rpvn,
            ">": value > rpvn,
            ">=": value >= rpvn,
        }
        return table.get(q.op, False)
    return False


def query_json(
    json_line: str, projections: list[str], query: Query
) -> tuple[bool, list]:
    """(passed_filter, projected values) for one JSON line
    (QueryJson, query_json.go:18)."""
    try:
        doc = json.loads(json_line)
    except ValueError:
        return False, []
    if not _filter(doc, query):
        return False, []
    values = []
    for p in projections:
        v = get_path(doc, p)
        values.append(None if v is _MISSING else v)
    return True, values
