"""JSON-lines filter + projection.

Behavioral match of reference weed/query/json/query_json.go:18-105
(gjson-based): a Query(field, op, value) filters each JSON line by the
field's *runtime type* — string ops compare lexically, number ops
numerically, booleans have the reference's quirky ordering table — and
projections pull dotted-path fields from passing lines. The `%` / `!%`
ops are glob matches (tidwall/match → fnmatch)."""

from __future__ import annotations

import fnmatch
import json
from dataclasses import dataclass
from typing import Any

_MISSING = object()


@dataclass
class Query:
    field: str = ""
    op: str = ""
    value: str = ""


def get_path(obj: Any, path: str):
    """gjson-style path lookup (gjson.Get role, query_json.go:18).

    Supported path syntax (the subset the reference's queries use):
      a.b.c    dotted descent through objects
      a.2.c    integer segments index arrays (no negative indices,
               matching gjson)
      a.*.c    `*`/`?` glob segments match object keys; the FIRST
               matching key wins (gjson's wildcard rule)
      a.#      length of the array at `a`
      a.#.c    collects `c` from every element of `a` (elements where
               the sub-path is absent are skipped, like gjson)
    Returns _MISSING when any segment can't resolve."""
    return _get(obj, path.split("."))


def _get(cur: Any, segs: list[str]):
    for i, seg in enumerate(segs):
        if isinstance(cur, dict):
            if seg in cur:
                cur = cur[seg]
                continue
            if "*" in seg or "?" in seg:
                rest = segs[i + 1 :]
                for k in cur:
                    if fnmatch.fnmatchcase(k, seg):
                        v = _get(cur[k], rest)
                        if v is not _MISSING:
                            return v
                return _MISSING
            return _MISSING
        if isinstance(cur, list):
            if seg == "#":
                rest = segs[i + 1 :]
                if not rest:
                    return len(cur)
                return [
                    v
                    for el in cur
                    if (v := _get(el, rest)) is not _MISSING
                ]
            try:
                idx = int(seg)
            except ValueError:
                return _MISSING
            if idx < 0 or idx >= len(cur):
                return _MISSING
            cur = cur[idx]
            continue
        return _MISSING
    return cur


def _filter(doc: Any, q: Query) -> bool:
    if not q.field:
        return True  # no filter: projection-only select passes all
    value = get_path(doc, q.field)
    if value is _MISSING:
        return False
    if q.op == "":
        return True  # existence check
    rpv = q.value
    if isinstance(value, str):
        table = {
            "=": value == rpv,
            "!=": value != rpv,
            "<": value < rpv,
            "<=": value <= rpv,
            ">": value > rpv,
            ">=": value >= rpv,
            "%": fnmatch.fnmatchcase(value, rpv),
            "!%": not fnmatch.fnmatchcase(value, rpv),
        }
        return table.get(q.op, False)
    if isinstance(value, bool):
        # gjson True/False tables (query_json.go:81-104)
        if value:
            return {
                "=": rpv == "true",
                "!=": rpv != "true",
                ">": rpv == "false",
                ">=": True,
            }.get(q.op, False)
        return {
            "=": rpv == "false",
            "!=": rpv != "false",
            "<": rpv == "true",
            "<=": True,
        }.get(q.op, False)
    if isinstance(value, (int, float)):
        try:
            rpvn = float(rpv)
        except ValueError:
            rpvn = 0.0
        table = {
            "=": value == rpvn,
            "!=": value != rpvn,
            "<": value < rpvn,
            "<=": value <= rpvn,
            ">": value > rpvn,
            ">=": value >= rpvn,
        }
        return table.get(q.op, False)
    return False


def query_json(
    json_line: str, projections: list[str], query: Query
) -> tuple[bool, list]:
    """(passed_filter, projected values) for one JSON line
    (QueryJson, query_json.go:18)."""
    try:
        doc = json.loads(json_line)
    except ValueError:
        return False, []
    if not _filter(doc, query):
        return False, []
    values = []
    for p in projections:
        v = get_path(doc, p)
        values.append(None if v is _MISSING else v)
    return True, values
