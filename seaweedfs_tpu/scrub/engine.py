"""ScrubEngine: the volume server's background integrity sweeper.

Until this plane existed, integrity was purely reactive — a corrupt EC
shard was only noticed when a foreground read tripped over it
(EcVolume._quarantine_if_truncated), the quarantine never left the
process, and repair was a human typing `ec.rebuild`. The engine makes
detection continuous: every `interval` seconds it sweeps

  * plain volumes — every live needle re-read through the CRC32-C
    check (scrub/verify.scan_plain_volume);
  * EC volumes — all 14 shards streamed tile by tile through the
    parity re-verify (scrub/verify.verify_parity_stream), remote
    shards fetched from their holders via the same VolumeEcShardRead
    path degraded reads use; localized corrupt LOCAL shards are
    quarantined (unmount + .bad rename) on the spot.

Foreground p99 is protected by a token bucket charged before every
byte read, and by sweeping in bounded segments (the engine yields the
GIL and the bucket between segments). Cursors + health persist per
disk location (scrub/state.py) so restarts resume mid-volume. Every
corruption or quarantine fires `on_event` — the volume server wires
that to its heartbeat wake-up, so the master learns on the next forced
delta beat, not the next tick.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

from seaweedfs_tpu import trace
from seaweedfs_tpu.scrub import verify as _verify
from seaweedfs_tpu.scrub.ratelimit import TokenBucket
from seaweedfs_tpu.scrub.state import ScrubState, VolumeScrubHealth
from seaweedfs_tpu.util import wlog

# per-shard bytes verified per EC segment / per plain segment before
# the engine persists cursors and re-checks stop/trigger; small enough
# that trigger() and stop() stay responsive at throttled rates
SEGMENT_BYTES = 64 * 1024 * 1024

STATE_FILE = "scrub_state.json"


class ScrubEngine:
    def __init__(
        self,
        store,
        *,
        interval: float = 600.0,
        rate_mb_s: float = 64.0,
        tile_bytes: int = _verify.DEFAULT_TILE_BYTES,
        fetcher_factory: Optional[Callable] = None,
        on_event: Optional[Callable[[], None]] = None,
        node_label: str = "",
    ):
        self.store = store
        self.interval = interval
        self.tile_bytes = tile_bytes
        # fetcher_factory(ev) -> fetch(sid, offset, size) -> bytes|None
        # (the volume server passes _remote_shard_fetcher so sweeps
        # reach shards this node doesn't hold)
        self.fetcher_factory = fetcher_factory
        self.on_event = on_event or (lambda: None)
        self.node_label = node_label
        # burst capped at 2 tiles (not the bucket's default of one
        # second of rate): a sweep start must trickle, not storm — a
        # 64 MB burst of back-to-back preads+CRC is a foreground p99
        # spike regardless of the steady-state rate
        self.limiter = TokenBucket(
            rate_mb_s * 1024 * 1024,
            burst_bytes=2 * tile_bytes if rate_mb_s > 0 else None,
        )
        self.rate_mb_s = rate_mb_s
        self._states: dict[str, ScrubState] = {}
        for loc in store.locations:
            self._states[loc.directory] = ScrubState(
                os.path.join(loc.directory, STATE_FILE)
            )
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._priority: list[int] = []  # vids queued by trigger()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.sweeps_completed = 0
        self.sweep_running = False
        self.last_sweep_started = 0.0
        self.last_sweep_finished = 0.0

    # ------------------------------------------------------------------
    def start(self) -> None:
        # check+spawn under one hold: two concurrent start() calls must
        # not both see None and double-spawn the loop (weedlint v4
        # race-check-then-act, PR 19 round)
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="scrub-engine"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def trigger(self, vid: int | None = None) -> None:
        """Start a sweep now; with `vid`, scrub that volume first."""
        if vid is not None:
            with self._lock:
                if vid not in self._priority:
                    self._priority.append(vid)
        self._wake.set()

    def _loop(self) -> None:
        # first sweep only after one full interval: a restart storm
        # must not synchronize every node into sweeping at boot
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.sweep_once()
            except Exception:  # noqa: BLE001 - the sweeper must survive
                import traceback

                # NOTE: wlog.warning has no exc_info kwarg — passing it
                # raises TypeError and KILLS this thread silently (the
                # engine then never sweeps again); format explicitly
                wlog.warning(
                    "scrub: sweep crashed: %s", traceback.format_exc()
                )

    # ------------------------------------------------------------------
    def sweep_once(self) -> dict:
        """One full pass over every local volume (resuming cursors).
        Returns a summary dict (also used by tests and /scrub/trigger)."""
        self.sweep_running = True
        self.last_sweep_started = time.time()
        summary = {"volumes": 0, "ec_volumes": 0, "corruptions": 0,
                   "quarantined": 0, "scanned_bytes": 0}
        try:
            with self._lock:
                priority = list(self._priority)
                self._priority.clear()

            def order(vids):
                return sorted(vids, key=lambda v: (v not in priority, v))

            for loc in self.store.locations:
                state = self._states[loc.directory]
                for vid in order(list(loc.volumes)):
                    if self._stop.is_set():
                        return summary
                    v = loc.volumes.get(vid)
                    if v is None:
                        continue
                    try:
                        # tracing plane: each volume's scrub is a span
                        # tagged plane=scrub, so any remote reads it
                        # triggers are visibly NOT serving traffic
                        with trace.span(
                            "scrub.volume", plane="scrub",
                            node=self.node_label,
                        ) as sp:
                            if sp:
                                sp.annotate("vid", vid)
                            r = self._scrub_plain(v, state)
                    except Exception as e:  # noqa: BLE001
                        # one un-scrubable volume (deleted/compacted
                        # under us mid-sweep) must not abort the pass
                        # for every volume after it
                        wlog.warning(
                            "scrub: volume %d sweep failed: %r", vid, e
                        )
                        continue
                    summary["volumes"] += 1
                    summary["corruptions"] += r[0]
                    summary["scanned_bytes"] += r[1]
                for vid in order(list(loc.ec_volumes)):
                    if self._stop.is_set():
                        return summary
                    ev = loc.ec_volumes.get(vid)
                    if ev is None:
                        continue
                    try:
                        with trace.span(
                            "scrub.ec_volume", plane="scrub",
                            node=self.node_label,
                        ) as sp:
                            if sp:
                                sp.annotate("vid", vid)
                            c, q, b = self._scrub_ec(ev, state)
                    except Exception as e:  # noqa: BLE001
                        wlog.warning(
                            "scrub: ec volume %d sweep failed: %r", vid, e
                        )
                        continue
                    summary["ec_volumes"] += 1
                    summary["corruptions"] += c
                    summary["quarantined"] += q
                    summary["scanned_bytes"] += b
                # prune rows for volumes that left this location
                # (deleted, EC-migrated, moved): their stale health
                # must not keep riding heartbeats. list() snapshots —
                # foreground allocate/delete mutates these dicts from
                # HTTP handler threads mid-iteration
                present = {(vid, False) for vid in list(loc.volumes)} | {
                    (vid, True) for vid in list(loc.ec_volumes)
                }
                for key in list(state.volumes):
                    if key not in present:
                        state.forget(*key)
                state.save()
            self.sweeps_completed += 1
            self.last_sweep_finished = time.time()
        finally:
            self.sweep_running = False
        return summary

    # ------------------------------------------------------------------
    def _scrub_plain(self, v, state: ScrubState) -> tuple[int, int]:
        from seaweedfs_tpu.stats.metrics import (
            SCRUB_CORRUPTIONS,
            SCRUB_SCANNED,
        )

        h = state.get(v.id, is_ec=False)
        found = scanned = 0
        if h.cursor == 0:
            h.pass_corruptions = 0  # fresh pass starts its own count
        # ONE needle-map enumeration per volume pass, sliced across
        # segments via `consumed` — re-sorting millions of keys every
        # 64 MiB segment would be O(segments x needles) of GIL time
        # the token bucket never accounts for
        keys = _verify.live_needle_keys(v, h.cursor)
        while not self._stop.is_set():
            res = _verify.scan_plain_volume(
                v,
                after_key=h.cursor,
                keys=keys,
                limiter=self.limiter,
                stop=self._stop,
                max_bytes=SEGMENT_BYTES,
            )
            keys = keys[res.consumed :]
            h.cursor = res.last_key
            h.scanned_bytes += res.scanned_bytes
            scanned += res.scanned_bytes
            SCRUB_SCANNED.labels(self.node_label, "plain").inc(
                res.scanned_bytes
            )
            if res.corruptions:
                found += len(res.corruptions)
                h.corruptions_found += len(res.corruptions)
                h.pass_corruptions += len(res.corruptions)
                # report new damage NOW (never zeroed mid-pass: a
                # still-corrupt volume must not read clean to the
                # scheduler, or its backoff state would reset each sweep)
                h.sweep_corruptions = max(
                    h.sweep_corruptions, h.pass_corruptions
                )
                h.last_error = (
                    f"needle {res.corruptions[-1][0]}: "
                    f"{res.corruptions[-1][1]}"
                )
                SCRUB_CORRUPTIONS.labels(self.node_label, "plain").inc(
                    len(res.corruptions)
                )
                wlog.warning(
                    "scrub: volume %d: %d corrupt needle(s), e.g. %s",
                    v.id, len(res.corruptions), h.last_error,
                )
                self.on_event()
            state.save()
            if res.aborted:
                break
            if res.complete:
                h.cursor = 0
                h.sweeps += 1
                h.last_sweep_unix = time.time()
                # a COMPLETED pass is the new truth: drops to 0 after
                # a repair, stays honest for persistent damage
                h.sweep_corruptions = h.pass_corruptions
                if h.sweep_corruptions == 0:
                    h.last_error = ""  # clean pass supersedes history
                state.save()
                break
        return found, scanned

    # ------------------------------------------------------------------
    def _ec_readers(self, ev):
        """14 shard readers: local pread where mounted, remote
        VolumeEcShardRead (via the server's fetcher) otherwise.
        Returns None when some shard is reachable nowhere."""
        from seaweedfs_tpu.ec.ec_volume import ShardTruncated

        fetch = self.fetcher_factory(ev) if self.fetcher_factory else None
        readers = []
        for sid in range(ev.rs.total_shards):
            shard = ev.shards.get(sid)
            if shard is not None:
                def read_local(off, size, _s=shard, _sid=sid):
                    # clamp like VolumeEcShardRead: a walk off the end
                    # of the shard is EOF, not truncation
                    n = min(size, max(0, _s.size - off))
                    if n <= 0:
                        return b""
                    try:
                        return _s.read_at(off, n)
                    except ShardTruncated:
                        # the sweep found a shard shorter on disk than
                        # its nominal length: same quarantine a
                        # foreground read would perform
                        ev._quarantine_if_truncated(_sid)
                        raise

                readers.append(read_local)
            elif fetch is not None:
                def read_remote(off, size, _sid=sid, _f=fetch):
                    data = _f(_sid, off, size)
                    if data is None:
                        raise RuntimeError(
                            f"ec shard {_sid} reachable nowhere"
                        )
                    return data

                readers.append(read_remote)
            else:
                return None
        return readers

    def _scrub_ec(self, ev, state: ScrubState) -> tuple[int, int, int]:
        from seaweedfs_tpu.stats.metrics import (
            SCRUB_CORRUPTIONS,
            SCRUB_SCANNED,
        )

        h = state.get(ev.volume_id, is_ec=True)
        # `.ecc` fast path: a fresh sidecar turns the 14-shard parity
        # re-verify into a read+CRC pass (scrub/verify.verify_ecc_stream).
        # Eligibility is checked every volume visit; missing/stale
        # sidecars fall through LOUDLY (wlog + fallback counter) — the
        # parity sweep below still verifies everything.
        ecc = self._scrub_ec_ecc(ev, state, h)
        if ecc is not None:
            return ecc
        found = quarantined = scanned = 0
        if h.cursor == 0:
            h.pass_corruptions = 0
        while not self._stop.is_set():
            readers = self._ec_readers(ev)
            if readers is None:
                h.last_error = "shards missing and no remote fetcher"
                state.save()
                break
            # snapshot so the error handler can see quarantines that
            # happened DURING the verify (read_local self-quarantines a
            # truncated shard before re-raising)
            quarantined_before = set(ev.quarantined)
            try:
                res = _verify.verify_parity_stream(
                    readers,
                    rs=ev.rs,
                    start=h.cursor,
                    tile_bytes=self.tile_bytes,
                    limiter=self.limiter,
                    stop=self._stop,
                    max_bytes=SEGMENT_BYTES,
                )
            except (RuntimeError, OSError) as e:
                # length skew or an unreachable remote shard. Skew can
                # be transient (a shard being rebuilt under us) — but a
                # shard that was truncated BEFORE mount has a stale
                # short .size that the local reader clamps to, so the
                # skew is permanent and would stall this volume's scrub
                # forever. Re-verify every local shard's on-disk length
                # against the siblings' nominal; genuinely short ones
                # get the same quarantine a foreground read performs,
                # and the sweep retries immediately via remote fetch.
                evicted = sum(
                    1
                    for sid in list(ev.shards)
                    if ev._quarantine_if_truncated(sid)
                )
                # read_local may have quarantined the culprit itself
                # mid-verify (ShardTruncated path) — that eviction also
                # makes an immediate remote-fetch retry viable
                evicted += len(set(ev.quarantined) - quarantined_before)
                if evicted:
                    quarantined += evicted
                    self.on_event()
                    continue
                h.last_error = str(e)
                state.save()
                break
            h.cursor = res.end_offset
            h.scanned_bytes += res.bytes_per_shard * ev.rs.total_shards
            scanned += res.bytes_per_shard * ev.rs.total_shards
            SCRUB_SCANNED.labels(self.node_label, "ec").inc(
                res.bytes_per_shard * ev.rs.total_shards
            )
            if res.corrupt:
                found += len(res.bad_tiles)
                h.corruptions_found += len(res.bad_tiles)
                h.pass_corruptions += len(res.bad_tiles)
                h.sweep_corruptions = max(
                    h.sweep_corruptions, h.pass_corruptions
                )
                SCRUB_CORRUPTIONS.labels(self.node_label, "ec").inc(
                    len(res.bad_tiles)
                )
                h.last_error = (
                    f"parity mismatch {res.mismatch}; culprits "
                    f"{sorted(res.culprits)}; unlocalized {res.unlocalized}"
                )
                for sid in sorted(res.culprits):
                    if sid in ev.shards:
                        wlog.warning(
                            "scrub: quarantining corrupt shard %d of "
                            "vid %d (%d bad tile(s))",
                            sid, ev.volume_id, res.culprits[sid],
                        )
                        if ev.quarantine_shard(
                            sid, f"scrub: {res.culprits[sid]} corrupt tile(s)"
                        ):
                            quarantined += 1
                    else:
                        wlog.warning(
                            "scrub: vid %d shard %d corrupt on a REMOTE "
                            "holder; reporting via heartbeat",
                            ev.volume_id, sid,
                        )
                self.on_event()
            state.save()
            if res.aborted:
                break
            if res.complete:
                h.cursor = 0
                h.sweeps += 1
                h.last_sweep_unix = time.time()
                h.sweep_corruptions = h.pass_corruptions
                if h.sweep_corruptions == 0:
                    h.last_error = ""
                    # a clean FULL pass proves the cluster-wide volume
                    # is healthy again (the pass read the quarantined
                    # shards' rebuilt replacements, wherever they
                    # live): local quarantine markers are now history,
                    # not current damage — clearing stops the master
                    # re-flagging a repaired volume forever
                    for sid in list(ev.quarantined):
                        ev.quarantined.pop(sid, None)
                        self.store.clear_quarantine(ev.volume_id, sid)
                state.save()
                break
        return found, quarantined, scanned

    # ------------------------------------------------------------------
    def _scrub_ec_ecc(self, ev, state: ScrubState, h) -> tuple[int, int, int] | None:
        """The `.ecc` sidecar arm of the EC sweep; None = not eligible
        (knob off, shards not all local, sidecar missing/stale) — the
        caller then runs the full parity re-verify.

        Eligibility requires every shard LOCAL: the sidecar lives next
        to the shards it attests, and a CRC pass over remote shards
        would just move the same bytes over the network that the parity
        path moves (each holder scrubs its own copy instead)."""
        from seaweedfs_tpu.ec import ecc_sidecar
        from seaweedfs_tpu.stats.metrics import (
            SCRUB_CORRUPTIONS,
            SCRUB_ECC_FALLBACK,
            SCRUB_SCANNED,
        )

        if not ecc_sidecar.ecc_enabled():
            return None
        local = {sid: s.path for sid, s in ev.shards.items()}
        if len(local) != ev.rs.total_shards:
            return None  # remote shards: parity path, no fallback noise
        status, doc = ecc_sidecar.sidecar_status(
            ev.base_name, local, ev.rs.total_shards
        )
        if status != "ok":
            wlog.warning(
                "scrub: vid %d .ecc sidecar %s; falling back to full "
                "parity re-verify",
                ev.volume_id, status,
            )
            SCRUB_ECC_FALLBACK.labels(self.node_label, status).inc()
            return None
        found = quarantined = scanned = 0
        if h.ecc_shard == 0 and h.ecc_offset == 0:
            h.pass_corruptions = 0
        while not self._stop.is_set():
            res = _verify.verify_ecc_stream(
                local,
                doc,
                start_shard=h.ecc_shard,
                start_offset=h.ecc_offset,
                run_crc=h.ecc_crc,
                tile_bytes=self.tile_bytes,
                limiter=self.limiter,
                stop=self._stop,
                max_bytes=SEGMENT_BYTES,
            )
            h.ecc_shard = res.shard_idx
            h.ecc_offset = res.offset
            h.ecc_crc = res.run_crc
            h.scanned_bytes += res.bytes_scanned
            scanned += res.bytes_scanned
            SCRUB_SCANNED.labels(self.node_label, "ec").inc(res.bytes_scanned)
            if res.corrupt:
                found += len(res.bad_shards)
                h.corruptions_found += len(res.bad_shards)
                h.pass_corruptions += len(res.bad_shards)
                h.sweep_corruptions = max(
                    h.sweep_corruptions, h.pass_corruptions
                )
                SCRUB_CORRUPTIONS.labels(self.node_label, "ec").inc(
                    len(res.bad_shards)
                )
                worst = sorted(res.bad_shards)[-1]
                h.last_error = (
                    f".ecc mismatch shard {worst}: {res.bad_shards[worst]}"
                )
                # the sidecar pins the culprit directly (a CRC names
                # its shard) — no localization pass needed
                for sid, why in sorted(res.bad_shards.items()):
                    if sid in ev.shards:
                        wlog.warning(
                            "scrub: quarantining shard %d of vid %d "
                            "(.ecc: %s)", sid, ev.volume_id, why,
                        )
                        if ev.quarantine_shard(sid, f"scrub .ecc: {why}"):
                            quarantined += 1
                self.on_event()
            state.save()
            if res.aborted:
                break
            if res.complete:
                h.ecc_shard = h.ecc_offset = h.ecc_crc = 0
                h.sweeps += 1
                h.last_sweep_unix = time.time()
                h.sweep_corruptions = h.pass_corruptions
                if h.sweep_corruptions == 0:
                    h.last_error = ""
                    for sid in list(ev.quarantined):
                        ev.quarantined.pop(sid, None)
                        self.store.clear_quarantine(ev.volume_id, sid)
                state.save()
                break
        return found, quarantined, scanned

    # ------------------------------------------------------------------
    def health_rows(self) -> list[VolumeScrubHealth]:
        rows: list[VolumeScrubHealth] = []
        for state in self._states.values():
            with state._lock:
                rows.extend(list(state.volumes.values()))
        return rows

    def status(self) -> dict:
        return {
            "Interval": self.interval,
            "RateMBs": self.rate_mb_s,
            "SweepRunning": self.sweep_running,
            "SweepsCompleted": self.sweeps_completed,
            "LastSweepStarted": self.last_sweep_started,
            "LastSweepFinished": self.last_sweep_finished,
            "Volumes": [h.to_dict() for h in self.health_rows()],
        }
