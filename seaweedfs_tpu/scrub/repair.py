"""RepairScheduler: the master's automatic time-to-repair engine.

The warehouse-cluster study (arxiv 1309.0186) and the Reed-Solomon
repair literature (arxiv 2205.11015) agree on the operational point:
erasure-coded durability is dominated by how fast and how carefully
damage is repaired, not by the code itself. This scheduler closes the
loop the scrub plane opens — it watches the leader's topology (shard
registry, replica layouts, per-node ScrubStat rows) and turns damage
into repair RPCs with production guardrails:

  * detection grace — a volume must stay damaged for `grace` seconds
    before repair starts, so transient states (an ec.balance move, a
    node restart mid-heartbeat) don't trigger spurious rebuilds;
  * global concurrency cap — repair traffic is cluster read traffic
    (a 10-of-14 rebuild streams ~10x the lost bytes); the cap bounds
    how much of the cluster's bandwidth repair may take;
  * per-volume exponential backoff — a repair that keeps failing
    (unreachable holders, full disks) retries at 2^n spacing instead
    of hammering;
  * post-success cool-down — the repaired state needs a heartbeat
    round-trip to reach the topology; the cool-down stops the next
    scan from double-repairing in that window.

Repair verbs reuse the shell's drivers verbatim (do_ec_rebuild's
rack-gather streaming rebuild, plan_fix_replication + VolumeCopy), so
automatic and operator-driven repair exercise the same code path.
"""

from __future__ import annotations

import io
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import grpc

from seaweedfs_tpu.ec.locate import DATA_SHARDS, TOTAL_SHARDS
from seaweedfs_tpu.pb import rpc, volume_pb2
from seaweedfs_tpu.scrub.arbiter import get_arbiter
from seaweedfs_tpu.util import wlog


@dataclass
class RepairTask:
    kind: str  # ec_rebuild | replicate | replace | drain_move | drain_ec
    volume_id: int
    collection: str = ""
    detail: str = ""
    bad_node: str = ""  # replace: the node holding the corrupt copy
    first_detected: float = 0.0
    attempts: int = 0
    next_try: float = 0.0
    in_flight: bool = False
    cooling_until: float = 0.0
    last_error: str = ""

    def to_dict(self) -> dict:
        return {
            "Kind": self.kind,
            "VolumeId": self.volume_id,
            "Collection": self.collection,
            "Detail": self.detail,
            "BadNode": self.bad_node,
            "FirstDetected": self.first_detected,
            "Attempts": self.attempts,
            "NextTry": self.next_try,
            "InFlight": self.in_flight,
            "CoolingUntil": self.cooling_until,
            "LastError": self.last_error,
        }


@dataclass
class RepairScheduler:
    master: object  # MasterServer (topology, is_leader, host, port)
    interval: float = 10.0
    concurrency: int = 2
    grace: float = 30.0
    backoff_base: float = 15.0
    backoff_max: float = 900.0
    cooldown: float = 60.0
    # replace repairs cool down much longer: the "damage" signal is the
    # bad node's scrub row, which only goes clean after a FULL sweep of
    # the fresh copy completes (we trigger one, but it can take minutes
    # at the rate cap) — a 60 s cool-down would delete+recopy a healthy
    # volume every cycle until then
    replace_cooldown: float = 900.0
    # whole-attempt budget (deadline plane, docs/CHAOS.md): one repair
    # attempt — all its verbs and remote gathers together — may not
    # outlive this; a partitioned peer then costs one bounded failed
    # attempt + backoff, not a parked concurrency slot
    repair_deadline_s: float = 900.0
    tasks: dict = field(default_factory=dict)  # (kind, vid) -> RepairTask
    history: deque = field(default_factory=lambda: deque(maxlen=50))

    def __post_init__(self) -> None:
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._lock = threading.Lock()
        self._active = 0
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def start(self) -> None:
        # check+spawn under one hold: two concurrent start() calls must
        # not both see None and double-spawn the loop (weedlint v4
        # race-check-then-act, PR 19 round)
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="repair-scheduler"
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def trigger(self) -> None:
        self._wake.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            if not getattr(self.master, "is_leader", True):
                continue
            try:
                self.scan_once()
            except Exception:  # noqa: BLE001 - scheduler must survive
                import traceback

                # wlog.warning has no exc_info kwarg — passing it would
                # raise and kill this thread; format explicitly
                wlog.warning(
                    "repair: scan crashed: %s", traceback.format_exc()
                )

    # ------------------------------------------------------------------
    # detection
    def detect(self) -> dict[tuple[str, int], RepairTask]:
        """Damage visible in the topology right now, keyed (kind, vid)."""
        topo = self.master.topology
        found: dict[tuple[str, int], RepairTask] = {}
        # EC volumes missing shards (but still decodable)
        for vid, locs in list(topo.ec_shard_map.items()):
            present = [
                sid
                for sid in range(TOTAL_SHARDS)
                if locs.locations[sid]
            ]
            missing = TOTAL_SHARDS - len(present)
            if 0 < missing and len(present) >= DATA_SHARDS:
                found[("ec_rebuild", vid)] = RepairTask(
                    kind="ec_rebuild",
                    volume_id=vid,
                    collection=locs.collection,
                    detail=f"{missing} shard(s) unregistered",
                )
        # plain volumes below their replica placement
        from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement

        holders: dict[int, list] = {}
        info: dict[int, object] = {}
        for dn in topo.data_nodes():
            for vid, v in list(dn.volumes.items()):
                holders.setdefault(vid, []).append(dn)
                info[vid] = v
        for vid, nodes in holders.items():
            if vid in topo.ec_shard_map:
                # the EC plane owns this vid (mid- or post-ec.encode):
                # re-replicating the plain copy would race the encode
                # pipeline's readonly→delete cutover and resurrect a
                # zombie plain volume that shadows the EC shards
                continue
            v = info[vid]
            want = ReplicaPlacement.from_byte(v.replica_placement).copy_count
            if 0 < len(nodes) < want:
                found[("replicate", vid)] = RepairTask(
                    kind="replicate",
                    volume_id=vid,
                    collection=v.collection,
                    detail=f"{len(nodes)}/{want} replicas",
                )
        # scrub-reported corrupt replicas, replaceable from a clean peer
        for dn in topo.data_nodes():
            for s in list(getattr(dn, "scrub_stats", {}).values()):
                if s.is_ec or s.corruptions_found <= 0:
                    continue
                vid = s.volume_id
                if vid in topo.ec_shard_map:
                    continue  # EC plane owns this vid (see above)
                nodes = holders.get(vid, [])
                if len(nodes) < 2 or dn not in nodes:
                    continue  # sole copy: nothing to replace from
                # the copy source must have a VERIFIED-clean sweep of
                # this volume, not merely no corruption report: a
                # never-swept (or scrub-disabled) peer could be corrupt
                # in different needles, and replace DELETES the flagged
                # copy — possibly the only good bytes of those needles
                verified = self._verified_clean_holders(vid)
                clean = [
                    n for n in nodes if n is not dn and n.url in verified
                ]
                if not clean:
                    continue
                found[("replace", vid)] = RepairTask(
                    kind="replace",
                    volume_id=vid,
                    collection=info[vid].collection,
                    bad_node=dn.url,
                    detail=(
                        f"{s.corruptions_found} corrupt needle(s) on "
                        f"{dn.url}; clean copy on {clean[0].url}"
                    ),
                )
        # weedguard drain (docs/HEALTH.md): nodes marked draining (the
        # node.drain shell command, or a SIGTERM self-drain) get their
        # data moved off before decommission — one task per volume /
        # per EC vid held, executed under the same concurrency cap and
        # backoff as damage repair
        health = getattr(self.master, "health", None)
        draining = health.draining_urls() if health is not None else set()
        for dn in topo.data_nodes():
            if dn.url not in draining:
                continue
            for vid, v in list(dn.volumes.items()):
                if vid in topo.ec_shard_map:
                    continue  # the EC registry owns this vid
                found[("drain_move", vid)] = RepairTask(
                    kind="drain_move",
                    volume_id=vid,
                    collection=v.collection,
                    bad_node=dn.url,
                    detail=f"drain {dn.url}",
                )
            for vid, s in list(dn.ec_shards.items()):
                found[("drain_ec", vid)] = RepairTask(
                    kind="drain_ec",
                    volume_id=vid,
                    collection=s.collection,
                    bad_node=dn.url,
                    detail=f"drain {dn.url}",
                )
        return found

    # ------------------------------------------------------------------
    def scan_once(self) -> None:
        now = time.time()
        current = self.detect()
        launch: list[RepairTask] = []
        with self._lock:
            # drop tracked damage that healed (heartbeats caught up or
            # an operator fixed it) once its cool-down lapsed
            for key in list(self.tasks):
                task = self.tasks[key]
                if task.in_flight:
                    continue
                if key not in current and now >= task.cooling_until:
                    del self.tasks[key]
            for key, fresh in current.items():
                task = self.tasks.get(key)
                if task is None:
                    fresh.first_detected = now
                    # drain tasks carry explicit operator intent: no
                    # detection grace (the grace guards against
                    # transient damage states, which a drain is not)
                    grace = 0.0 if fresh.kind.startswith("drain") else self.grace
                    fresh.next_try = now + grace
                    self.tasks[key] = task = fresh
                else:
                    task.detail = fresh.detail
                    task.bad_node = fresh.bad_node or task.bad_node
                if task.in_flight or now < task.next_try:
                    continue
                if now < task.cooling_until:
                    continue
                if self._active + len(launch) >= self.concurrency:
                    continue
                task.in_flight = True
                launch.append(task)
            self._active += len(launch)
        # concurrent ec_rebuild tasks ride ONE batched repair: a node
        # loss surfaces many small EC volumes with identical damage in
        # the same scan, and the batch verb amortizes one mesh decode
        # program across them (ec_files.rebuild_ec_files_batch) instead
        # of paying per-volume dispatch latency N times
        ec_batch = [t for t in launch if t.kind == "ec_rebuild"]
        if len(ec_batch) >= 2:
            launch = [t for t in launch if t.kind != "ec_rebuild"]
            threading.Thread(
                target=self._run_ec_batch,
                args=(ec_batch,),
                daemon=True,
                name=f"repair-ec_rebuild-batch-{len(ec_batch)}",
            ).start()
        for task in launch:
            threading.Thread(
                target=self._run_task,
                args=(task,),
                daemon=True,
                name=f"repair-{task.kind}-{task.volume_id}",
            ).start()

    # ------------------------------------------------------------------
    def _run_task(self, task: RepairTask) -> None:
        from seaweedfs_tpu.stats.metrics import (
            REPAIR_FAILED,
            REPAIR_STARTED,
            REPAIR_SUCCEEDED,
            TIME_TO_REPAIR,
        )

        REPAIR_STARTED.labels(task.kind).inc()
        t0 = time.time()
        try:
            # tracing plane: the whole repair is one plane=repair span;
            # every hop it drives (rebuild verbs, copies, EC shard
            # reads) inherits the tag via gRPC metadata, so rebuild
            # traffic competing with serving traffic is attributable
            from seaweedfs_tpu import trace
            from seaweedfs_tpu.util import deadline as _deadline

            # deadline plane (docs/CHAOS.md): every repair attempt runs
            # under one whole-repair budget. The ambient deadline rides
            # the gRPC Stub auto-derivation onto every verb the repair
            # drives (rebuild, copies, remote EC shard gathers on the
            # target node's pool threads) — so a PARTITIONED survivor
            # fails this attempt within the budget and the scheduler's
            # exponential backoff takes over, instead of one parked
            # gather pinning a concurrency slot for the full per-verb
            # timeout stack.
            with trace.span(f"repair.{task.kind}", plane="repair") as sp, \
                    _deadline.scope(
                        _deadline.Deadline.after(self.repair_deadline_s)
                    ):
                if sp:
                    sp.annotate("vid", task.volume_id)
                if task.kind == "ec_rebuild":
                    self._repair_ec(task)
                elif task.kind == "replicate":
                    self._repair_replicate(task)
                elif task.kind == "replace":
                    self._repair_replace(task)
                elif task.kind == "drain_move":
                    self._repair_drain_move(task)
                elif task.kind == "drain_ec":
                    self._repair_drain_ec(task)
                else:
                    raise ValueError(f"unknown repair kind {task.kind}")
        except Exception as e:  # noqa: BLE001 - becomes backoff state
            REPAIR_FAILED.labels(task.kind).inc()
            with self._lock:
                task.in_flight = False
                task.attempts += 1
                task.last_error = str(e)[:300]
                task.next_try = time.time() + min(
                    self.backoff_base * (2 ** (task.attempts - 1)),
                    self.backoff_max,
                )
                self._active -= 1
            wlog.warning(
                "repair: %s vid %d attempt %d failed: %s",
                task.kind, task.volume_id, task.attempts, e,
            )
            return
        took = time.time() - t0
        ttr = time.time() - task.first_detected
        REPAIR_SUCCEEDED.labels(task.kind).inc()
        TIME_TO_REPAIR.observe(ttr, task.kind)
        with self._lock:
            task.in_flight = False
            task.last_error = ""
            # the topology needs a heartbeat round-trip to reflect the
            # repair; cool down so the next scan can't double-repair
            # (replace waits out a full scrub pass — see replace_cooldown)
            task.cooling_until = time.time() + (
                self.replace_cooldown
                if task.kind == "replace"
                else self.cooldown
            )
            task.next_try = task.cooling_until
            self._active -= 1
            self.history.append(
                {
                    "Kind": task.kind,
                    "VolumeId": task.volume_id,
                    "Detail": task.detail,
                    "FinishedUnix": time.time(),
                    "RepairSeconds": round(took, 3),
                    "TimeToRepairSeconds": round(ttr, 3),
                    "Attempts": task.attempts + 1,
                }
            )
        wlog.warning(
            "repair: %s vid %d done in %.1fs (time-to-repair %.1fs)",
            task.kind, task.volume_id, took, ttr,
        )

    # ------------------------------------------------------------------
    def _run_ec_batch(self, tasks: list["RepairTask"]) -> None:
        """One batched ec_rebuild repair for N concurrent tasks — the
        shell's do_ec_rebuild_batch groups same-node local-survivor
        volumes through the BatchRebuild verb and falls back to the
        single-volume flow for the rest, so per-task semantics (and
        the scheduler's backoff on failure) are unchanged; only the
        dispatch is amortized. Whole-batch failure backs off every
        task: the next scan retries them (batched again if still
        concurrent)."""
        from seaweedfs_tpu.stats.metrics import (
            REPAIR_FAILED,
            REPAIR_STARTED,
            REPAIR_SUCCEEDED,
            TIME_TO_REPAIR,
        )

        for task in tasks:
            REPAIR_STARTED.labels(task.kind).inc()
        t0 = time.time()
        try:
            from seaweedfs_tpu import trace
            from seaweedfs_tpu.shell.commands import do_ec_rebuild_batch
            from seaweedfs_tpu.util import deadline as _deadline

            # one whole-batch budget sized like the serial sum: N
            # volumes under N x the per-repair deadline (the batch is
            # strictly faster than serial, so this only loosens)
            with trace.span("repair.ec_rebuild_batch", plane="repair") as sp, \
                    _deadline.scope(
                        _deadline.Deadline.after(
                            self.repair_deadline_s * len(tasks)
                        )
                    ):
                if sp:
                    sp.annotate("vids", [t.volume_id for t in tasks])
                do_ec_rebuild_batch(
                    self._env(),
                    [t.volume_id for t in tasks],
                    io.StringIO(),
                    apply=True,
                )
        except Exception as e:  # noqa: BLE001 - becomes backoff state
            now = time.time()
            with self._lock:
                for task in tasks:
                    REPAIR_FAILED.labels(task.kind).inc()
                    task.in_flight = False
                    task.attempts += 1
                    task.last_error = str(e)[:300]
                    task.next_try = now + min(
                        self.backoff_base * (2 ** (task.attempts - 1)),
                        self.backoff_max,
                    )
                    self._active -= 1
            wlog.warning(
                "repair: batched ec_rebuild of vids %s failed: %s",
                [t.volume_id for t in tasks], e,
            )
            return
        took = time.time() - t0
        now = time.time()
        with self._lock:
            for task in tasks:
                ttr = now - task.first_detected
                REPAIR_SUCCEEDED.labels(task.kind).inc()
                TIME_TO_REPAIR.observe(ttr, task.kind)
                task.in_flight = False
                task.last_error = ""
                task.cooling_until = now + self.cooldown
                task.next_try = task.cooling_until
                self._active -= 1
                self.history.append(
                    {
                        "Kind": task.kind,
                        "VolumeId": task.volume_id,
                        "Detail": task.detail + " (batched)",
                        "FinishedUnix": now,
                        "RepairSeconds": round(took, 3),
                        "TimeToRepairSeconds": round(ttr, 3),
                        "Attempts": task.attempts + 1,
                    }
                )
        wlog.warning(
            "repair: batched ec_rebuild of %d volume(s) %s done in %.1fs",
            len(tasks), [t.volume_id for t in tasks], took,
        )

    # ------------------------------------------------------------------
    # repair verbs (shell drivers reused — one code path for auto and
    # operator repair)
    def _env(self):
        from seaweedfs_tpu.shell.command_env import CommandEnv

        return CommandEnv([f"{self.master.host}:{self.master.port}"])

    def _corrupt_holders(self, vid: int) -> set[str]:
        """Nodes whose scrub rows currently flag this plain volume —
        NEVER a copy source: replicating from a corrupt replica would
        propagate the rot cluster-wide with no operator in the loop."""
        urls: set[str] = set()
        for dn in self.master.topology.data_nodes():
            for s in list(getattr(dn, "scrub_stats", {}).values()):
                if (
                    not s.is_ec
                    and s.volume_id == vid
                    and s.corruptions_found > 0
                ):
                    urls.add(dn.url)
        return urls

    def _verified_clean_holders(self, vid: int) -> set[str]:
        """Nodes whose scrub COMPLETED a clean pass over this plain
        volume (the bar for being a replace-repair source)."""
        urls: set[str] = set()
        for dn in self.master.topology.data_nodes():
            s = getattr(dn, "scrub_stats", {}).get((vid, False))
            if (
                s is not None
                and s.last_sweep_unix > 0
                and s.corruptions_found == 0
            ):
                urls.add(dn.url)
        return urls

    def _repair_ec(self, task: RepairTask) -> None:
        from seaweedfs_tpu.shell.commands import do_ec_rebuild

        do_ec_rebuild(self._env(), task.volume_id, io.StringIO(), apply=True)

    def _timed_copy(self, vid: int, collection: str, src: str, dst: str) -> None:
        """VolumeCopy with a deadline: the shell's _copy_volume carries
        no timeout, and a wedged destination node would otherwise pin
        this repair thread (and its concurrency slot) forever."""
        host, _, port = dst.partition(":")
        with rpc.dial(f"{host}:{int(port) + 10000}") as ch:
            rpc.volume_stub(ch).VolumeCopy(
                volume_pb2.VolumeCopyRequest(
                    volume_id=vid,
                    collection=collection,
                    source_data_node=src,
                ),
                timeout=600,
            )

    def _repair_replicate(self, task: RepairTask) -> None:
        from seaweedfs_tpu.shell.commands import plan_fix_replication

        env = self._env()
        plans = [
            p
            for p in plan_fix_replication(env.collect_topology())
            if p["vid"] == task.volume_id
        ]
        if not plans:
            # healed between detect and launch — that's success
            return
        corrupt = self._corrupt_holders(task.volume_id)
        clean_sources = [
            dn.url
            for dn in self.master.topology.data_nodes()
            if task.volume_id in dn.volumes and dn.url not in corrupt
        ]
        for p in plans:
            src = p["from"]
            if src in corrupt:
                if not clean_sources:
                    raise RuntimeError(
                        f"vid {task.volume_id}: every replica is "
                        f"scrub-flagged corrupt; refusing to replicate "
                        f"from a corrupt source"
                    )
                src = clean_sources[0]
            self._timed_copy(p["vid"], p["collection"], src, p["to"])

    def _repair_replace(self, task: RepairTask) -> None:
        """Drop the scrub-flagged corrupt replica, then re-copy from a
        clean one onto the same node (a fresh byte-identical copy)."""
        topo = self.master.topology
        nodes = [
            dn
            for dn in topo.data_nodes()
            if task.volume_id in dn.volumes
        ]
        bad = next((n for n in nodes if n.url == task.bad_node), None)
        verified = self._verified_clean_holders(task.volume_id)
        sources = [
            n
            for n in nodes
            if n.url != task.bad_node and n.url in verified
        ]
        if bad is None or not sources:
            raise RuntimeError(
                f"replace vid {task.volume_id}: bad/clean holder set "
                f"changed under the scheduler"
            )
        with rpc.dial(f"{bad.ip}:{bad.port + 10000}") as ch:
            rpc.volume_stub(ch).VolumeDelete(
                volume_pb2.VolumeDeleteRequest(volume_id=task.volume_id),
                timeout=60,
            )
        # unregister immediately: the copy below re-registers via the
        # target's heartbeat; waiting for the bad node's beat here
        # would race the VolumeCopy ALREADY_EXISTS check
        bad.volumes.pop(task.volume_id, None)
        with rpc.dial(f"{bad.ip}:{bad.port + 10000}") as ch:
            try:
                rpc.volume_stub(ch).VolumeCopy(
                    volume_pb2.VolumeCopyRequest(
                        volume_id=task.volume_id,
                        collection=task.collection,
                        source_data_node=sources[0].url,
                    ),
                    timeout=600,
                )
            except grpc.RpcError as e:
                raise RuntimeError(
                    f"re-copy after delete failed: {e.code().name}; "
                    f"volume now under-replicated (replicate task will "
                    f"retry)"
                ) from e
        # ask the (ex-)bad node to re-sweep the fresh copy promptly:
        # its next clean pass zeroes the corruption row that flagged
        # this task, closing the loop without waiting a full scrub
        # interval
        import urllib.request

        try:
            # weedlint: ignore[no-deadline] — leader-side best-effort nudge with a 5 s cap; no request deadline exists on the scheduler thread
            urllib.request.urlopen(
                f"http://{task.bad_node}/scrub/trigger"
                f"?volumeId={task.volume_id}",
                timeout=5,
            ).close()
        except OSError:
            pass  # scrub disabled there: the row ages out on its own

    # ------------------------------------------------------------------
    # drain moves (weedguard, docs/HEALTH.md): empty a draining node
    def _drain_targets(self, src_url: str, vid: int | None = None) -> list:
        """Eligible destinations for data leaving a draining node:
        registered, not draining, assignable per the health plane, with
        free slots, and (for plain volumes) not already a holder of the
        vid. Fullest-free first so drains spread wide."""
        health = getattr(self.master, "health", None)
        draining = health.draining_urls() if health is not None else set()
        out = []
        for dn in self.master.topology.data_nodes():
            if dn.url == src_url or dn.url in draining:
                continue
            if health is not None and not health.assignable(dn.url):
                continue
            if vid is not None and vid in dn.volumes:
                continue
            if dn.free_space() <= 0:
                continue
            out.append(dn)
        out.sort(key=lambda d: -d.free_space())
        return out

    def _repair_drain_move(self, task: RepairTask) -> None:
        """Move one plain volume off the draining node: readonly guard
        → copy → delete (the shell's volume.move driver, so operator
        and automatic moves share one code path)."""
        from seaweedfs_tpu.shell.commands import _move_volume

        topo = self.master.topology
        src = next(
            (d for d in topo.data_nodes() if d.url == task.bad_node), None
        )
        if src is None or task.volume_id not in src.volumes:
            return  # already gone — that's success
        targets = self._drain_targets(task.bad_node, vid=task.volume_id)
        if not targets:
            # surplus replica: when enough OTHER holders already
            # satisfy the placement, dropping the draining copy IS the
            # complete move (no fresh node required). Below placement,
            # the drain is genuinely blocked on capacity — error into
            # backoff so the repair queue (and node.drain's timeout
            # report) names it.
            from seaweedfs_tpu.storage.replica_placement import (
                ReplicaPlacement,
            )

            v = src.volumes[task.volume_id]
            others = [
                d
                for d in topo.data_nodes()
                if d is not src and task.volume_id in d.volumes
            ]
            want = ReplicaPlacement.from_byte(
                v.replica_placement
            ).copy_count
            if len(others) < want:
                raise RuntimeError(
                    f"drain {task.bad_node}: no eligible target for "
                    f"vid {task.volume_id} and only {len(others)}/{want} "
                    f"other replica(s) — add capacity to proceed"
                )
            with rpc.dial(
                f"{src.ip}:{src.port + 10000}"
            ) as ch:
                rpc.volume_stub(ch).VolumeDelete(
                    volume_pb2.VolumeDeleteRequest(
                        volume_id=task.volume_id
                    ),
                    timeout=60,
                )
        else:
            _move_volume(
                self._env(), task.volume_id, task.collection,
                task.bad_node, targets[0].url,
            )
        # unregister immediately — node AND layout (the target's forced
        # delta beat re-registers the moved copy). Popping only
        # dn.volumes would erase the evidence the source's next FULL
        # beat needs to report the delete, leaving a stale layout entry
        # routing reads at the drained node forever (full-suite race).
        v = src.volumes.pop(task.volume_id, None)
        if v is not None:
            self.master.topology._layout_for(v).unregister_volume(
                v.id, src
            )

    def _repair_drain_ec(self, task: RepairTask) -> None:
        """Move every EC shard of one vid off the draining node:
        copy+mount on a target, then unmount+delete on the source (the
        shell ec_common verbs, shard by shard so a failure mid-vid
        leaves each shard wholly on exactly one node)."""
        from seaweedfs_tpu.shell import ec_common

        env = self._env()
        topo = self.master.topology
        src = next(
            (d for d in topo.data_nodes() if d.url == task.bad_node), None
        )
        if src is None:
            return
        info = src.ec_shards.get(task.volume_id)
        if info is None:
            return  # already gone
        sids = ec_common.shard_bits_to_ids(info.ec_index_bits)
        targets = self._drain_targets(task.bad_node)
        if not targets:
            raise RuntimeError(
                f"drain {task.bad_node}: no eligible target for ec "
                f"vid {task.volume_id}"
            )
        from types import SimpleNamespace

        for i, sid in enumerate(sids):
            # ec_common helpers address targets by .url only
            dst = SimpleNamespace(url=targets[i % len(targets)].url)
            ec_common.copy_and_mount_shards(
                env, dst, task.volume_id, task.collection, [sid],
                task.bad_node,
            )
            ec_common.unmount_and_delete_shards(
                env, task.bad_node, task.volume_id, task.collection, [sid]
            )
        src.ec_shards.pop(task.volume_id, None)
        topo.unregister_ec_shards(task.volume_id, src)

    # ------------------------------------------------------------------
    def queue_snapshot(self) -> dict:
        with self._lock:
            return {
                "Config": {
                    "Interval": self.interval,
                    "Concurrency": self.concurrency,
                    "GraceSeconds": self.grace,
                    "BackoffBaseSeconds": self.backoff_base,
                    "BackoffMaxSeconds": self.backoff_max,
                    "CooldownSeconds": self.cooldown,
                },
                "Active": self._active,
                "Tasks": [t.to_dict() for t in self.tasks.values()],
                "History": list(self.history),
                # bandwidth arbiter view: what the background planes
                # (rebuild/replication/handoff/tier) are being paced at
                # right now (docs/TIERING.md)
                "Arbiter": get_arbiter().stats(),
            }
