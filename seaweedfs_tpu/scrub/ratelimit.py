"""Token bucket: the scrub plane's foreground-p99 guardrail.

Every byte the scrubber reads (local pread or remote shard fetch) is
charged here BEFORE the read happens, so a sweep can never burst past
its configured bandwidth and starve foreground reads of the same
spindle/NIC. rate <= 0 disables limiting (bench mode).
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    def __init__(self, rate_bytes_s: float, burst_bytes: int | None = None):
        self.rate = float(rate_bytes_s)
        # default burst: one second of rate — big enough for a 4 MiB
        # verify tile at any sane rate, small enough that a wake-up
        # after idle can't dump minutes of backlog at once
        self.burst = float(
            burst_bytes if burst_bytes is not None else max(self.rate, 1.0)
        )
        self._tokens = self.burst
        self._last = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self) -> None:
        now = time.monotonic()
        self._tokens = min(
            self.burst, self._tokens + (now - self._last) * self.rate
        )
        self._last = now

    def take(self, n: int, stop: threading.Event | None = None) -> bool:
        """Block until the bucket can admit the request, then charge
        the FULL `n` — the balance may go negative (debt), and later
        takes wait the debt out. This keeps the long-run rate exact
        for requests larger than the burst (clamping the charge would
        silently run a 4 MiB-tile scrub at 4x a 1 MB/s cap); a single
        oversized read still can't deadlock, because the admission
        threshold is min(n, burst). Returns False (without consuming)
        when `stop` fires first."""
        if self.rate <= 0:
            return True
        need = min(float(n), self.burst)
        while True:
            with self._lock:
                self._refill_locked()
                if self._tokens >= need:
                    self._tokens -= float(n)
                    return True
                wait = (need - self._tokens) / self.rate
            # sleep outside the lock; cap so stop stays responsive
            wait = min(wait, 0.5)
            if stop is not None:
                if stop.wait(wait):
                    return False
            else:
                time.sleep(wait)
