"""Persisted scrub state: per-volume cursor + health, one JSON file
per disk location.

Restart-resumability is the point: a 30 GB volume at a 64 MB/s scrub
rate takes ~8 minutes to sweep; a volume server restart mid-sweep must
resume at the cursor, not start over (or worse, never finish under a
restart-heavy deploy cadence). Writes are atomic (tmp + rename) so a
crash can't leave a torn state file.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import asdict, dataclass, field

from seaweedfs_tpu.util import durable


@dataclass
class VolumeScrubHealth:
    """One volume's scrub-plane record (the ScrubStat heartbeat row
    plus the resume cursor, which stays local)."""

    volume_id: int
    is_ec: bool = False
    # plain volumes: last verified needle id; EC volumes: byte offset
    # into the shard the next sweep resumes at
    cursor: int = 0
    last_sweep_unix: float = 0.0
    scanned_bytes: int = 0  # cumulative across sweeps
    corruptions_found: int = 0  # cumulative (metrics/status surface)
    # corruption events as of the most recent COMPLETED sweep pass —
    # this is what heartbeats report, so a repaired volume's next clean
    # pass drops the row to 0 and the master's repair scheduler
    # converges instead of re-repairing on stale history. New finds
    # mid-pass ADD immediately (damage must reach the master now); the
    # value only ever drops when a full pass finishes, so a still-
    # corrupt volume never reads as clean mid-sweep (which would reset
    # the scheduler's backoff state every sweep).
    sweep_corruptions: int = 0
    # finds within the in-progress pass (becomes sweep_corruptions at
    # pass completion); persisted so a restart mid-pass keeps counting
    pass_corruptions: int = 0
    sweeps: int = 0
    last_error: str = ""
    # .ecc sidecar sweep cursor triple (scrub/verify.verify_ecc_stream):
    # shard being read, byte offset within it, and the RUNNING CRC-32C
    # at that offset — persisting the running CRC lets a restart resume
    # mid-shard instead of reverifying from byte 0. Independent of
    # `cursor` (the parity-path offset): a volume can flip between the
    # two paths mid-life when its sidecar appears/goes stale.
    ecc_shard: int = 0
    ecc_offset: int = 0
    ecc_crc: int = 0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeScrubHealth":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class ScrubState:
    path: str
    volumes: dict[tuple[int, bool], VolumeScrubHealth] = field(
        default_factory=dict
    )

    def __post_init__(self) -> None:
        self._lock = threading.Lock()
        self.load()

    def load(self) -> None:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return
        # same lock as get/forget/save: load() is construction-time
        # today, but it is a public method on a table that heartbeat
        # and engine threads read — keep the guard discipline uniform
        with self._lock:
            for d in raw.get("volumes", []):
                try:
                    h = VolumeScrubHealth.from_dict(d)
                except TypeError:
                    continue  # unknown/legacy row: start that volume fresh
                self.volumes[(h.volume_id, h.is_ec)] = h

    def get(self, volume_id: int, is_ec: bool) -> VolumeScrubHealth:
        with self._lock:
            key = (volume_id, is_ec)
            h = self.volumes.get(key)
            if h is None:
                h = self.volumes[key] = VolumeScrubHealth(
                    volume_id=volume_id, is_ec=is_ec
                )
            return h

    def forget(self, volume_id: int, is_ec: bool) -> None:
        with self._lock:
            self.volumes.pop((volume_id, is_ec), None)

    def save(self) -> None:
        with self._lock:
            payload = {
                "volumes": [h.to_dict() for h in self.volumes.values()]
            }
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(payload, f)
            # fsync + rename + dir fsync: the cursor file is the first
            # thing restart recovery reads — a torn or lost publish
            # would restart every in-flight sweep from zero (or worse,
            # parse-fail and reset health history)
            durable.publish(tmp, self.path)
        except OSError:
            # a disk too sick to persist scrub state is a disk the
            # sweep itself will report on; never crash the engine here
            try:
                os.remove(tmp)
            except OSError:
                pass
