"""Scrub & self-healing plane.

Beyond-reference subsystem (the 2019 reference has no background
integrity machinery at all; see docs/SCRUB.md): background integrity
sweeps on every volume server, scrub/quarantine health flowing to the
master over the heartbeat stream, and a master-side repair scheduler
that turns detected damage into VolumeEcShardsRebuild / re-replication
work with a global concurrency cap and per-volume backoff.

  ratelimit  — token bucket bounding scrub disk+network bandwidth
  state      — per-disk-location persisted cursors + health records
  verify     — parity re-verify / reconstruct-compare / needle CRC walk
  engine     — the volume-server background sweeper (ScrubEngine)
  repair     — the master-side repair scheduler (RepairScheduler)
"""

from seaweedfs_tpu.scrub.engine import ScrubEngine
from seaweedfs_tpu.scrub.ratelimit import TokenBucket
from seaweedfs_tpu.scrub.repair import RepairScheduler
from seaweedfs_tpu.scrub.state import ScrubState, VolumeScrubHealth

__all__ = [
    "ScrubEngine",
    "RepairScheduler",
    "ScrubState",
    "TokenBucket",
    "VolumeScrubHealth",
]
