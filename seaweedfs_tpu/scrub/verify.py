"""Scrub verify cores: EC parity re-verify with culprit localization,
and the plain-volume needle CRC walk.

The EC path is the product face of the verify tier that until now only
the `ec.verify` shell command exercised (parallel/mesh_codec
verify_batch_u32 / the SWAR host path feed `rs.encode` through
ec/codec.py's backend selection): stream all 14 shards tile by tile,
recompute the 4 parity rows from the 10 data rows, and compare. A
corrupt DATA shard disagrees with every parity row; a corrupt PARITY
shard only with its own. Localization then pins the culprit shard(s)
by hypothesis testing: reconstruct candidate set S from the other
shards; if every member of S changes AND the repaired tile passes a
full parity check, S is the corrupt set. Singles then pairs — beyond
two simultaneously-corrupt shards in one 4 MiB tile the sweep reports
the tile unlocalized rather than guessing (quarantining a healthy
shard on a guess costs real redundancy).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from itertools import combinations
from typing import Callable, Optional, Sequence

import numpy as np

from seaweedfs_tpu.ec.codec import ReedSolomon, new_encoder
from seaweedfs_tpu.scrub.ratelimit import TokenBucket
from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import CorruptNeedle, get_actual_size
from seaweedfs_tpu.storage.volume import NeedleNotFound

DEFAULT_TILE_BYTES = 4 * 1024 * 1024

# reader(offset, size) -> bytes; short return means EOF
ShardReader = Callable[[int, int], bytes]


@dataclass
class ParityScanResult:
    # per-parity-row mismatched byte counts (the ec.verify contract)
    mismatch: list[int]
    bytes_per_shard: int = 0  # verified by THIS call
    bad_tiles: list[tuple[int, int]] = field(default_factory=list)
    # sid -> number of bad tiles localized to it
    culprits: dict[int, int] = field(default_factory=dict)
    unlocalized: int = 0  # bad tiles no 1- or 2-shard hypothesis explains
    end_offset: int = 0
    complete: bool = False  # swept through shard EOF
    aborted: bool = False  # stop event fired mid-scan

    @property
    def corrupt(self) -> bool:
        return any(self.mismatch)


def verify_parity_stream(
    readers: Sequence[ShardReader],
    *,
    rs: ReedSolomon | None = None,
    start: int = 0,
    tile_bytes: int = DEFAULT_TILE_BYTES,
    limiter: TokenBucket | None = None,
    stop: threading.Event | None = None,
    max_bytes: int | None = None,
    localize: bool = True,
) -> ParityScanResult:
    """Stream every shard from `start`, recompute + compare parity per
    tile. `max_bytes` bounds the PER-SHARD bytes verified this call
    (the engine's incremental-sweep budget); the cursor to resume from
    is `end_offset`."""
    rs = rs or new_encoder()
    k, total = rs.data_shards, rs.total_shards
    if len(readers) != total:
        raise ValueError(f"expected {total} shard readers, got {len(readers)}")
    res = ParityScanResult(mismatch=[0] * rs.parity_shards, end_offset=start)
    offset = start
    while True:
        if stop is not None and stop.is_set():
            res.aborted = True
            break
        if max_bytes is not None and res.bytes_per_shard >= max_bytes:
            break
        # charge per SHARD read, not per 14-shard tile: one tile's
        # worth (tile_bytes x 14 = 56 MiB at the default tile) would
        # dwarf any sane burst and turn the pacing into start-of-sweep
        # storms — exactly the foreground-p99 spikes the bucket exists
        # to prevent. Charged AFTER the read, for the bytes actually
        # returned: the debt model keeps the long-run rate exact while
        # short final tiles and the zero-byte EOF probe cost nothing
        # (pre-charging the nominal tile wastes ~1 s of budget per
        # volume per sweep on exactly-tile-aligned shards).
        tiles = []
        for sid in range(total):
            if limiter is not None and stop is not None and stop.is_set():
                res.aborted = True
                return res
            data = readers[sid](offset, tile_bytes)
            if limiter is not None and not limiter.take(len(data), stop):
                res.aborted = True
                return res
            tiles.append(data)
        n = len(tiles[0])
        if any(len(tile) != n for tile in tiles):
            lens = [len(tile) for tile in tiles]
            raise RuntimeError(f"shard length skew at {offset}: {lens}")
        if n == 0:
            res.complete = True
            break
        shards: list[Optional[np.ndarray]] = [
            np.frombuffer(tiles[i], dtype=np.uint8).copy() for i in range(k)
        ] + [None] * rs.parity_shards
        rs.encode(shards)
        tile_bad = False
        for p in range(rs.parity_shards):
            given = np.frombuffer(tiles[k + p], dtype=np.uint8)
            bad = int(np.count_nonzero(shards[k + p] != given))
            if bad:
                tile_bad = True
                res.mismatch[p] += bad
        if tile_bad:
            res.bad_tiles.append((offset, n))
            if localize:
                culprits = localize_corrupt_shards(tiles, rs)
                if culprits is None:
                    res.unlocalized += 1
                else:
                    for sid in culprits:
                        res.culprits[sid] = res.culprits.get(sid, 0) + 1
        res.bytes_per_shard += n
        offset += n
        res.end_offset = offset
        if n < tile_bytes:
            res.complete = True
            break
    return res


def localize_corrupt_shards(
    tiles: Sequence[bytes], rs: ReedSolomon | None = None
) -> list[int] | None:
    """Which shard(s) hold the wrong bytes for this tile? Hypothesis
    test over 1- then 2-shard candidate sets; None when unexplained."""
    rs = rs or new_encoder()
    k, total = rs.data_shards, rs.total_shards
    arrays = [np.frombuffer(tile, dtype=np.uint8) for tile in tiles]

    def reconstructed(targets: tuple[int, ...]) -> dict[int, np.ndarray] | None:
        shards: list[Optional[np.ndarray]] = [
            None if i in targets else arrays[i].copy() for i in range(total)
        ]
        try:
            rs.reconstruct(shards)
        except Exception:  # noqa: BLE001 - not enough clean survivors
            return None
        return {i: shards[i] for i in targets}  # type: ignore[misc]

    def parity_clean(repl: dict[int, np.ndarray]) -> bool:
        shards: list[Optional[np.ndarray]] = [
            repl.get(i, arrays[i]).copy() for i in range(k)
        ] + [None] * rs.parity_shards
        rs.encode(shards)
        for p in range(rs.parity_shards):
            want = repl.get(k + p, arrays[k + p])
            if not np.array_equal(shards[k + p], want):
                return False
        return True

    for r in (1, 2):
        for combo in combinations(range(total), r):
            repl = reconstructed(combo)
            if repl is None:
                continue
            # every member of the hypothesis must actually change —
            # else a smaller set explains it (and was already tried)
            if any(np.array_equal(repl[i], arrays[i]) for i in combo):
                continue
            if parity_clean(repl):
                return list(combo)
    return None


# ---------------------------------------------------------------------------
# EC volumes with a fresh .ecc sidecar: plain read+CRC pass per shard


@dataclass
class EccScanResult:
    """Resume state for the sidecar-CRC sweep: (shard_idx, offset,
    run_crc) is the cursor triple — the engine persists it so a
    restart mid-shard keeps the running CRC instead of rereading."""

    bytes_scanned: int = 0  # total bytes read by THIS call
    bad_shards: dict[int, str] = field(default_factory=dict)
    shard_idx: int = 0
    offset: int = 0
    run_crc: int = 0
    complete: bool = False
    aborted: bool = False

    @property
    def corrupt(self) -> bool:
        return bool(self.bad_shards)


def verify_ecc_stream(
    shard_paths: dict[int, str],
    doc: dict,
    *,
    start_shard: int = 0,
    start_offset: int = 0,
    run_crc: int = 0,
    tile_bytes: int = DEFAULT_TILE_BYTES,
    limiter: TokenBucket | None = None,
    stop: threading.Event | None = None,
    max_bytes: int | None = None,
) -> EccScanResult:
    """Verify shard files against their `.ecc`-attested whole-file
    CRC-32C + size (ec/ecc_sidecar.py): a sequential read + running
    CRC per shard, no GF math — the cheap arm of the EC scrub.

    Same pacing contract as verify_parity_stream: the limiter is
    charged AFTER each read for the bytes actually returned (debt
    model), `max_bytes` bounds the TOTAL bytes this call reads (the
    engine's segment budget), and the cursor triple to resume from is
    (shard_idx, offset, run_crc). Unlike the parity sweep this pins
    the culprit directly: a CRC or size mismatch names its shard."""
    from seaweedfs_tpu.util.crc import crc32c

    res = EccScanResult(
        shard_idx=start_shard, offset=start_offset, run_crc=run_crc
    )
    # one reused read buffer: at several GB/s the per-tile bytes
    # allocation of a plain f.read is a measurable fraction of the pass
    buf = memoryview(bytearray(tile_bytes))
    sids = sorted(shard_paths)
    # resume position may name a shard that was quarantined since
    idx = next((i for i, s in enumerate(sids) if s >= start_shard), len(sids))
    if idx < len(sids) and sids[idx] != start_shard:
        res.offset, res.run_crc = 0, 0
    while idx < len(sids):
        sid = sids[idx]
        res.shard_idx = sid
        ent = doc["shards"].get(str(sid))
        if ent is None:
            # callers gate on sidecar_status == ok, but the sidecar can
            # be republished under us; treat as corrupt-signal for the
            # caller to fall back on
            res.bad_shards[sid] = "no sidecar entry"
            idx += 1
            res.offset, res.run_crc = 0, 0
            continue
        try:
            # buffering=0: raw FileIO reads straight into the reused
            # buffer, skipping the BufferedReader copy layer
            with open(shard_paths[sid], "rb", buffering=0) as f:
                if res.offset:
                    f.seek(res.offset)
                while True:
                    if stop is not None and stop.is_set():
                        res.aborted = True
                        return res
                    if max_bytes is not None and res.bytes_scanned >= max_bytes:
                        return res
                    got = f.readinto(buf)
                    if limiter is not None and not limiter.take(got, stop):
                        res.aborted = True
                        return res
                    if not got:
                        break
                    res.run_crc = crc32c(buf[:got], res.run_crc)
                    res.offset += got
                    res.bytes_scanned += got
        except OSError as e:
            res.bad_shards[sid] = f"read failed: {e!r}"
            idx += 1
            res.offset, res.run_crc = 0, 0
            continue
        if res.offset != ent.get("size"):
            res.bad_shards[sid] = (
                f"size {res.offset} != attested {ent.get('size')}"
            )
        elif res.run_crc != ent.get("crc"):
            res.bad_shards[sid] = (
                f"crc {res.run_crc:#010x} != attested {ent.get('crc'):#010x}"
            )
        idx += 1
        res.offset, res.run_crc = 0, 0
        res.shard_idx = sids[idx] if idx < len(sids) else sids[-1] + 1
    res.complete = True
    return res


# ---------------------------------------------------------------------------
# plain volumes: re-read every live needle through the CRC check


@dataclass
class PlainScanResult:
    scanned_bytes: int = 0
    corruptions: list[tuple[int, str]] = field(default_factory=list)
    last_key: int = 0
    consumed: int = 0  # entries of `keys` iterated (callers slice)
    complete: bool = False
    aborted: bool = False


def live_needle_keys(volume, after_key: int = 0) -> list[int]:
    """Sorted live needle ids > after_key — the sweep's work list.
    Split out so segmented callers enumerate/sort the map ONCE per
    volume pass instead of once per 64 MiB segment (O(segments x
    needles) of GIL-burning overhead on a big volume otherwise).

    Enumerates under the volume's write lock: nm.items() is a lazy
    generator over the live dict, and a concurrent foreground write
    mutating the map mid-iteration would raise RuntimeError and abort
    the whole sweep. Writers hold the same lock (write_needle), so one
    brief exclusion here is the correct snapshot."""
    with volume._lock:
        return sorted(
            nv.key
            for nv in volume.nm.items()
            if nv.key > after_key
            and nv.offset != 0
            and nv.size != t.TOMBSTONE_FILE_SIZE
        )


def scan_plain_volume(
    volume,
    *,
    after_key: int = 0,
    keys: list[int] | None = None,
    limiter: TokenBucket | None = None,
    stop: threading.Event | None = None,
    max_bytes: int | None = None,
) -> PlainScanResult:
    """Re-read every live needle with id > after_key through the full
    parse + CRC32-C check (Needle.from_bytes raises CorruptNeedle on a
    flipped byte). Walks the NEEDLE MAP, not the raw .dat: the map is
    exactly the reachable set — overwritten generations and tombstones
    are dead bytes whose rot cannot hurt a read, and a framing walk of
    a corrupt .dat would desync and drown the report in false hits.

    `keys` (from live_needle_keys) lets a segmented caller reuse one
    enumeration across segments; result.consumed says how many entries
    this call got through, so the caller can slice."""
    from seaweedfs_tpu.storage.volume import CookieMismatch

    res = PlainScanResult(last_key=after_key)
    live = keys if keys is not None else live_needle_keys(volume, after_key)
    res.complete = True
    for key in live:
        if stop is not None and stop.is_set():
            res.aborted = True
            res.complete = False
            break
        nv = volume.nm.get(key)
        if nv is None or nv.offset == 0 or nv.size == t.TOMBSTONE_FILE_SIZE:
            res.consumed += 1
            res.last_key = key
            continue  # deleted since the snapshot
        record = get_actual_size(nv.size, volume.version)
        # budget check only after progress: a single record larger than
        # the whole budget must still scan (else the caller's
        # segment loop would spin forever at zero progress)
        if (
            max_bytes is not None
            and res.scanned_bytes
            and res.scanned_bytes + record > max_bytes
        ):
            res.complete = False
            break
        if limiter is not None and not limiter.take(record, stop):
            res.aborted = True
            res.complete = False
            break
        try:
            volume.read_needle(key)
        except CorruptNeedle as e:
            res.corruptions.append((key, str(e)))
        except (NeedleNotFound, CookieMismatch):
            pass  # deleted/expired between snapshot and read
        except Exception as e:  # noqa: BLE001 - EIO, parse desync, ...
            # a latent sector error (OSError) or a framing/parse blowup
            # is exactly the damage a scrubber exists to find — record
            # it and keep sweeping; letting it propagate would wedge
            # the engine at this cursor forever (every sweep re-crashes
            # on the same needle and nothing after it is ever scanned)
            res.corruptions.append((key, f"read failed: {e!r}"))
        res.scanned_bytes += record
        res.consumed += 1
        res.last_key = key
    return res
