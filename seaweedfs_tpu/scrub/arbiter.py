"""BandwidthArbiter: ONE budget for every background byte-mover.

Four claimants share the node's background bandwidth — EC rebuilds
("rebuild"), cross-cluster replication ("replication"), hinted-handoff
replay ("handoff"), and lifecycle tiering ("tier"). Before PR 17 each
ran unpaced: a big handoff spool replayed at full speed against a
rebuild racing a second shard loss (the known gap ROADMAP names), and
tier/replication would have joined the stampede. Now every one of them
charges its bytes here BEFORE moving them.

Mechanics (docs/TIERING.md):

  * weighted max-min shares: each claimant owns a token bucket whose
    rate is its weight's slice of the total — but only ACTIVE
    claimants (charged within the last 2 s) count in the denominator,
    so a lone claimant gets the whole budget and shares shrink only
    under real contention. That is what makes the handoff-vs-rebuild
    regression hold: a 100-hint replay storm drops to its weighted
    slice the moment a rebuild starts charging.
  * serve-first yield (the PR-12 rebuild arbitration idiom): the
    serving path stamps note_serve() on every foreground GET/POST;
    while a stamp is fresher than the yield window, every background
    rate is multiplied down by the yield factor — foreground latency
    outranks all four claimants.
  * debt semantics match scrub/ratelimit.TokenBucket: admission waits
    on min(n, burst), the charge is the full n, so oversized items
    (a 4 MiB shard tile) keep the long-run rate exact without
    deadlocking.

`WEED_ARBITER=0` disables pacing wholesale (every take returns
immediately; stats still count). `WEED_ARBITER_MBPS` sets the total
budget (default 256 MB/s), `WEED_ARBITER_YIELD_MS` /
`WEED_ARBITER_YIELD_FACTOR` tune the serve-first yield.
"""

from __future__ import annotations

import os
import threading
import time

# weighted shares under full contention; rebuilds outrank everything
# (they are the redundancy clock), tier is the most patient
DEFAULT_WEIGHTS = {
    "rebuild": 0.45,
    "replication": 0.25,
    "handoff": 0.20,
    "tier": 0.10,
}

_ACTIVE_WINDOW_S = 2.0


def arbiter_enabled() -> bool:
    return os.environ.get("WEED_ARBITER", "1") != "0"


def _float(raw: str | None, default: float) -> float:
    # callers pass os.environ.get("WEED_...") inline so the weedlint
    # contract-env rule can see which knob each read belongs to
    try:
        return float(raw or default)
    except ValueError:
        return default


class _Claim:
    __slots__ = ("tokens", "last", "last_active", "bytes", "waited_s", "takes")

    def __init__(self, now: float):
        self.tokens = 0.0
        self.last = now
        self.last_active = 0.0  # never active until the first take
        self.bytes = 0
        self.waited_s = 0.0
        self.takes = 0


class BandwidthArbiter:
    def __init__(
        self,
        total_bytes_s: float | None = None,
        weights: dict[str, float] | None = None,
        yield_window_s: float | None = None,
        yield_factor: float | None = None,
    ):
        if total_bytes_s is None:
            total_bytes_s = (
                _float(os.environ.get("WEED_ARBITER_MBPS"), 256.0) * 1e6
            )
        self.total = float(total_bytes_s)
        self.weights = dict(weights or DEFAULT_WEIGHTS)
        self.yield_window_s = (
            _float(os.environ.get("WEED_ARBITER_YIELD_MS"), 200.0) / 1000.0
            if yield_window_s is None
            else yield_window_s
        )
        self.yield_factor = (
            _float(os.environ.get("WEED_ARBITER_YIELD_FACTOR"), 0.25)
            if yield_factor is None
            else yield_factor
        )
        now = time.monotonic()
        self._claims = {name: _Claim(now) for name in self.weights}
        self._last_serve = 0.0
        self._lock = threading.Lock()
        self.enabled = arbiter_enabled() and self.total > 0

    # --- serve-first yield -------------------------------------------------
    def note_serve(self) -> None:
        """Stamp foreground traffic; lock-free (a torn float store does
        not exist in CPython, and staleness of one GET is harmless)."""
        self._last_serve = time.monotonic()

    def _rate_locked(self, name: str, now: float) -> float:
        active_weight = 0.0
        for n, c in self._claims.items():
            if n == name or now - c.last_active < _ACTIVE_WINDOW_S:
                active_weight += self.weights.get(n, 0.1)
        share = self.weights.get(name, 0.1) / max(active_weight, 1e-9)
        rate = self.total * share
        if now - self._last_serve < self.yield_window_s:
            rate *= self.yield_factor
        return max(rate, 1.0)

    # --- the charge point --------------------------------------------------
    def take(self, name: str, n: int, stop: threading.Event | None = None) -> bool:
        """Charge `n` background bytes to claimant `name`, blocking
        until the claimant's current share admits them. Returns False
        (without consuming) when `stop` fires first."""
        from seaweedfs_tpu.stats.metrics import ARBITER_BYTES, ARBITER_WAIT_SECONDS

        with self._lock:
            claim = self._claims.get(name)
            if claim is None:
                claim = self._claims[name] = _Claim(time.monotonic())
            claim.takes += 1
            claim.bytes += int(n)
        ARBITER_BYTES.labels(name).inc(int(n))
        if not self.enabled:
            return True
        started = time.monotonic()
        while True:
            with self._lock:
                now = time.monotonic()
                rate = self._rate_locked(name, now)
                burst = max(rate, 1.0)  # one second of the current share
                claim.tokens = min(
                    burst, claim.tokens + (now - claim.last) * rate
                )
                claim.last = now
                claim.last_active = now
                need = min(float(n), burst)
                if claim.tokens >= need:
                    claim.tokens -= float(n)
                    waited = now - started
                    claim.waited_s += waited
                    if waited > 0:
                        ARBITER_WAIT_SECONDS.labels(name).inc(waited)
                    return True
                wait = (need - claim.tokens) / rate
            wait = min(wait, 0.25)
            if stop is not None:
                if stop.wait(wait):
                    with self._lock:
                        claim.bytes -= int(n)  # never moved
                    return False
            else:
                time.sleep(wait)

    # --- observability -----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "Enabled": self.enabled,
                "TotalBytesPerSec": self.total,
                "YieldWindowMs": round(self.yield_window_s * 1000, 1),
                "YieldFactor": self.yield_factor,
                "Serving": now - self._last_serve < self.yield_window_s,
                "Claimants": {
                    name: {
                        "Weight": self.weights.get(name, 0.1),
                        "Bytes": c.bytes,
                        "Takes": c.takes,
                        "WaitedSeconds": round(c.waited_s, 3),
                        "Active": now - c.last_active < _ACTIVE_WINDOW_S,
                        "RateBytesPerSec": round(self._rate_locked(name, now)),
                    }
                    for name, c in self._claims.items()
                },
            }


# --- process-global instance ----------------------------------------------
# One arbiter per process: the RepairScheduler owns/constructs it on the
# master, but volume servers + filers (handoff replay, replication,
# tier transfers) reach it through this accessor so every claimant in a
# process shares ONE budget. Tests swap it with set_arbiter().
_arbiter: BandwidthArbiter | None = None
_arbiter_lock = threading.Lock()


def get_arbiter() -> BandwidthArbiter:
    global _arbiter
    with _arbiter_lock:
        if _arbiter is None:
            _arbiter = BandwidthArbiter()
        return _arbiter


def set_arbiter(a: BandwidthArbiter | None) -> BandwidthArbiter | None:
    """Install (or with None, reset) the process arbiter; returns the
    previous one so tests can restore it."""
    global _arbiter
    with _arbiter_lock:
        prev, _arbiter = _arbiter, a
        return prev
