"""File-key sequencer: allocates needle-id ranges for /dir/assign.

Behavioral match of reference weed/sequence/memory_sequencer.go: a
counter starting at 1; NextFileId(count) hands out [counter,
counter+count) and advances; SetMax lifts the counter when heartbeats
report larger keys already in use (master_grpc_server.go via
Topology). The etcd-backed variant (etcd_sequencer.go) plugs in behind
the same two methods.
"""

from __future__ import annotations

import threading

from seaweedfs_tpu.util import durable


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Returns the first id of a freshly reserved range of `count`."""
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        # '>=' so a reported key equal to the counter advances past it
        # (memory_sequencer.go:28 `counter <= value`) — otherwise the
        # next assign re-issues an id already on disk.
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class FileSequencer:
    """Durable sequencer: the counter survives master restarts the way
    the reference's EtcdSequencer does (sequence/etcd_sequencer.go) —
    without an external KV store, the durable medium is a local file.

    Ranges are reserved in batches: the file stores the upper bound of
    the reserved range, so one fsync covers `batch` allocations and a
    crash only skips ids (never reuses them) — the same no-reuse
    guarantee etcd leases give the reference."""

    BATCH = 10000  # ids reserved per durable write (etcd_sequencer.go step)

    def __init__(self, path: str, batch: int = BATCH):
        import os

        self._path = path
        self._batch = batch
        self._lock = threading.Lock()
        reserved = 0
        if os.path.exists(path):
            try:
                with open(path) as f:
                    reserved = int(f.read().strip() or 0)
            except (OSError, ValueError):
                reserved = 0
        # resume past everything previously reserved: ids in (counter,
        # reserved] may or may not have been handed out pre-crash
        self._counter = reserved + 1
        self._reserved = reserved
        self._ensure_reserved_locked()

    def _ensure_reserved_locked(self) -> None:
        import os

        if self._counter <= self._reserved:
            return  # still inside the durably reserved range
        self._reserved = self._counter + self._batch
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self._reserved))
        # fsync + rename + dir fsync: a reservation that does not
        # survive the crash can re-issue file ids the old process
        # already handed out
        durable.publish(tmp, self._path)

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            self._ensure_reserved_locked()
            return start

    def set_max(self, seen_value: int) -> None:
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1
                self._ensure_reserved_locked()

    def peek(self) -> int:
        with self._lock:
            return self._counter


class EtcdSequencer:
    """Sequencer backed by an external etcd cluster — the multi-master
    external-KV role of sequence/etcd_sequencer.go, speaking etcd's v3
    grpc-gateway REST API directly (/v3/kv/range, /v3/kv/put,
    /v3/kv/txn) instead of a client library. (The reference rides the
    long-dead etcd v2 client API; the semantics are the same: reserve
    [current, max) ranges with a compare-and-swap step bump, lift the
    stored max when heartbeats report larger keys.)

    Gated on connectivity: constructing dials the endpoint and raises
    with guidance when no etcd (or the in-repo fake,
    tests/cloud_fakes.FakeEtcd) answers."""

    KEY = "/seaweedfs/master/sequence"
    STEP = 500  # ids reserved per etcd CAS (DefaultEtcdSteps)

    def __init__(self, urls: str, step: int = STEP):
        import base64

        from seaweedfs_tpu.util.etcd import EtcdKv

        self._kv = EtcdKv(urls)
        self._step = step
        self._lock = threading.Lock()
        self._key_b64 = base64.b64encode(self.KEY.encode()).decode()
        try:
            stored = self._get()
        except OSError as e:
            raise RuntimeError(
                f"etcd sequencer cannot reach {urls!r} ({e}); start etcd "
                "(or use the default file-backed sequencer via -mdir)"
            ) from e
        if stored is None:
            self._cas_create(0)
            stored = self._get() or 0
        # ids start at 1 (memory_sequencer.go convention)
        self._current = max(stored, 1)
        self._max = stored

    # --- etcd v3 gateway primitives ------------------------------------
    def _call(self, op: str, payload: dict) -> dict:
        return self._kv.call(op, payload)

    def _get(self) -> int | None:
        import base64

        resp = self._call("range", {"key": self._key_b64})
        kvs = resp.get("kvs", [])
        if not kvs:
            return None
        return int(base64.b64decode(kvs[0]["value"]))

    def _b64(self, n: int) -> str:
        import base64

        return base64.b64encode(str(n).encode()).decode()

    def _cas_create(self, value: int) -> bool:
        """Create-if-absent (createRevision == 0 compare)."""
        resp = self._call(
            "txn",
            {
                "compare": [
                    {
                        "key": self._key_b64,
                        "target": "CREATE",
                        "createRevision": "0",
                    }
                ],
                "success": [
                    {
                        "requestPut": {
                            "key": self._key_b64,
                            "value": self._b64(value),
                        }
                    }
                ],
            },
        )
        return bool(resp.get("succeeded"))

    def _cas_swap(self, prev: int, new: int) -> bool:
        resp = self._call(
            "txn",
            {
                "compare": [
                    {
                        "key": self._key_b64,
                        "target": "VALUE",
                        "value": self._b64(prev),
                    }
                ],
                "success": [
                    {
                        "requestPut": {
                            "key": self._key_b64,
                            "value": self._b64(new),
                        }
                    }
                ],
            },
        )
        return bool(resp.get("succeeded"))

    def _reserve_locked(self, at_least: int) -> None:
        """CAS-bump the stored max until [current, max) covers
        at_least ids (batchGetSequenceFromEtcd's retry loop)."""
        while self._max - self._current < at_least:
            stored = self._get()
            new_max = max(stored or 0, self._current) + max(self._step, at_least)
            if stored is None:
                # key vanished (deleted externally): a VALUE compare can
                # never match an absent key, so create-if-absent instead
                ok = self._cas_create(new_max)
            else:
                ok = self._cas_swap(stored, new_max)
            if ok:
                self._current = max(self._current, stored or 0)
                self._max = new_max

    # --- Sequencer API --------------------------------------------------
    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            self._reserve_locked(count)
            start = self._current
            self._current += count
            return start

    def set_max(self, seen_value: int) -> None:
        with self._lock:
            if seen_value < self._current:
                return
            self._current = seen_value + 1
            self._reserve_locked(1)

    def peek(self) -> int:
        with self._lock:
            return self._current
