"""File-key sequencer: allocates needle-id ranges for /dir/assign.

Behavioral match of reference weed/sequence/memory_sequencer.go: a
counter starting at 1; NextFileId(count) hands out [counter,
counter+count) and advances; SetMax lifts the counter when heartbeats
report larger keys already in use (master_grpc_server.go via
Topology). The etcd-backed variant (etcd_sequencer.go) plugs in behind
the same two methods.
"""

from __future__ import annotations

import threading


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Returns the first id of a freshly reserved range of `count`."""
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        # '>=' so a reported key equal to the counter advances past it
        # (memory_sequencer.go:28 `counter <= value`) — otherwise the
        # next assign re-issues an id already on disk.
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter


class FileSequencer:
    """Durable sequencer: the counter survives master restarts the way
    the reference's EtcdSequencer does (sequence/etcd_sequencer.go) —
    without an external KV store, the durable medium is a local file.

    Ranges are reserved in batches: the file stores the upper bound of
    the reserved range, so one fsync covers `batch` allocations and a
    crash only skips ids (never reuses them) — the same no-reuse
    guarantee etcd leases give the reference."""

    BATCH = 10000  # ids reserved per durable write (etcd_sequencer.go step)

    def __init__(self, path: str, batch: int = BATCH):
        import os

        self._path = path
        self._batch = batch
        self._lock = threading.Lock()
        reserved = 0
        if os.path.exists(path):
            try:
                with open(path) as f:
                    reserved = int(f.read().strip() or 0)
            except (OSError, ValueError):
                reserved = 0
        # resume past everything previously reserved: ids in (counter,
        # reserved] may or may not have been handed out pre-crash
        self._counter = reserved + 1
        self._reserved = reserved
        self._ensure_reserved_locked()

    def _ensure_reserved_locked(self) -> None:
        import os

        if self._counter <= self._reserved:
            return  # still inside the durably reserved range
        self._reserved = self._counter + self._batch
        tmp = self._path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(self._reserved))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            self._ensure_reserved_locked()
            return start

    def set_max(self, seen_value: int) -> None:
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1
                self._ensure_reserved_locked()

    def peek(self) -> int:
        with self._lock:
            return self._counter
