"""File-key sequencer: allocates needle-id ranges for /dir/assign.

Behavioral match of reference weed/sequence/memory_sequencer.go: a
counter starting at 1; NextFileId(count) hands out [counter,
counter+count) and advances; SetMax lifts the counter when heartbeats
report larger keys already in use (master_grpc_server.go via
Topology). The etcd-backed variant (etcd_sequencer.go) plugs in behind
the same two methods.
"""

from __future__ import annotations

import threading


class MemorySequencer:
    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        """Returns the first id of a freshly reserved range of `count`."""
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen_value: int) -> None:
        # '>=' so a reported key equal to the counter advances past it
        # (memory_sequencer.go:28 `counter <= value`) — otherwise the
        # next assign re-issues an id already on disk.
        with self._lock:
            if seen_value >= self._counter:
                self._counter = seen_value + 1

    def peek(self) -> int:
        with self._lock:
            return self._counter
