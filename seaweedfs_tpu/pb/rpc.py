"""gRPC service binding without grpc_tools.

Each service is a method table {name: (RequestCls, ResponseCls, kind)};
`servicer_handler` turns an implementation object into a generic
handler for grpc.Server, and `Stub` builds the client-side callables on
a channel. Equivalent to what generated *_pb2_grpc code does, minus the
codegen dependency.
"""

from __future__ import annotations

import threading

import grpc

from seaweedfs_tpu.pb import filer_pb2 as f
from seaweedfs_tpu.pb import master_pb2 as m
from seaweedfs_tpu.pb import raft_pb2 as r
from seaweedfs_tpu.pb import volume_pb2 as v
from seaweedfs_tpu.util import deadline as _deadline

GRPC_PORT_OFFSET = 10000  # reference convention: grpc port = http port + 10000


def grpc_address(http_addr: str) -> str:
    """"host:9333" → "host:19333"."""
    host, _, port = http_addr.partition(":")
    return f"{host}:{int(port) + GRPC_PORT_OFFSET}"


# --- process-wide gRPC TLS (security/tls.go role) ---------------------------
# set_tls() installs one TlsConfig for every dial()/add_port() in the
# process; None (default) keeps plaintext channels. The grpc "target
# name override" lets certs issued for a common name (e.g. "seaweedfs")
# verify against 127.0.0.1 endpoints, as cluster-internal mTLS needs.
_TLS = None
_TLS_SERVER_NAME = ""


def set_tls(tls, server_name_override: str = "") -> None:
    global _TLS, _TLS_SERVER_NAME
    _TLS = tls
    _TLS_SERVER_NAME = server_name_override
    _reset_channel_cache()  # pooled channels carry the old credentials


_CHANNEL_CACHE: dict[str, grpc.Channel] = {}
_CHANNEL_CACHE_LOCK = threading.Lock()


def cached_channel(addr: str) -> grpc.Channel:
    """Process-wide pooled channel to `addr` (grpc channels are
    thread-safe and multiplex concurrent RPCs over one HTTP/2
    connection). The reference pools the same way
    (operation/grpc_client.go:15-41); dialing per call pays a fresh
    TCP+HTTP/2 handshake on every assign/lookup. Never close the
    returned channel — set_tls() invalidates the pool wholesale."""
    with _CHANNEL_CACHE_LOCK:
        ch = _CHANNEL_CACHE.get(addr)
        if ch is None:
            ch = _CHANNEL_CACHE[addr] = dial(addr)
        return ch


def _reset_channel_cache() -> None:
    with _CHANNEL_CACHE_LOCK:
        old = list(_CHANNEL_CACHE.values())
        _CHANNEL_CACHE.clear()
    for ch in old:
        try:
            ch.close()
        except Exception:
            pass


def dial(addr: str) -> grpc.Channel:
    """TLS channel when the process has TLS configured, else plaintext
    (the single seam every client-side channel goes through)."""
    if _TLS is not None and _TLS.is_enabled:
        from seaweedfs_tpu.security.tls import client_credentials

        options = []
        if _TLS_SERVER_NAME:
            options.append(
                ("grpc.ssl_target_name_override", _TLS_SERVER_NAME)
            )
        return grpc.secure_channel(addr, client_credentials(_TLS), options)
    return grpc.insecure_channel(addr)


def add_port(server: grpc.Server, addr: str) -> None:
    """Bind a server port honoring the process TLS config."""
    if _TLS is not None and _TLS.is_enabled:
        from seaweedfs_tpu.security.tls import server_credentials

        server.add_secure_port(addr, server_credentials(_TLS))
    else:
        server.add_insecure_port(addr)


UNARY_UNARY = "unary_unary"
UNARY_STREAM = "unary_stream"
STREAM_UNARY = "stream_unary"
STREAM_STREAM = "stream_stream"

MASTER_SERVICE = "seaweedfs_tpu.master.Master"
MASTER_METHODS = {
    "Heartbeat": (m.HeartbeatRequest, m.HeartbeatResponse, STREAM_STREAM),
    "KeepConnected": (m.ClientHello, m.VolumeLocationDelta, STREAM_STREAM),
    "Assign": (m.AssignRequest, m.AssignResponse, UNARY_UNARY),
    "LookupVolume": (m.LookupVolumeRequest, m.LookupVolumeResponse, UNARY_UNARY),
    "LookupEcVolume": (m.LookupEcVolumeRequest, m.LookupEcVolumeResponse, UNARY_UNARY),
    "Statistics": (m.StatisticsRequest, m.StatisticsResponse, UNARY_UNARY),
    "CollectionList": (m.CollectionListRequest, m.CollectionListResponse, UNARY_UNARY),
    "CollectionDelete": (m.CollectionDeleteRequest, m.CollectionDeleteResponse, UNARY_UNARY),
    "VolumeList": (m.VolumeListRequest, m.VolumeListResponse, UNARY_UNARY),
    "GetMasterConfiguration": (
        m.GetMasterConfigurationRequest,
        m.GetMasterConfigurationResponse,
        UNARY_UNARY,
    ),
}

VOLUME_SERVICE = "seaweedfs_tpu.volume.VolumeServer"
VOLUME_METHODS = {
    "BatchDelete": (v.BatchDeleteRequest, v.BatchDeleteResponse, UNARY_UNARY),
    "VacuumVolumeCheck": (v.VacuumVolumeCheckRequest, v.VacuumVolumeCheckResponse, UNARY_UNARY),
    "VacuumVolumeCompact": (v.VacuumVolumeCompactRequest, v.VacuumVolumeCompactResponse, UNARY_UNARY),
    "VacuumVolumeCommit": (v.VacuumVolumeCommitRequest, v.VacuumVolumeCommitResponse, UNARY_UNARY),
    "VacuumVolumeCleanup": (v.VacuumVolumeCleanupRequest, v.VacuumVolumeCleanupResponse, UNARY_UNARY),
    "AllocateVolume": (v.AllocateVolumeRequest, v.AllocateVolumeResponse, UNARY_UNARY),
    "DeleteCollection": (v.DeleteCollectionRequest, v.DeleteCollectionResponse, UNARY_UNARY),
    "VolumeDelete": (v.VolumeDeleteRequest, v.VolumeDeleteResponse, UNARY_UNARY),
    "VolumeMarkReadonly": (v.VolumeMarkReadonlyRequest, v.VolumeMarkReadonlyResponse, UNARY_UNARY),
    "VolumeMarkWritable": (v.VolumeMarkWritableRequest, v.VolumeMarkWritableResponse, UNARY_UNARY),
    "VolumeMount": (v.VolumeMountRequest, v.VolumeMountResponse, UNARY_UNARY),
    "VolumeUnmount": (v.VolumeUnmountRequest, v.VolumeUnmountResponse, UNARY_UNARY),
    "VolumeSyncStatus": (v.VolumeSyncStatusRequest, v.VolumeSyncStatusResponse, UNARY_UNARY),
    "VolumeCopy": (v.VolumeCopyRequest, v.VolumeCopyResponse, UNARY_UNARY),
    "CopyFile": (v.CopyFileRequest, v.CopyFileResponse, UNARY_STREAM),
    "VolumeIncrementalCopy": (
        v.VolumeIncrementalCopyRequest,
        v.VolumeIncrementalCopyResponse,
        UNARY_STREAM,
    ),
    "VolumeEcShardsGenerate": (
        v.VolumeEcShardsGenerateRequest,
        v.VolumeEcShardsGenerateResponse,
        UNARY_UNARY,
    ),
    "VolumeEcShardsBatchGenerate": (
        v.VolumeEcShardsBatchGenerateRequest,
        v.VolumeEcShardsBatchGenerateResponse,
        UNARY_UNARY,
    ),
    "VolumeEcShardsRebuild": (
        v.VolumeEcShardsRebuildRequest,
        v.VolumeEcShardsRebuildResponse,
        UNARY_UNARY,
    ),
    # batch rebuild rides the BatchGenerate message pair (ids in,
    # empty response): the method table IS the service definition
    # here, so a new verb needs no proto regeneration as long as an
    # existing message shape fits
    "VolumeEcShardsBatchRebuild": (
        v.VolumeEcShardsBatchGenerateRequest,
        v.VolumeEcShardsBatchGenerateResponse,
        UNARY_UNARY,
    ),
    "VolumeEcShardsCopy": (v.VolumeEcShardsCopyRequest, v.VolumeEcShardsCopyResponse, UNARY_UNARY),
    "VolumeEcShardsDelete": (
        v.VolumeEcShardsDeleteRequest,
        v.VolumeEcShardsDeleteResponse,
        UNARY_UNARY,
    ),
    "VolumeEcShardsMount": (v.VolumeEcShardsMountRequest, v.VolumeEcShardsMountResponse, UNARY_UNARY),
    "VolumeEcShardsUnmount": (
        v.VolumeEcShardsUnmountRequest,
        v.VolumeEcShardsUnmountResponse,
        UNARY_UNARY,
    ),
    "VolumeEcShardRead": (v.VolumeEcShardReadRequest, v.VolumeEcShardReadResponse, UNARY_STREAM),
    "VolumeEcBlobDelete": (v.VolumeEcBlobDeleteRequest, v.VolumeEcBlobDeleteResponse, UNARY_UNARY),
    "VolumeEcShardsToVolume": (
        v.VolumeEcShardsToVolumeRequest,
        v.VolumeEcShardsToVolumeResponse,
        UNARY_UNARY,
    ),
    "VolumeTierMoveDatToRemote": (
        v.VolumeTierMoveDatToRemoteRequest,
        v.VolumeTierMoveDatToRemoteResponse,
        UNARY_STREAM,
    ),
    "VolumeTierMoveDatFromRemote": (
        v.VolumeTierMoveDatFromRemoteRequest,
        v.VolumeTierMoveDatFromRemoteResponse,
        UNARY_STREAM,
    ),
    "Query": (v.QueryRequest, v.QueriedStripe, UNARY_STREAM),
    "VolumeTailSender": (
        v.VolumeTailSenderRequest,
        v.VolumeTailSenderResponse,
        UNARY_STREAM,
    ),
    "VolumeTailReceiver": (
        v.VolumeTailReceiverRequest,
        v.VolumeTailReceiverResponse,
        UNARY_UNARY,
    ),
}


FILER_SERVICE = "seaweedfs_tpu.filer.Filer"
FILER_METHODS = {
    "LookupDirectoryEntry": (
        f.LookupDirectoryEntryRequest,
        f.LookupDirectoryEntryResponse,
        UNARY_UNARY,
    ),
    "ListEntries": (f.ListEntriesRequest, f.ListEntriesResponse, UNARY_STREAM),
    "CreateEntry": (f.CreateEntryRequest, f.CreateEntryResponse, UNARY_UNARY),
    "UpdateEntry": (f.UpdateEntryRequest, f.UpdateEntryResponse, UNARY_UNARY),
    "DeleteEntry": (f.DeleteEntryRequest, f.DeleteEntryResponse, UNARY_UNARY),
    "AtomicRenameEntry": (
        f.AtomicRenameEntryRequest,
        f.AtomicRenameEntryResponse,
        UNARY_UNARY,
    ),
    "AssignVolume": (f.AssignVolumeRequest, f.AssignVolumeResponse, UNARY_UNARY),
    "LookupVolume": (f.LookupVolumeRequest, f.LookupVolumeResponse, UNARY_UNARY),
    "DeleteCollection": (f.DeleteCollectionRequest, f.DeleteCollectionResponse, UNARY_UNARY),
    "Statistics": (f.StatisticsRequest, f.StatisticsResponse, UNARY_UNARY),
    "GetFilerConfiguration": (
        f.GetFilerConfigurationRequest,
        f.GetFilerConfigurationResponse,
        UNARY_UNARY,
    ),
}


def _deadline_guard(fn, kind):
    """Server-side deadline enforcement for every gRPC service bound
    through servicer_handler (docs/CHAOS.md): an `x-weed-deadline`
    metadata budget that arrived already expired aborts with
    DEADLINE_EXCEEDED before the method runs, and unary-response
    methods execute under the budget as the ambient deadline so their
    own downstream hops inherit it. Streaming-response methods get the
    fast-reject only — their generators run lazily on other threads,
    where a scoped thread-local would not follow."""
    unary_resp = kind in (UNARY_UNARY, STREAM_UNARY)

    def call(request, context):
        dl = _deadline.from_grpc_context(context) if _deadline.enabled() else None
        if dl is None:
            return fn(request, context)
        if dl.expired:
            context.abort(
                grpc.StatusCode.DEADLINE_EXCEEDED,
                "x-weed-deadline expired before dispatch",
            )
        if not unary_resp:
            return fn(request, context)
        with _deadline.scope(dl):
            return fn(request, context)

    return call


def servicer_handler(service_name: str, methods: dict, impl) -> grpc.GenericRpcHandler:
    """Bind `impl`'s methods (same names as the table) into a generic
    gRPC handler. Methods receive (request_or_iterator, context)."""
    handlers = {}
    for name, (req_cls, _resp_cls, kind) in methods.items():
        fn = getattr(impl, name)
        factory = getattr(grpc, f"{kind}_rpc_method_handler")
        handlers[name] = factory(
            _deadline_guard(fn, kind),
            request_deserializer=req_cls.FromString,
            response_serializer=lambda msg: msg.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(service_name, handlers)


def _traced_call(multicallable):
    """Auto-inject the current trace context as gRPC invocation
    metadata (docs/TRACING.md): ONE wrapper here propagates the
    `X-Weed-Trace` header across every internal gRPC hop — EC shard
    reads, copies, rebuild verbs, heartbeats — without touching call
    sites. Explicit metadata= wins (the EC readers capture context at
    factory time because their calls run on pool threads).

    Deadline plane (docs/CHAOS.md): the same wrapper derives each
    attempt's gRPC timeout from the ambient request deadline's
    REMAINING budget (min with any explicit timeout) and forwards the
    budget as `x-weed-deadline` metadata; an already-exhausted budget
    raises DeadlineExceeded without dialing."""

    def call(request, timeout=None, metadata=None, **kwargs):
        if metadata is None:
            from seaweedfs_tpu.trace import grpc_metadata

            metadata = grpc_metadata()
        dl = _deadline.effective(None)
        if dl is not None:
            timeout = dl.cap(timeout)  # DeadlineExceeded when spent
            md = list(metadata) if metadata else []
            if not any(k == _deadline.DEADLINE_HEADER for k, _ in md):
                md.append((_deadline.DEADLINE_HEADER, dl.header_value()))
            metadata = md
        return multicallable(
            request, timeout=timeout, metadata=metadata, **kwargs
        )

    return call


class Stub:
    """Client stub: one callable attribute per method."""

    def __init__(self, channel: grpc.Channel, service_name: str, methods: dict):
        for name, (req_cls, resp_cls, kind) in methods.items():
            factory = getattr(channel, kind)
            setattr(
                self,
                name,
                _traced_call(
                    factory(
                        f"/{service_name}/{name}",
                        request_serializer=lambda msg: msg.SerializeToString(),
                        response_deserializer=resp_cls.FromString,
                    )
                ),
            )


RAFT_SERVICE = "seaweedfs_tpu.raft.Raft"
RAFT_METHODS = {
    "RequestVote": (r.RequestVoteRequest, r.RequestVoteResponse, UNARY_UNARY),
    "AppendEntries": (r.AppendEntriesRequest, r.AppendEntriesResponse, UNARY_UNARY),
}


def raft_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, RAFT_SERVICE, RAFT_METHODS)


def master_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, MASTER_SERVICE, MASTER_METHODS)


def volume_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, VOLUME_SERVICE, VOLUME_METHODS)


def filer_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, FILER_SERVICE, FILER_METHODS)


# --- TiKV raw-KV + PD routing (pingcap/kvproto wire) ------------------------
# Service full names are the REAL kvproto ones so these stubs speak to
# an actual PD/TiKV deployment; messages live in tikv.proto (semantic
# clone with kvproto field numbers). Used by filer/tikv_store.py and
# served offline by tests/cloud_fakes.FakeTikv.

from seaweedfs_tpu.pb import tikv_pb2 as tk

PD_SERVICE = "pdpb.PD"
PD_METHODS = {
    "GetMembers": (tk.GetMembersRequest, tk.GetMembersResponse, UNARY_UNARY),
    "GetRegion": (tk.GetRegionRequest, tk.GetRegionResponse, UNARY_UNARY),
    "GetStore": (tk.GetStoreRequest, tk.GetStoreResponse, UNARY_UNARY),
}

TIKV_SERVICE = "tikvpb.Tikv"
TIKV_METHODS = {
    "RawGet": (tk.RawGetRequest, tk.RawGetResponse, UNARY_UNARY),
    "RawPut": (tk.RawPutRequest, tk.RawPutResponse, UNARY_UNARY),
    "RawDelete": (tk.RawDeleteRequest, tk.RawDeleteResponse, UNARY_UNARY),
    "RawDeleteRange": (
        tk.RawDeleteRangeRequest,
        tk.RawDeleteRangeResponse,
        UNARY_UNARY,
    ),
    "RawScan": (tk.RawScanRequest, tk.RawScanResponse, UNARY_UNARY),
}


def pd_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, PD_SERVICE, PD_METHODS)


def tikv_stub(channel: grpc.Channel) -> Stub:
    return Stub(channel, TIKV_SERVICE, TIKV_METHODS)
