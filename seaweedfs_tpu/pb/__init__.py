"""Wire-protocol definitions: protobuf messages + gRPC method tables.

master.proto / volume.proto are compiled with `protoc --python_out`
(make_pb.sh). The environment ships grpc but not grpc_tools, so the
service layer (stubs + servicer registration) is built from the method
tables in rpc.py via grpc's generic-handler API instead of generated
*_pb2_grpc modules.
"""
