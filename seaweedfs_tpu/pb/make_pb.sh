#!/bin/sh
# Regenerate protobuf message modules (run from the repo root).
# The gRPC service layer is hand-bound in rpc.py, so only --python_out
# is needed (no grpc_tools in this environment).
set -e
protoc --python_out=. seaweedfs_tpu/pb/master.proto seaweedfs_tpu/pb/volume.proto seaweedfs_tpu/pb/tikv.proto
