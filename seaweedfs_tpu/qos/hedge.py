"""Hedged replica reads: fire a second GET when the first runs long.

The classic tied-request defense (Dean & Barroso, "The Tail at Scale"):
a read that has not answered within an adaptive delay — tracked
per-volume as a latency quantile, NOT a fixed timer — fires a second
attempt at the next replica, takes whichever answers first, and tears
down the loser's connection so the slow server stops working on it.
The second attempt carries the `x-weed-hedge` hop header so servers
can tell tied reads from first attempts (they count them and annotate
the span; the loser's socket teardown is the cancel signal).

Used by the filer chunk-read path (filer/stream.py — which is what the
S3 and WebDAV gateways read through) and by weedload's hedged GET
workers. `WEED_QOS=0` / `WEED_QOS_HEDGE=0` routes every read back
through the plain pooled single-attempt path wholesale.

Why not the op.http_call pool: cancellation closes a socket mid-
response, which a shared keep-alive pool must never see. Attempts
check connections out of a small dedicated pool; a cancelled attempt's
connection is closed and never returned.
"""

from __future__ import annotations

import http.client
import io
import os
import queue
import threading
import urllib.error

from seaweedfs_tpu import trace
from seaweedfs_tpu import qos
from seaweedfs_tpu.client import vid_map as _vm
from seaweedfs_tpu.stats.metrics import (
    HEDGE_CANCELLED,
    HEDGE_FIRED,
    HEDGE_WON,
)

_MIN_DELAY_S = 0.001
_SAMPLES_FOR_QUANTILE = 16


def _initial_delay_s() -> float:
    """Hedge delay before a volume has latency history (and the floor
    the adaptive delay decays toward): WEED_QOS_HEDGE_MS, default 25."""
    try:
        return float(os.environ.get("WEED_QOS_HEDGE_MS", "25")) / 1000.0
    except ValueError:
        return 0.025


def _max_delay_s() -> float:
    """Adaptive-delay ceiling: WEED_QOS_HEDGE_MAX_MS, default 1000."""
    try:
        return float(os.environ.get("WEED_QOS_HEDGE_MAX_MS", "1000")) / 1000.0
    except ValueError:
        return 1.0


class LatencyTracker:
    """Per-key ring of recent winner latencies; the hedge delay is the
    p95 of the ring (clamped), so a volume that usually answers in 2 ms
    hedges at ~2 ms while a 50 ms volume waits 50 ms — a fixed timer
    would either hedge everything or nothing."""

    _RING = 64

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rings: dict[object, list[float]] = {}
        self._pos: dict[object, int] = {}

    def record(self, key, seconds: float) -> None:
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = []
                self._pos[key] = 0
                if len(self._rings) > 4096:  # bound: forget oldest keys
                    for stale in list(self._rings)[:1024]:
                        if stale != key:
                            self._rings.pop(stale, None)
                            self._pos.pop(stale, None)
            if len(ring) < self._RING:
                ring.append(seconds)
            else:
                ring[self._pos[key]] = seconds
                self._pos[key] = (self._pos[key] + 1) % self._RING

    def delay_s(self, key) -> float:
        with self._lock:
            ring = list(self._rings.get(key, ()))
        if len(ring) < _SAMPLES_FOR_QUANTILE:
            return _initial_delay_s()
        ring.sort()
        p95 = ring[min(len(ring) - 1, int(len(ring) * 0.95))]
        return min(max(p95, _MIN_DELAY_S), _max_delay_s())


class _ConnPool:
    """Tiny keep-alive pool attempts check connections OUT of (so a
    cancel can close a socket that is provably owned by one attempt)."""

    _PER_HOST = 4

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idle: dict[str, list] = {}

    def checkout(self, netloc: str, timeout: float):
        with self._lock:
            idle = self._idle.get(netloc)
            if idle:
                c = idle.pop()
                c.settimeout(timeout)
                return c, True
        from seaweedfs_tpu.client.operation import _RawHTTPConnection

        host, _, port = netloc.partition(":")
        return _RawHTTPConnection(host, int(port or 80), timeout), False

    def checkin(self, netloc: str, conn) -> None:
        with self._lock:
            idle = self._idle.setdefault(netloc, [])
            if len(idle) < self._PER_HOST:
                idle.append(conn)
                return
        conn.close()


_POOL = _ConnPool()


class _AttemptPool:
    """Reusable attempt workers (ROADMAP tail-latency follow-on): every
    hedged-capable chunk GET used to spawn 1–2 fresh threads — ~100 µs
    each, noise at ms-scale network reads but pure overhead at high
    fan-out. This is a cached pool: submit() hands the task to a parked
    idle worker when one exists, else starts a new thread that runs the
    task and then PARKS (up to `_MAX_IDLE`; beyond that it exits). A
    hedge never queues behind a busy worker — the fresh-thread fallback
    keeps the fire latency of the old code while the steady state
    recycles the same few threads."""

    _MAX_IDLE = 8
    _IDLE_S = 30.0

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._idle: list[queue.SimpleQueue] = []
        self.spawned = 0  # lifetime thread count (leak-baseline tests)

    def submit(self, fn, *args) -> None:
        with self._lock:
            q = self._idle.pop() if self._idle else None
        if q is not None:
            q.put((fn, args))
            return
        q = queue.SimpleQueue()
        q.put((fn, args))
        with self._lock:
            self.spawned += 1
        threading.Thread(
            target=self._worker, args=(q,), daemon=True,
            name="weed-hedge-worker",
        ).start()

    def _worker(self, q: "queue.SimpleQueue") -> None:
        while True:
            try:
                fn, args = q.get(timeout=self._IDLE_S)
            except queue.Empty:
                with self._lock:
                    if q in self._idle:
                        self._idle.remove(q)
                        return
                # a submitter claimed this queue between the timeout
                # and the lock: its task is (or is about to be) queued
                fn, args = q.get()
            try:
                fn(*args)
            except BaseException:  # noqa: BLE001 — attempts report via out_q
                pass
            with self._lock:
                if len(self._idle) >= self._MAX_IDLE:
                    return
                self._idle.append(q)


_ATTEMPTS = _AttemptPool()


def submit_attempt(fn, *args) -> None:
    """Run `fn(*args)` on the shared cached attempt-worker pool (the
    same parked threads hedged GETs fire on). Fire-and-forget: results
    travel through whatever channel `fn` closes over."""
    _ATTEMPTS.submit(fn, *args)


def gather_first_k(tasks: dict, k: int, timeout: float = 30.0) -> dict:
    """Fan every task out on the shared attempt pool; return the first
    `k` successes as {tag: result}. The generalized k-of-n gather the
    EC degraded read path runs over shard survivors (ROADMAP QoS
    follow-on "hedging for EC degraded reads"): all candidates race,
    the k fastest win, the rest are abandoned.

    `tasks` maps tag -> callable(done_event) -> result; returning None
    (or raising) is a miss. `done_event` is set once k results are in —
    a long task can consult it between retries to stop doing abandoned
    work (attempt-level cancellation; the pool worker itself is
    recycled either way)."""
    if k <= 0 or not tasks:
        return {}
    done = threading.Event()
    out_q: queue.Queue = queue.Queue()

    def run(tag, fn):
        result = None
        try:
            result = fn(done)
        except Exception:  # noqa: BLE001 — a failed attempt is a miss
            result = None
        out_q.put((tag, result))

    for tag, fn in tasks.items():
        _ATTEMPTS.submit(run, tag, fn)
    import time as _time

    got: dict = {}
    pending = len(tasks)
    deadline = _time.monotonic() + timeout
    while len(got) < k and pending > 0:
        wait = deadline - _time.monotonic()
        if wait <= 0:
            break
        try:
            tag, result = out_q.get(timeout=wait)
        except queue.Empty:
            break
        pending -= 1
        if result is not None:
            got[tag] = result
    done.set()
    return got


class _Attempt:
    """One in-flight GET try. cancel() is safe against the completion
    race: the owning thread marks `finished` under the same lock before
    returning its connection to the pool, so cancel can never close a
    connection that has been (or could be) handed to someone else."""

    __slots__ = ("tag", "url", "lock", "conn", "netloc", "finished",
                 "cancelled")

    def __init__(self, tag: int, url: str):
        self.tag = tag
        self.url = url
        self.lock = threading.Lock()
        self.conn = None
        self.netloc = url.partition("/")[0]
        self.finished = False
        self.cancelled = False

    def cancel(self) -> bool:
        """Tear down the in-flight attempt; True if it was still live
        (the socket close is what stops the server-side work)."""
        with self.lock:
            if self.finished or self.cancelled:
                return False
            self.cancelled = True
            if self.conn is not None:
                self.conn.close()
            return True

    def run(self, headers: dict, timeout: float, out_q: "queue.Queue") -> None:
        try:
            conn, reused = _POOL.checkout(self.netloc, timeout)
        except OSError as e:
            out_q.put((self.tag, e, 0, None, None))
            return
        with self.lock:
            if self.cancelled:
                conn.close()
                out_q.put((self.tag, OSError("hedge attempt cancelled"),
                           0, None, None))
                return
            self.conn = conn
        path = "/" + self.url.partition("/")[2]
        try:
            conn.send_request("GET", path, None, headers)
            status, rheaders, body, will_close = conn.read_response("GET")
        except (OSError, http.client.HTTPException) as e:
            conn.close()
            cancelled_now = self.cancelled
            if not reused or cancelled_now:
                out_q.put((self.tag, e, 0, None, None))
                return
            # a stale pooled connection gets ONE fresh-dial retry (GET
            # is idempotent), mirroring op.http_call's retry contract
            from seaweedfs_tpu.client.operation import _RawHTTPConnection

            host, _, port = self.netloc.partition(":")
            try:
                conn = _RawHTTPConnection(host, int(port or 80), timeout)
            except OSError as e2:
                out_q.put((self.tag, e2, 0, None, None))
                return
            with self.lock:
                if self.cancelled:
                    conn.close()
                    out_q.put((self.tag, e, 0, None, None))
                    return
                self.conn = conn
            try:
                conn.send_request("GET", path, None, headers)
                status, rheaders, body, will_close = conn.read_response("GET")
            except (OSError, http.client.HTTPException) as e2:
                conn.close()
                out_q.put((self.tag, e2, 0, None, None))
                return
        with self.lock:
            if self.cancelled:
                conn.close()
                out_q.put((self.tag, OSError("hedge attempt cancelled"),
                           0, None, None))
                return
            self.finished = True
        if will_close:
            conn.close()
        else:
            _POOL.checkin(self.netloc, conn)
        out_q.put((self.tag, None, status, rheaders, body))


TRACKER = LatencyTracker()


def download(
    urls: list[str],
    key=None,
    timeout: float = 30.0,
    stats: dict | None = None,
    eager: bool = False,
) -> tuple[bytes, dict]:
    """GET `urls[0]`, hedging to `urls[1]` after the adaptive delay.

    `urls` are scheme-less "host:port/fid" replica targets (healthiest
    first — callers order them through the vid_map circuit breaker).
    `key` buckets the latency history (pass the volume id). `stats`, if
    given, collects {"fired","won","cancelled"} increments for callers
    that report their own counts (weedload workers). `eager` fires the
    hedge IMMEDIATELY instead of waiting the adaptive delay — the
    health plane's lever (docs/HEALTH.md) when the primary candidate
    is a master-flagged suspect: waiting a p95 against a gray node
    just donates the delay to the tail. Returns (body, headers) like
    client.operation.download; raises HTTPError on an error status and
    OSError when every replica fails."""
    from seaweedfs_tpu.client import operation as op

    if len(urls) < 2 or not qos.enabled("hedge"):
        return op.download(urls[0], timeout=timeout)
    import time as _time

    # deadline plane (docs/CHAOS.md): the hedge race runs on the SAME
    # clock as everything else — the driver's overall timeout shrinks
    # to the request's remaining budget (raising DeadlineExceeded when
    # none is left, before any attempt fires), and each attempt's
    # request carries the hop header so a server can fast-reject work
    # the caller already gave up on
    from seaweedfs_tpu.util import deadline as _dl_mod

    dl = _dl_mod.effective(None)
    if dl is not None:
        timeout = dl.cap(timeout)
    if key is None:
        # fid "vid,..." → vid buckets the latency history
        tail = urls[0].partition("/")[2]
        key = tail.partition(",")[0]
    out_q: queue.Queue = queue.Queue()
    with trace.span("qos.hedge", plane="serve") as sp:
        base_headers: dict = {}
        trace.inject(base_headers)
        _dl_mod.stamp(base_headers, dl)
        primary = _Attempt(0, urls[0])
        attempts = [primary]
        _ATTEMPTS.submit(primary.run, base_headers, timeout, out_q)

        def fire_hedge():
            # the second (tied) attempt: hop header stamped, counted as
            # fired whether the trigger was the elapsed delay or an
            # outright primary failure (so won <= fired always holds)
            HEDGE_FIRED.inc()
            if stats is not None:
                stats["fired"] = stats.get("fired", 0) + 1
            sp.annotate("hedged", 1)
            h2 = dict(base_headers)
            h2[qos.HEDGE_HEADER] = "1"
            second = _Attempt(1, urls[1])
            attempts.append(second)
            _ATTEMPTS.submit(second.run, h2, timeout, out_q)

        delay = 0.0 if eager else TRACKER.delay_s(key)
        t0 = _time.perf_counter()
        hedged = False
        deadline = t0 + timeout
        result = None  # (tag, status, headers, body)
        last_err: Exception | None = None
        saw_redirect = False
        while result is None:
            now = _time.perf_counter()
            if now >= deadline:
                break
            if not hedged:
                wait = min(delay - (now - t0), deadline - now)
            else:
                wait = deadline - now
            if wait > 0:
                try:
                    tag, err, status, rheaders, body = out_q.get(timeout=wait)
                except queue.Empty:
                    if hedged:
                        break
                    tag = None
            else:
                tag = None
            if tag is None:
                if hedged:
                    continue
                # adaptive delay elapsed with no answer: fire the hedge
                hedged = True
                fire_hedge()
                continue
            if err is not None or status >= 300:
                if err is not None:
                    last_err = err
                    _vm.note_failure(attempts[tag].netloc)
                else:
                    last_err = urllib.error.HTTPError(
                        f"http://{attempts[tag].url}", status,
                        f"HTTP {status}", rheaders, io.BytesIO(body),
                    )
                    if 300 <= status < 400:
                        saw_redirect = True
                attempts[tag].finished = True
                if len(attempts) == 1:
                    # primary failed outright: go straight to replica 2
                    hedged = True
                    fire_hedge()
                elif all(a.finished or a.cancelled for a in attempts):
                    break
                continue
            result = (tag, status, rheaders, body)
        # cancel whichever attempt lost (or still runs on timeout)
        for a in attempts:
            if result is None or a.tag != result[0]:
                if a.cancel():
                    HEDGE_CANCELLED.inc()
                    if stats is not None:
                        stats["cancelled"] = stats.get("cancelled", 0) + 1
        if result is None:
            if saw_redirect:
                # volume read-redirect (a `-readRedirect` server 302s
                # when its location map says the volume moved): the
                # hedge driver doesn't chase redirects across attempt
                # threads — if ANY replica pointed elsewhere (not just
                # the last to answer; a stale peer's 404 may land
                # after the 302), hand the read to the pooled
                # single-attempt path, which follows redirects like
                # the pre-hedge code did
                return op.download(urls[0], timeout=timeout)
            raise last_err if last_err is not None else OSError(
                f"hedged read of {urls[0]} timed out"
            )
        tag, status, rheaders, body = result
        if tag == 1:
            HEDGE_WON.inc()
            if stats is not None:
                stats["won"] = stats.get("won", 0) + 1
            sp.annotate("hedge_won", 1)
        _vm.note_success(attempts[tag].netloc)
        # adaptive-delay feedback. A hedged completion is a CENSORED
        # observation: the primary was abandoned at `delay`, so the
        # winner's total (≈ delay + hedge RTT) says nothing about the
        # un-truncated service-time distribution — recording it raw
        # ratchets the p95 upward by one hedge RTT per hedge (each new
        # delay re-truncates the distribution just above itself).
        # Record hedged wins AT the censoring point and unhedged
        # completions at their true latency: the quantile then tracks
        # the volume's real service tail and the delay has a fixpoint.
        sample = _time.perf_counter() - t0
        if not eager:
            # eager races (suspect primary) say nothing about the
            # volume's normal service tail — recording their min(·, 0)
            # would poison the ring with zeros
            TRACKER.record(key, min(sample, delay) if hedged else sample)
        return body, rheaders
