"""QoS / tail-latency plane (docs/QOS.md).

Four defenses for p99.9 under heavy multi-tenant contention, each
individually switchable and all killable at once:

  * hedged replica reads with an adaptive per-volume delay (hedge.py)
  * per-client admission control at the serving edge (admission.py)
  * group commit on the volume write path (group_commit.py)
  * queue-depth-aware write assignment in the master (the volume
    servers report in-flight/queue depth on heartbeats; the master's
    pick-for-write runs power-of-two-choices over them)

`WEED_QOS=0` restores pre-QoS behavior wholesale; the per-feature
switches (`WEED_QOS_HEDGE`, `WEED_QOS_ADMISSION`, `WEED_QOS_COMMIT`,
`WEED_QOS_ASSIGN`) flip one defense at a time for A/B runs. Env vars
are read per call so a test (or an operator restarting one daemon) can
flip a feature without touching module import order.
"""

from __future__ import annotations

import os
import threading

# the hedge hop header: the client stamps it on the SECOND (hedged)
# attempt so servers can tell tied reads apart from first attempts —
# they count them (weed_hedge_served_total) and annotate the span, and
# the loser's teardown (client closes the socket) is how "drop the
# loser" reaches the server
HEDGE_HEADER = "x-weed-hedge"


def _feature_reads() -> dict[str, str]:
    """Literal per-feature env reads (one os.environ.get per name, so
    the weedlint contract tier can cross-check each knob against the
    OPERATIONS.md table — an f-string composed name would be invisible
    to it)."""
    return {
        "hedge": os.environ.get("WEED_QOS_HEDGE", "1"),
        "admission": os.environ.get("WEED_QOS_ADMISSION", "1"),
        "commit": os.environ.get("WEED_QOS_COMMIT", "1"),
        "assign": os.environ.get("WEED_QOS_ASSIGN", "1"),
    }


def enabled(feature: str = "") -> bool:
    """True when the QoS plane (and, if given, `feature`) is on.
    feature ∈ {"hedge", "admission", "commit", "assign"}."""
    if os.environ.get("WEED_QOS", "1") == "0":
        return False
    if feature:
        return _feature_reads()[feature] != "0"
    return True


class LoadTracker:
    """In-flight request counter for one serving process.

    The mini request loop (util/httpd.serve_connection) enters/exits
    around each dispatch when the server installs one of these; the
    volume server ships the current value to the master on every
    heartbeat (in_flight_requests) so pick-for-write can weigh nodes by
    live load, not just volume counts.

    Also counts 5xx responses served (the funnel calls note_error);
    the cumulative total rides heartbeats as `request_errors`, feeding
    the master health plane's per-node error EWMA (docs/HEALTH.md)."""

    __slots__ = ("_n", "_errors", "_lock")

    def __init__(self) -> None:
        self._n = 0
        self._errors = 0
        self._lock = threading.Lock()

    def enter(self) -> None:
        with self._lock:
            self._n += 1

    def exit(self) -> None:
        with self._lock:
            self._n -= 1

    def note_error(self) -> None:
        with self._lock:
            self._errors += 1

    def errors(self) -> int:
        with self._lock:
            return self._errors

    def inflight(self) -> int:
        with self._lock:
            return self._n


__all__ = ["HEDGE_HEADER", "LoadTracker", "enabled"]
