"""Singleflight leases: N concurrent requests for one cold key, ONE
worker doing the expensive fill.

Extracted from EcVolume's degraded-read tile decode (the inline
dict-of-Events idiom PR 12 landed) so the registrant-handoff protocol
has one home, one test surface, and one unit under the weedrace
schedule enumerator (analysis/race.py run_singleflight). The protocol:

  * lead(key) registers this thread as the key's leader and returns a
    lease, or returns None when another leader is already in flight;
  * followers wait(key) on the leader's lease, then re-probe whatever
    cache the leader was filling — a miss after the wakeup means the
    leader FAILED (or its result was already evicted), and the
    follower self-serves rather than waiting forever;
  * release(key, lease) unregisters the lease and wakes every waiter.
    Release is owner-checked: a lease can only remove itself, so a
    stale release (leader that already timed out a follower's patience
    and was replaced) cannot evict a successor's registration.

The contract the race enumerator asserts: at most one live lease per
key, every follower wakes, and no lease outlives its run (a leaked
lease would wedge every later request for the key into the wait
path's timeout).
"""

from __future__ import annotations

import threading


class SingleFlight:
    """dict-of-Events registrant handoff; all methods thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._leases: dict = {}

    def lead(self, key) -> threading.Event | None:
        """Try to become `key`'s leader: the returned lease (an Event)
        must later be passed to release(). None = someone else leads;
        wait() on them and re-probe."""
        with self._lock:
            if key in self._leases:
                return None
            ev = threading.Event()
            self._leases[key] = ev
            return ev

    def wait(self, key, timeout: float | None = None) -> bool:
        """Block until `key`'s current leader releases (True), the
        timeout lapses (False), or there is no leader at all (True —
        the fill already finished or never started; probe and
        self-serve)."""
        with self._lock:
            ev = self._leases.get(key)
        if ev is None:
            return True
        return ev.wait(timeout)

    def release(self, key, lease: threading.Event) -> None:
        """Unregister `lease` and wake its waiters. Owner-checked: only
        the exact registered lease unregisters, so a late release never
        evicts a successor leader's registration (its waiters still get
        woken — they re-probe, the universal recovery move)."""
        with self._lock:
            if self._leases.get(key) is lease:
                del self._leases[key]
        lease.set()

    def inflight(self) -> int:
        """Outstanding lease count (test/status surface)."""
        with self._lock:
            return len(self._leases)
