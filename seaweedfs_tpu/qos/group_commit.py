"""Group commit for the volume write path (docs/QOS.md).

Concurrent POSTs against one volume coalesce into a commit window: the
first writer becomes the window's leader, waits up to `-commitWindowUs`
(or until the byte/batch cap fills) for riders, then commits the whole
batch through Volume.write_needles — ONE pwritev and at most ONE fsync
where N serial writes paid N of each. Results are byte-identical per
request by construction (write_needles runs the serial path's checks
and encodes at the serial path's offsets, in arrival order).

The C POST fast path declines to Python while a committer is active
(server/write_path.try_native_post is skipped) — the C hot loop's
one-call append can't ride a window, and batching is the bigger win
under the concurrency that makes windows fill.

`WEED_QOS=0` / `WEED_QOS_COMMIT=0` (or `-commitWindowUs 0`) restores
today's write-per-POST behavior wholesale; `-commitFsync` alone keeps
per-POST durability without batching (the A/B baseline the
fsyncs-per-POST bench ratio compares against).
"""

from __future__ import annotations

import threading

from seaweedfs_tpu import qos
from seaweedfs_tpu.stats.metrics import (
    GROUP_COMMIT_BATCHES,
    GROUP_COMMIT_WRITES,
)


class _Entry:
    __slots__ = ("needle", "stages", "done", "result")

    def __init__(self, needle, stages):
        self.needle = needle
        self.stages = stages
        self.done = threading.Event()
        self.result = None


class _Batch:
    __slots__ = ("entries", "nbytes", "full", "closed")

    def __init__(self):
        self.entries: list[_Entry] = []
        self.nbytes = 0
        self.full = threading.Event()
        self.closed = False


class GroupCommitter:
    def __init__(
        self,
        window_us: int = 500,
        max_bytes: int = 4 << 20,
        max_batch: int = 64,
        fsync: bool = False,
    ):
        self.window_us = window_us
        self.max_bytes = max_bytes
        self.max_batch = max_batch
        self.fsync = fsync
        self._lock = threading.Lock()
        self._open: dict[int, _Batch] = {}  # vid -> accepting batch

    # ------------------------------------------------------------------
    def active(self) -> bool:
        """Whether writes should route through the committer at all —
        also what makes the C POST fast path decline to Python."""
        return self.window_us > 0 and qos.enabled("commit")

    def depth(self) -> int:
        """Writes currently queued in open windows (the heartbeat's
        write_queue_depth field)."""
        with self._lock:
            return sum(len(b.entries) for b in self._open.values())

    # ------------------------------------------------------------------
    def write(self, volume, needle, stages: dict | None = None):
        """The write seam: returns (offset, size, unchanged) exactly
        like Volume.write_needle, raising the same exceptions."""
        if not self.active():
            res = volume.write_needle(needle, stages=stages)
            if self.fsync:
                volume.commit()
            return res
        entry = _Entry(needle, stages)
        with self._lock:
            batch = self._open.get(volume.id)
            leader = batch is None or batch.closed
            if leader:
                batch = _Batch()
                self._open[volume.id] = batch
            batch.entries.append(entry)
            batch.nbytes += len(needle.data or b"")
            if (
                len(batch.entries) >= self.max_batch
                or batch.nbytes >= self.max_bytes
            ):
                batch.full.set()
        if leader:
            self._commit(volume, batch)
        else:
            # the leader always signals every rider (even on error); the
            # long timeout is a belt against a leader thread dying to
            # something unhandled — surface loudly rather than hang
            if not entry.done.wait(timeout=60.0):
                raise RuntimeError(
                    f"group commit window for volume {volume.id} never "
                    "committed (leader died?)"
                )
        if isinstance(entry.result, BaseException):
            raise entry.result
        return entry.result

    def _commit(self, volume, batch: _Batch) -> None:
        batch.full.wait(self.window_us / 1e6)
        with self._lock:
            batch.closed = True
            if self._open.get(volume.id) is batch:
                del self._open[volume.id]
            entries = list(batch.entries)
        try:
            outcomes = volume.write_needles(
                [(e.needle, e.stages) for e in entries],
                durable=self.fsync,
            )
        except BaseException as e:  # noqa: BLE001 — fan the error out
            for en in entries:
                en.result = e
                en.done.set()
            raise
        GROUP_COMMIT_BATCHES.inc()
        GROUP_COMMIT_WRITES.inc(len(entries))
        for en, out in zip(entries, outcomes):
            en.result = out
            en.done.set()
