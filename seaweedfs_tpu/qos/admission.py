"""Per-client admission control at the serving edge (docs/QOS.md).

A token bucket per client key (S3 access key when the request carries
one, else the remote address) plus a process-wide in-flight cap. Over
budget → shed with 503 + Retry-After and the
weed_admission_rejected_total counter: backpressure instead of
collapse, and the client's `op.http_call` honors the Retry-After with
jitter so well-behaved tenants converge on their fair share.

The check runs inside the mini request loop's dispatch funnel
(util/httpd.serve_connection) — the one place every serving daemon's
requests pass through, including connections the C epoll loop hands
off — so shed requests still get spans, status-labelled request
counters, and correct keep-alive accounting for free.

`-serveProcs` process groups: with `shm_path` set, every sibling
charges ONE shared-memory GCRA bucket per client key (mmap'd file,
lock-free CAS — native/serve.c weed_shm_admit), so the GLOBAL rate
holds under arbitrarily skewed connection spread, and the C epoll
loop sheds over-budget requests without leaving C. Without it, each
sibling runs its own controller at rate/N — exact only when the
kernel spreads connections uniformly across SO_REUSEPORT listeners.
"""

from __future__ import annotations

import threading
import time

from seaweedfs_tpu import qos
from seaweedfs_tpu.stats.metrics import ADMISSION_REJECTED

_MAX_BUCKETS = 4096


def client_key(handler) -> str:
    """The admission identity of one request: the S3 access key when an
    Authorization header carries one (AWS4-HMAC-SHA256 Credential=KEY/…
    or the legacy `AWS KEY:sig`), else the remote address."""
    auth = handler.headers.get("authorization", "") if handler.headers else ""
    if auth:
        if auth.startswith("AWS4-HMAC-SHA256"):
            idx = auth.find("Credential=")
            if idx >= 0:
                cred = auth[idx + len("Credential="):]
                return cred.split("/", 1)[0].strip()
        elif auth.startswith("AWS "):
            return auth[4:].split(":", 1)[0].strip()
    addr = getattr(handler, "client_address", None)
    return addr[0] if addr else "unknown"


class AdmissionController:
    """admit(key) → None (admitted) or a Retry-After float (shed)."""

    def __init__(
        self,
        rate: float,
        burst: float = 0.0,
        max_inflight: int = 0,
        procs: int = 1,
        label: str = "server",
        retry_after_s: float = 1.0,
        shm_path: str = "",
    ):
        procs = max(1, procs)
        # shared-bucket mode (this PR): every `-serveProcs`/`-workers`
        # sibling charges ONE mmap'd GCRA bucket per client key, so the
        # GLOBAL rate holds even when the kernel parks every connection
        # on one listener. No rate/N split; the in-flight cap stays
        # process-local (queue length is a per-process resource). The
        # C epoll loop enforces the same bucket natively when it serves
        # a request without handing off.
        self.shared = False
        self.shm_path = shm_path
        if shm_path and rate > 0:
            from seaweedfs_tpu.util import native_serve

            try:
                self.shared = native_serve.admission_shm_attach(
                    shm_path,
                    rate,
                    max(rate, burst or 2.0 * rate),
                    retry_after_s,
                )
            except OSError:
                self.shared = False  # fall back to the per-process split
        if self.shared:
            self.rate = rate
            self.burst = max(rate, burst or 2.0 * rate)
        else:
            # per-process share of the GLOBAL per-client budget
            self.rate = rate / procs
            self.burst = max(self.rate, (burst or 2.0 * rate) / procs)
        self.max_inflight = max_inflight
        self.label = label
        self.retry_after_s = retry_after_s
        self._lock = threading.Lock()
        self._buckets: dict[str, tuple[float, float]] = {}  # key -> (tokens, ts)
        self._inflight = 0
        self.rejected = 0  # process-local count (operator surfaces)

    # ------------------------------------------------------------------
    def admit(self, key: str, now: float | None = None) -> float | None:
        """Charge one token against `key`'s bucket; returns None when
        admitted, else the seconds the client should wait (Retry-After).
        The in-flight cap sheds regardless of key — queue length is a
        process-wide resource."""
        return self._admit_enter(key, now=now, enter=False)[0]

    def _admit_enter(
        self, key: str, now: float | None = None, enter: bool = True
    ) -> tuple[float | None, bool]:
        """(retry_after | None, entered). With `enter`, an admitted
        request is counted into the in-flight total INSIDE the same
        lock hold as the cap check — a separate check-then-increment
        window would let a simultaneous burst of N threads all pass a
        cap of 2 before any of them counted. `entered` tells the caller
        whether an _exit() is owed: the env kill switches are read per
        call, so a flip mid-request must not make the finally-side
        decrement underflow the counter (and silently widen the cap)."""
        if not qos.enabled("admission"):
            return None, False
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self.max_inflight and self._inflight >= self.max_inflight:
                self.rejected += 1
                ADMISSION_REJECTED.labels(self.label).inc()
                return self.retry_after_s, False
            if self.shared:
                from seaweedfs_tpu.util import native_serve

                # one CAS against the mmap'd bucket all siblings share;
                # the retry value already carries the retry_after floor
                retry = native_serve.admission_shm_admit(key)
                if retry > 0.0:
                    self.rejected += 1
                    ADMISSION_REJECTED.labels(self.label).inc()
                    return retry, False
            elif self.rate > 0:
                tokens, ts = self._buckets.get(key, (self.burst, now))
                tokens = min(self.burst, tokens + (now - ts) * self.rate)
                if tokens < 1.0:
                    self._buckets[key] = (tokens, now)
                    self.rejected += 1
                    ADMISSION_REJECTED.labels(self.label).inc()
                    # time until one whole token refills
                    return (
                        max(self.retry_after_s, (1.0 - tokens) / self.rate),
                        False,
                    )
                self._buckets[key] = (tokens - 1.0, now)
                if len(self._buckets) > _MAX_BUCKETS:
                    self._evict(now)
            if enter:
                self._inflight += 1
        return None, enter

    def _exit(self) -> None:
        with self._lock:
            self._inflight -= 1

    def _evict(self, now: float) -> None:
        # drop the stalest half by last-touch; called under the lock
        items = sorted(self._buckets.items(), key=lambda kv: kv[1][1])
        for k, _ in items[: len(items) // 2]:
            del self._buckets[k]

    # ------------------------------------------------------------------
    # dispatch gate: serve_connection wraps the routed do_* method with
    # this so shed requests reply through the SAME traced/metered path
    def gate(self, method, handler):
        retry, entered = self._admit_enter(client_key(handler))
        if retry is None:
            try:
                return method(handler)
            finally:
                if entered:
                    self._exit()
        return self._shed(handler, retry)

    def _shed(self, handler, retry: float) -> None:
        handler.fast_reply(
            503,
            b'{"error": "admission control: over per-client budget"}',
            {
                "Content-Type": "application/json",
                "Retry-After": f"{retry:.3f}",
            },
        )

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def status(self) -> dict:
        with self._lock:
            return {
                "RatePerProc": self.rate,
                "BurstPerProc": self.burst,
                "MaxInflight": self.max_inflight,
                "Inflight": self._inflight,
                "Clients": len(self._buckets),
                "Rejected": self.rejected,
                "Shared": self.shared,
                "ShmPath": self.shm_path if self.shared else "",
            }
