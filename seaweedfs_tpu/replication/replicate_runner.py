"""`weed filer.replicate` — consume the notification queue and drive a
Replicator (weed/command/filer_replicate.go runFilerReplicate)."""

from __future__ import annotations

import os
import time

from seaweedfs_tpu.replication.replicator import Replicator
from seaweedfs_tpu.replication.sink import FilerSink, LocalSink, S3Sink
from seaweedfs_tpu.replication.source import FilerSource
from seaweedfs_tpu.scrub.arbiter import get_arbiter
from seaweedfs_tpu.stats.metrics import REPLICATION_APPLIED, REPLICATION_LAG
from seaweedfs_tpu.util import wlog
from seaweedfs_tpu.util import durable
from seaweedfs_tpu.util.config import load_config, Configuration


def repl_enabled() -> bool:
    """`WEED_REPL=0` kills the replication consumer wholesale: the
    runner exits without draining. The durable queue keeps absorbing
    filer events, so flipping the switch back on resumes from the
    committed cursor — lag, not loss."""
    return os.environ.get("WEED_REPL", "1") != "0"


def build_replicator(repl_cfg: Configuration) -> Replicator:
    src = repl_cfg.sub("source.filer")
    source = FilerSource(
        src.get("grpcAddress", "localhost:8888"),
        directory=src.get("directory", "/buckets"),
    )
    if repl_cfg.get_bool("sink.filer.enabled"):
        s = repl_cfg.sub("sink.filer")
        sink = FilerSink(
            s.get("grpcAddress", "localhost:8888"),
            directory=s.get("directory", "/backup"),
            replication=s.get("replication", ""),
            collection=s.get("collection", ""),
            ttl_sec=int(s.get("ttlSec", 0)),
        )
    elif repl_cfg.get_bool("sink.local.enabled"):
        sink = LocalSink(repl_cfg.sub("sink.local").get("directory", "/tmp/backup"))
    elif repl_cfg.get_bool("sink.s3.enabled"):
        s = repl_cfg.sub("sink.s3")
        sink = S3Sink(
            s.get("endpoint", "localhost:8333"),
            s.get("bucket", "backup"),
            access_key=s.get("aws_access_key_id", ""),
            secret_key=s.get("aws_secret_access_key", ""),
            directory=s.get("directory", ""),
            region=s.get("region", "us-east-1"),
        )
    elif repl_cfg.get_bool("sink.gcs.enabled"):
        from seaweedfs_tpu.replication.cloud_sinks import GcsSink

        s = repl_cfg.sub("sink.gcs")
        endpoint = s.get("endpoint", "https://storage.googleapis.com")
        if not s.get("token", "") and "googleapis.com" in endpoint:
            # real GCS always needs a bearer token; only custom
            # endpoints (emulators, the test fake) may go tokenless
            raise RuntimeError(
                "sink.gcs needs an OAuth bearer `token` (see "
                "replication/cloud_sinks.py), or a custom `endpoint`"
            )
        sink = GcsSink(
            s.get("bucket", ""),
            token=s.get("token", ""),
            directory=s.get("directory", ""),
            endpoint=endpoint,
        )
    elif repl_cfg.get_bool("sink.azure.enabled"):
        from seaweedfs_tpu.replication.cloud_sinks import AzureSink

        s = repl_cfg.sub("sink.azure")
        if not s.get("account_key", ""):
            raise RuntimeError(
                "sink.azure needs account_name/account_key (the SharedKey "
                "credentials); see replication/cloud_sinks.py"
            )
        sink = AzureSink(
            s.get("account_name", ""),
            s.get("account_key", ""),
            s.get("container", ""),
            directory=s.get("directory", ""),
            endpoint=s.get("endpoint", ""),
        )
    elif repl_cfg.get_bool("sink.backblaze.enabled"):
        from seaweedfs_tpu.replication.cloud_sinks import B2Sink

        s = repl_cfg.sub("sink.backblaze")
        if not s.get("b2_master_application_key", ""):
            raise RuntimeError(
                "sink.backblaze needs b2_account_id/"
                "b2_master_application_key; see replication/cloud_sinks.py"
            )
        sink = B2Sink(
            s.get("b2_account_id", ""),
            s.get("b2_master_application_key", ""),
            s.get("bucket", ""),
            directory=s.get("directory", ""),
            endpoint=s.get("endpoint", "https://api.backblazeb2.com"),
        )
    else:
        raise RuntimeError("no enabled sink in replication.toml")
    return Replicator(source, sink)


def run_replicate(
    config_path: str = "",
    poll_interval: float = 1.0,
    stop_after_idle: float = 0.0,
) -> int:
    """Consume the configured durable queue (dirqueue, or the
    partitioned logqueue with consumer group "replicate") and replicate
    each event; offsets are checkpointed so restarts resume.
    stop_after_idle > 0 makes the loop exit after that many idle
    seconds (tests / one-shot drains)."""
    if not repl_enabled():
        # kill switch (docs/TIERING.md): events keep accumulating in
        # the durable queue; re-enabling resumes from the committed
        # cursor with nothing lost
        wlog.warning("filer.replicate disabled (WEED_REPL=0); exiting")
        return 0
    if config_path:
        from seaweedfs_tpu.util.config import tomllib  # 3.10 fallback parser

        with open(config_path, "rb") as f:
            repl_cfg = Configuration(tomllib.load(f))
    else:
        repl_cfg = load_config("replication", required=True)
    notif_cfg = load_config("notification", required=False)

    from seaweedfs_tpu import notification

    replicator = build_replicator(repl_cfg)
    if notif_cfg.get_bool("notification.logqueue.enabled"):
        from seaweedfs_tpu.notification.logqueue import PartitionedLogQueue

        qdir = notif_cfg.get_string("notification.logqueue.dir", "./notifications")
        lq = PartitionedLogQueue(
            qdir,
            partitions=notif_cfg.get_int("notification.logqueue.partitions", 4),
        )
        wlog.info(
            "filer.replicate consuming logqueue %s (lag %d)",
            qdir,
            lq.depth("replicate"),
        )
        return _consume_logqueue(
            lq, replicator, poll_interval, stop_after_idle
        )
    if notif_cfg.get_bool("notification.kafka.enabled"):
        from seaweedfs_tpu.notification.kafka import KafkaSubscriber

        hosts = notif_cfg.get_string("notification.kafka.hosts", "localhost:9092")
        sub = KafkaSubscriber(
            hosts,
            topic=notif_cfg.get_string(
                "notification.kafka.topic", "seaweedfs_filer"
            ),
        )
        adapter = _KafkaOffsetAdapter(
            sub,
            notif_cfg.get_string(
                "notification.kafka.offset_dir", "./kafka_offsets"
            ),
        )
        wlog.info("filer.replicate consuming kafka %s", hosts)
        return _consume_logqueue(adapter, replicator, poll_interval, stop_after_idle)
    qdir = notif_cfg.get_string("notification.dirqueue.dir", "./notifications")
    dirqueue = notification.DirQueue(qdir)
    offset_file = os.path.join(qdir, ".replicate_offset")
    after = 0
    if os.path.exists(offset_file):
        with open(offset_file) as f:
            after = int(f.read().strip() or "0")
    idle_since = time.time()
    wlog.info("filer.replicate consuming %s from seq %d", qdir, after)
    while True:
        progressed = False
        for seq, key, msg in dirqueue.consume(after_seq=after):
            try:
                replicator.replicate(key, msg)
            except Exception as e:  # noqa: BLE001 — keep consuming
                wlog.error("replicate %s: %s", key, e)
            after = seq
            with open(offset_file, "w") as f:
                f.write(str(after))
            progressed = True
        if progressed:
            idle_since = time.time()
        elif stop_after_idle and time.time() - idle_since > stop_after_idle:
            return 0
        else:
            time.sleep(poll_interval)


class _KafkaOffsetAdapter:
    """Present a KafkaSubscriber through the logqueue consumer surface
    (poll/commit/trim) so the at-least-once drain loop below serves
    both. Offsets are durable on the consumer side (one file per
    partition, atomic replace) — the reference's sarama consumer keeps
    them broker-side via group coordination, which kafka.py
    deliberately omits (single subscriber per topic; see its module
    docstring)."""

    def __init__(self, sub, offset_dir: str):
        self._sub = sub
        self._dir = offset_dir
        os.makedirs(offset_dir, exist_ok=True)
        for p in sub.partitions:
            try:
                with open(os.path.join(offset_dir, f"p{p:03d}")) as f:
                    sub.offsets[p] = int(f.read().strip() or "0")
            except (OSError, ValueError):
                pass

    def poll(self, group: str, max_records: int = 256):
        return self._sub.poll(max_records)

    def commit(self, group: str, partition: int, next_offset: int) -> None:
        self._sub.commit(partition, next_offset)
        path = os.path.join(self._dir, f"p{partition:03d}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(next_offset))
        # same contract as logqueue.commit: lost = re-replicate (safe,
        # idempotent PUTs), torn = parse failure on restart
        durable.publish(tmp, path)

    def trim(self) -> int:
        return 0  # retention is the broker's concern


_MAX_EVENT_RETRIES = 8


def _consume_logqueue(lq, replicator, poll_interval, stop_after_idle) -> int:
    """Drain loop over the partitioned log: poll → replicate →
    commit-per-partition, then trim consumed segments.

    At-least-once for real: a failed event does NOT advance its
    partition's committed offset — the next poll() re-delivers from the
    last success, preserving per-partition order behind the failure.
    After _MAX_EVENT_RETRIES redeliveries the event is declared poison
    and skipped (committed past) so one bad event can't wedge its
    partition forever."""
    group = "replicate"
    idle_since = time.time()
    retries: dict[tuple[int, int], int] = {}  # (partition, offset) → attempts

    def _sample_lag() -> None:
        # lag = events the producer wrote that this consumer hasn't
        # committed past; surfaced on /metrics for the telemetry
        # collector's RULE_REPL_LAG alert (the Kafka adapter has no
        # cheap depth — it just doesn't report)
        depth = getattr(lq, "depth", None)
        if callable(depth):
            try:
                REPLICATION_LAG.set(depth(group), group)
            except OSError:
                pass

    while True:
        batch = lq.poll(group)
        _sample_lag()
        if batch:
            high: dict[int, int] = {}
            stalled: set[int] = set()
            for part, offset, key, msg in batch:
                if part in stalled:
                    continue  # order: nothing commits past the failure
                # cross-cluster apply traffic pays the bandwidth
                # arbiter: max-min share against rebuild/handoff/tier,
                # yielding to foreground serving (docs/TIERING.md)
                get_arbiter().take(
                    "replication", max(msg.ByteSize(), 1)
                )
                try:
                    replicator.replicate(key, msg)
                except Exception as e:  # noqa: BLE001 — redeliver next poll
                    attempts = retries.get((part, offset), 0) + 1
                    if attempts >= _MAX_EVENT_RETRIES:
                        wlog.error(
                            "replicate %s: %s — poison after %d attempts, skipping",
                            key, e, attempts,
                        )
                        retries.pop((part, offset), None)
                        high[part] = offset + 1  # give up: commit past it
                        REPLICATION_APPLIED.labels("skipped").inc()
                    else:
                        wlog.error(
                            "replicate %s: %s (attempt %d; partition %d "
                            "redelivers from offset %d)",
                            key, e, attempts, part, offset,
                        )
                        retries[(part, offset)] = attempts
                        stalled.add(part)
                        REPLICATION_APPLIED.labels("error").inc()
                    continue
                retries.pop((part, offset), None)
                high[part] = offset + 1
                REPLICATION_APPLIED.labels("ok").inc()
            for part, next_off in high.items():
                lq.commit(group, part, next_off)
            lq.trim()
            _sample_lag()
            if high:
                idle_since = time.time()
            if stalled:
                if stop_after_idle and time.time() - idle_since > stop_after_idle:
                    return 1  # stuck on failures, not idle: nonzero
                time.sleep(poll_interval)  # backoff before redelivery
        elif stop_after_idle and time.time() - idle_since > stop_after_idle:
            return 0
        else:
            time.sleep(poll_interval)
