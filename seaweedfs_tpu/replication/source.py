"""Replication source: reads chunk bytes out of the source cluster.

Behavioral match of weed/replication/source/filer_source.go: given a
chunk fid, look its volume up through the source filer's LookupVolume
and fetch the blob from a volume server."""

from __future__ import annotations

import grpc

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.pb import filer_pb2 as fpb, rpc


class FilerSource:
    def __init__(self, grpc_address: str, directory: str = "/"):
        # grpc_address is "host:httpPort" — the +10000 convention applies
        self.filer = grpc_address
        self.dir = directory.rstrip("/") or "/"
        self._channel: grpc.Channel | None = None

    def _stub(self):
        if self._channel is None:
            self._channel = rpc.dial(rpc.grpc_address(self.filer))
        return rpc.filer_stub(self._channel)

    def lookup_file_url(self, fid: str) -> str:
        vid = fid.split(",")[0]
        resp = self._stub().LookupVolume(fpb.LookupVolumeRequest(volume_ids=[vid]))
        locs = resp.locations_map.get(vid)
        if locs is None or not locs.locations:
            raise RuntimeError(f"volume {vid} not found via filer {self.filer}")
        return f"{locs.locations[0].url}/{fid}"

    def read_chunk(self, fid: str) -> bytes:
        data, _ = op.download(self.lookup_file_url(fid))
        return data

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()
