"""Event → sink dispatch.

Behavioral match of weed/replication/replicator.go:34-60: map the
source key into the sink directory, then route by (old, new) presence:
delete / create / update-with-create-fallback."""

from __future__ import annotations

from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.replication.sink import ReplicationSink
from seaweedfs_tpu.replication.source import FilerSource
from seaweedfs_tpu.util import wlog


class Replicator:
    def __init__(self, source: FilerSource, sink: ReplicationSink):
        self.source = source
        self.sink = sink
        sink.set_source_filer(source)

    def replicate(self, key: str, message: fpb.EventNotification) -> None:
        src_dir = self.source.dir
        if src_dir != "/" and not key.startswith(src_dir):
            wlog.V(4).info("skipping %s outside of %s", key, src_dir)
            return
        suffix = key[len(src_dir):] if src_dir != "/" else key
        new_key = (self.sink.get_sink_to_directory().rstrip("/") + suffix) or suffix

        has_old = bool(message.old_entry.name) or message.old_entry.is_directory
        has_new = bool(message.new_entry.name) or message.new_entry.is_directory
        if has_old and not has_new:
            self.sink.delete_entry(
                new_key, message.old_entry.is_directory, message.delete_chunks
            )
            return
        if has_new and not has_old:
            self.sink.create_entry(new_key, message.new_entry)
            return
        if not has_old and not has_new:
            wlog.warning("weird empty event for %s", key)
            return
        found = self.sink.update_entry(
            new_key,
            message.old_entry,
            message.new_parent_path,
            message.new_entry,
            message.delete_chunks,
        )
        if not found:
            # existing entry not at the sink yet: fall back to create
            # (replicator.go:56-60)
            self.sink.create_entry(new_key, message.new_entry)
