"""Replication sinks.

Behavioral match of weed/replication/sink/replication_sink.go (the
ReplicationSink interface: CreateEntry / UpdateEntry / DeleteEntry /
GetSinkToDirectory) with three concrete sinks:

* FilerSink — writes into a destination filer over gRPC, re-uploading
  every chunk through the destination cluster's AssignVolume + volume
  POST (sink/filersink/filer_sink.go + fetch_write.go). Chunk fids are
  cluster-local, so bytes always re-upload; the new chunk records the
  source fid for dedup-aware updates.
* LocalSink — materializes entries as plain files under a local
  directory.
* S3Sink — writes whole objects into any S3-compatible endpoint via
  the in-repo SigV4 client (sink/s3sink/s3_sink.go, minus the aws-sdk:
  gcs/azure/b2 remain gated since their SDKs are not in this image).
"""

from __future__ import annotations

import os

import grpc

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.pb import filer_pb2 as fpb, rpc
from seaweedfs_tpu.replication.source import FilerSource
from seaweedfs_tpu.util import wlog


class ReplicationSink:
    def get_sink_to_directory(self) -> str:
        raise NotImplementedError

    def set_source_filer(self, source: FilerSource) -> None:
        self.source = source

    def create_entry(self, key: str, entry: fpb.Entry) -> None:
        raise NotImplementedError

    def update_entry(
        self, key: str, old_entry: fpb.Entry, new_parent_path: str,
        new_entry: fpb.Entry, delete_chunks: bool,
    ) -> bool:
        """Returns True when an existing sink entry was found+updated."""
        raise NotImplementedError

    def delete_entry(self, key: str, is_directory: bool, delete_chunks: bool) -> None:
        raise NotImplementedError


class FilerSink(ReplicationSink):
    name = "filer"

    def __init__(
        self,
        grpc_address: str,
        directory: str = "/backup",
        replication: str = "",
        collection: str = "",
        ttl_sec: int = 0,
    ):
        self.filer = grpc_address
        self.dir = directory.rstrip("/")
        self.replication = replication
        self.collection = collection
        self.ttl_sec = ttl_sec
        self.source: FilerSource | None = None
        self._channel: grpc.Channel | None = None

    def _stub(self):
        if self._channel is None:
            self._channel = rpc.dial(rpc.grpc_address(self.filer))
        return rpc.filer_stub(self._channel)

    def get_sink_to_directory(self) -> str:
        return self.dir

    # ------------------------------------------------------------------
    def _replicate_chunks(self, chunks) -> list[fpb.FileChunk]:
        """Fetch every chunk from the source cluster and upload it into
        the sink cluster (fetch_write.go replicateChunks)."""
        out = []
        for chunk in chunks:
            data = self.source.read_chunk(chunk.fid)
            ar = self._stub().AssignVolume(
                fpb.AssignVolumeRequest(
                    count=1,
                    collection=self.collection,
                    replication=self.replication,
                    ttl_sec=self.ttl_sec,
                )
            )
            ur = op.upload(f"{ar.url}/{ar.fid}", data, jwt=ar.auth)
            if ur.error:
                raise RuntimeError(f"sink upload {ar.fid}: {ur.error}")
            out.append(
                fpb.FileChunk(
                    fid=ar.fid,
                    offset=chunk.offset,
                    size=chunk.size,
                    mtime=chunk.mtime,
                    e_tag=chunk.e_tag,
                    source_fid=chunk.fid,
                )
            )
        return out

    def create_entry(self, key: str, entry: fpb.Entry) -> None:
        directory, _, name = key.rpartition("/")
        new_entry = fpb.Entry(
            name=name,
            is_directory=entry.is_directory,
            attributes=entry.attributes,
        )
        if not entry.is_directory:
            new_entry.chunks.extend(self._replicate_chunks(entry.chunks))
        self._stub().CreateEntry(
            fpb.CreateEntryRequest(directory=directory or "/", entry=new_entry)
        )

    def update_entry(self, key, old_entry, new_parent_path, new_entry, delete_chunks) -> bool:
        directory, _, name = key.rpartition("/")
        try:
            existing = self._stub().LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(directory=directory or "/", name=name)
            ).entry
        except grpc.RpcError:
            return False
        # keep sink chunks that mirror source chunks still present; add
        # re-uploaded copies of new source chunks (filer_sink.go UpdateEntry)
        surviving_sources = {c.fid for c in new_entry.chunks}
        kept = [c for c in existing.chunks if c.source_fid in surviving_sources]
        mirrored = {c.source_fid for c in kept}
        fresh = [c for c in new_entry.chunks if c.fid not in mirrored]
        updated = fpb.Entry(
            name=name,
            is_directory=new_entry.is_directory,
            attributes=new_entry.attributes,
        )
        updated.chunks.extend(kept)
        if fresh:
            updated.chunks.extend(self._replicate_chunks(fresh))
        self._stub().UpdateEntry(
            fpb.UpdateEntryRequest(directory=directory or "/", entry=updated)
        )
        return True

    def delete_entry(self, key: str, is_directory: bool, delete_chunks: bool) -> None:
        directory, _, name = key.rpartition("/")
        try:
            self._stub().DeleteEntry(
                fpb.DeleteEntryRequest(
                    directory=directory or "/",
                    name=name,
                    is_delete_data=delete_chunks,
                    is_recursive=is_directory,
                )
            )
        except grpc.RpcError as e:
            wlog.warning("sink delete %s: %s", key, e)

    def close(self) -> None:
        if self._channel is not None:
            self._channel.close()


class LocalSink(ReplicationSink):
    """Write replicated entries as plain files under a directory — the
    object-store-sink analogue testable without cloud SDKs."""

    name = "local"

    def __init__(self, directory: str):
        self.dir = directory.rstrip("/")
        os.makedirs(self.dir, exist_ok=True)
        self.source: FilerSource | None = None

    def get_sink_to_directory(self) -> str:
        return ""

    def _local_path(self, key: str) -> str:
        return os.path.join(self.dir, key.lstrip("/"))

    def create_entry(self, key: str, entry: fpb.Entry) -> None:
        path = self._local_path(key)
        if entry.is_directory:
            os.makedirs(path, exist_ok=True)
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            for chunk in sorted(entry.chunks, key=lambda c: c.offset):
                f.seek(chunk.offset)
                f.write(self.source.read_chunk(chunk.fid))

    def update_entry(self, key, old_entry, new_parent_path, new_entry, delete_chunks) -> bool:
        existed = os.path.exists(self._local_path(key))
        self.create_entry(key, new_entry)
        return existed

    def delete_entry(self, key: str, is_directory: bool, delete_chunks: bool) -> None:
        path = self._local_path(key)
        if is_directory:
            import shutil

            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)


class AssemblingObjectSink(ReplicationSink):
    """Shared shape of the object-store sinks (S3/GCS/Azure/B2): each
    file entry becomes one object, assembled from its chunks through
    the visible-interval algebra (mtime-resolved overlaps, size-clamped
    views — NOT a raw offset sort, which would resurrect overwritten
    bytes and let truncated entries grow back past their EOF);
    directories are implicit in keys, recursive deletes sweep the
    replicated prefix. Providers implement _put/_delete/_list."""

    def __init__(self, directory: str = ""):
        self.dir = directory.strip("/")
        self.source: FilerSource | None = None

    def get_sink_to_directory(self) -> str:
        return ""

    def set_source_filer(self, source: FilerSource) -> None:
        self.source = source

    def _key(self, key: str) -> str:
        k = key.lstrip("/")
        return f"{self.dir}/{k}" if self.dir else k

    def _assemble(self, entry: fpb.Entry) -> bytes:
        from seaweedfs_tpu.filer import filechunks

        size = entry.attributes.file_size or sum(c.size for c in entry.chunks)
        buf = bytearray(size)
        for view in filechunks.view_from_chunks(list(entry.chunks), 0, size):
            data = self.source.read_chunk(view.fid)
            piece = data[view.offset : view.offset + view.size]
            buf[view.logic_offset : view.logic_offset + len(piece)] = piece
        return bytes(buf)

    def create_entry(self, key: str, entry: fpb.Entry) -> None:
        if entry.is_directory:
            return  # object stores have no directories
        self._put(self._key(key), self._assemble(entry))

    def update_entry(
        self, key, old_entry, new_parent_path, new_entry, delete_chunks
    ) -> bool:
        self.create_entry(key, new_entry)
        return True  # puts are idempotent upserts in an object store

    def delete_entry(self, key: str, is_directory: bool, delete_chunks: bool) -> None:
        if is_directory:
            # a recursive source delete emits ONE event for the top
            # directory; sweep the whole replicated prefix or every
            # object under it is orphaned in the bucket forever
            prefix = self._key(key).rstrip("/") + "/"
            for obj_key in self._list(prefix):
                self._delete(obj_key)
            return
        self._delete(self._key(key))

    # provider primitives
    def _put(self, name: str, data: bytes) -> None:
        raise NotImplementedError

    def _delete(self, name: str) -> None:
        raise NotImplementedError

    def _list(self, prefix: str) -> list[str]:
        raise NotImplementedError


class S3Sink(AssemblingObjectSink):
    """Replicate into an S3-compatible bucket (sink/s3sink/s3_sink.go).
    Works against any SigV4 endpoint including this repo's own gateway."""

    name = "s3"

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        access_key: str = "",
        secret_key: str = "",
        directory: str = "",
        region: str = "us-east-1",
    ):
        super().__init__(directory)
        from seaweedfs_tpu.s3api.client import S3Client

        self.client = S3Client(endpoint, access_key, secret_key, region=region)
        self.bucket = bucket

    def _put(self, name: str, data: bytes) -> None:
        self.client.put_object(self.bucket, name, data)

    def _delete(self, name: str) -> None:
        self.client.delete_object(self.bucket, name)

    def _list(self, prefix: str) -> list[str]:
        return list(self.client.list_objects(self.bucket, prefix))


# gcs / azure / backblaze live in replication/cloud_sinks.py — real
# wire-protocol implementations (no SDKs), gated only on credentials.
