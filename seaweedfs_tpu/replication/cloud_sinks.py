"""Cloud replication sinks speaking the providers' REST protocols.

Behavioral match of the reference's SDK-backed sinks — each file entry
becomes one object (chunks fetched from the source cluster and
assembled through the visible-interval algebra), directories are
implicit in keys, recursive deletes sweep the replicated prefix:

  GcsSink    weed/replication/sink/gcssink/gcs_sink.go — the GCS JSON
             API (upload?uploadType=media, objects list/delete) with a
             Bearer token
  AzureSink  weed/replication/sink/azuresink/azure_sink.go — Azure Blob
             REST (Put/Delete Blob, List Blobs) with SharedKey request
             signing (the wire protocol the Azure SDK implements)
  B2Sink     weed/replication/sink/b2sink/b2_sink.go — Backblaze B2
             native API (authorize_account, get_upload_url, upload,
             list_file_names, delete_file_version)

The reference needs the providers' SDKs; these sinks speak the wire
protocols directly over urllib (https-capable), so the only gate is
credentials/endpoint config — and they are testable offline against
the in-repo protocol fakes (tests/cloud_fakes.py)."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time
import urllib.error
import urllib.parse
import urllib.request
from email.utils import formatdate

from seaweedfs_tpu.replication.sink import AssemblingObjectSink


def _request(
    method: str,
    url: str,
    body: bytes | None = None,
    headers: dict | None = None,
    timeout: float = 30.0,
) -> tuple[int, dict, bytes]:
    req = urllib.request.Request(
        url, data=body, method=method, headers=headers or {}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.getheaders()), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


class GcsSink(AssemblingObjectSink):
    """GCS over the JSON API (storage/v1). `token` is an OAuth bearer
    token (how the SDK authenticates after its token dance); the fake
    accepts any."""

    name = "gcs"

    def __init__(
        self,
        bucket: str,
        token: str = "",
        directory: str = "",
        endpoint: str = "https://storage.googleapis.com",
    ):
        super().__init__(directory)
        self.bucket = bucket
        self.endpoint = endpoint.rstrip("/")
        self._headers = {"Authorization": f"Bearer {token}"} if token else {}

    def _put(self, name: str, data: bytes) -> None:
        q = urllib.parse.urlencode({"uploadType": "media", "name": name})
        status, _, body = _request(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?{q}",
            body=data,
            headers={**self._headers, "Content-Type": "application/octet-stream"},
        )
        if status != 200:
            raise RuntimeError(f"gcs put {name}: http {status} {body[:200]!r}")

    def _delete(self, name: str) -> None:
        enc = urllib.parse.quote(name, safe="")
        status, _, body = _request(
            "DELETE",
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{enc}",
            headers=self._headers,
        )
        if status not in (200, 204, 404):
            raise RuntimeError(f"gcs delete {name}: http {status}")

    def _list(self, prefix: str) -> list[str]:
        names: list[str] = []
        token = ""
        while True:
            params = {"prefix": prefix}
            if token:
                params["pageToken"] = token
            q = urllib.parse.urlencode(params)
            status, _, body = _request(
                "GET",
                f"{self.endpoint}/storage/v1/b/{self.bucket}/o?{q}",
                headers=self._headers,
            )
            if status != 200:
                raise RuntimeError(f"gcs list {prefix}: http {status}")
            resp = json.loads(body)
            names.extend(item["name"] for item in resp.get("items", []))
            token = resp.get("nextPageToken", "")
            if not token:
                return names


class AzureSink(AssemblingObjectSink):
    """Azure Blob storage over its REST API with SharedKey signing —
    the exact scheme the Azure SDK computes (Put Blob / Delete Blob /
    List Blobs, x-ms-version 2020-10-02)."""

    name = "azure"
    _VERSION = "2020-10-02"

    def __init__(
        self,
        account: str,
        account_key: str,
        container: str,
        directory: str = "",
        endpoint: str = "",  # default https://{account}.blob.core.windows.net
    ):
        super().__init__(directory)
        self.account = account
        self.key = base64.b64decode(account_key) if account_key else b""
        self.container = container
        self.endpoint = (
            endpoint.rstrip("/")
            or f"https://{account}.blob.core.windows.net"
        )

    def _signed_headers(
        self, method: str, path: str, query: dict, body: bytes | None,
        extra: dict,
    ) -> dict:
        headers = {
            "x-ms-date": formatdate(time.time(), usegmt=True),
            "x-ms-version": self._VERSION,
            **extra,
        }
        # canonicalized x-ms-* headers, sorted
        canon_headers = "".join(
            f"{k.lower()}:{v}\n"
            for k, v in sorted(headers.items())
            if k.lower().startswith("x-ms-")
        )
        canon_resource = f"/{self.account}{path}"
        for k in sorted(query):
            canon_resource += f"\n{k.lower()}:{query[k]}"
        length = str(len(body)) if body else ""
        string_to_sign = "\n".join(
            [
                method,
                "",  # Content-Encoding
                "",  # Content-Language
                length,  # Content-Length ("" when 0)
                "",  # Content-MD5
                extra.get("Content-Type", ""),
                "",  # Date (x-ms-date is used)
                "",  # If-Modified-Since
                "",  # If-Match
                "",  # If-None-Match
                "",  # If-Unmodified-Since
                "",  # Range
            ]
        ) + "\n" + canon_headers + canon_resource
        sig = base64.b64encode(
            hmac.new(self.key, string_to_sign.encode(), hashlib.sha256).digest()
        ).decode()
        headers["Authorization"] = f"SharedKey {self.account}:{sig}"
        return headers

    def _url(self, path: str, query: dict) -> str:
        q = urllib.parse.urlencode(query)
        return f"{self.endpoint}{path}" + (f"?{q}" if q else "")

    def _put(self, name: str, data: bytes) -> None:
        # sign the ENCODED path — Azure canonicalizes the URI path as
        # sent, so signing the decoded form 403s any name that
        # percent-encoding alters (spaces, '#', non-ASCII)
        path = f"/{self.container}/{urllib.parse.quote(name)}"
        headers = self._signed_headers(
            "PUT", path, {}, data,
            {
                "x-ms-blob-type": "BlockBlob",
                "Content-Type": "application/octet-stream",
            },
        )
        status, _, body = _request("PUT", self._url(path, {}), data, headers)
        if status not in (200, 201):
            raise RuntimeError(f"azure put {name}: http {status} {body[:200]!r}")

    def _delete(self, name: str) -> None:
        path = f"/{self.container}/{urllib.parse.quote(name)}"
        headers = self._signed_headers("DELETE", path, {}, None, {})
        status, _, _ = _request("DELETE", self._url(path, {}), None, headers)
        if status not in (200, 202, 404):
            raise RuntimeError(f"azure delete {name}: http {status}")

    def _list(self, prefix: str) -> list[str]:
        import re

        names: list[str] = []
        marker = ""
        while True:
            query = {"restype": "container", "comp": "list", "prefix": prefix}
            if marker:
                query["marker"] = marker
            headers = self._signed_headers(
                "GET", f"/{self.container}", query, None, {}
            )
            status, _, body = _request(
                "GET", self._url(f"/{self.container}", query), None, headers
            )
            if status != 200:
                raise RuntimeError(f"azure list {prefix}: http {status}")
            from xml.sax.saxutils import unescape

            text = body.decode()
            # XML-unescape: a blob named "a&b.bin" lists as a&amp;b.bin,
            # and sweeping the escaped name would 404 and strand the blob
            names.extend(
                unescape(n) for n in re.findall(r"<Name>([^<]+)</Name>", text)
            )
            m = re.search(r"<NextMarker>([^<]+)</NextMarker>", text)
            if not m:
                return names
            marker = unescape(m.group(1))


class B2Sink(AssemblingObjectSink):
    """Backblaze B2 over the native API: authorize_account once, then
    get_upload_url/upload_file per object (b2_sink.go's SDK flow)."""

    name = "backblaze"

    def __init__(
        self,
        key_id: str,
        application_key: str,
        bucket: str,
        directory: str = "",
        endpoint: str = "https://api.backblazeb2.com",
    ):
        super().__init__(directory)
        self.bucket_name = bucket
        basic = base64.b64encode(f"{key_id}:{application_key}".encode()).decode()
        status, _, body = _request(
            "GET",
            f"{endpoint.rstrip('/')}/b2api/v2/b2_authorize_account",
            headers={"Authorization": f"Basic {basic}"},
        )
        if status != 200:
            raise RuntimeError(f"b2 authorize: http {status} {body[:200]!r}")
        auth = json.loads(body)
        self.api_url = auth["apiUrl"].rstrip("/")
        self.token = auth["authorizationToken"]
        self.bucket_id = self._bucket_id()

    def _api(self, op: str, payload: dict) -> dict:
        status, _, body = _request(
            "POST",
            f"{self.api_url}/b2api/v2/{op}",
            body=json.dumps(payload).encode(),
            headers={"Authorization": self.token},
        )
        if status != 200:
            raise RuntimeError(f"b2 {op}: http {status} {body[:200]!r}")
        return json.loads(body)

    def _bucket_id(self) -> str:
        resp = self._api("b2_list_buckets", {"bucketName": self.bucket_name})
        for b in resp.get("buckets", []):
            if b["bucketName"] == self.bucket_name:
                return b["bucketId"]
        raise RuntimeError(f"b2: bucket {self.bucket_name!r} not found")

    _upload: tuple[str, str] | None = None  # cached (uploadUrl, token)

    def _put(self, name: str, data: bytes) -> None:
        # B2 lets an upload URL/token be reused until it errors; the
        # SDK flow caches it and re-fetches on failure — one extra API
        # round-trip per bulk sync instead of one per object
        for attempt in (0, 1):
            if self._upload is None:
                up = self._api("b2_get_upload_url", {"bucketId": self.bucket_id})
                self._upload = (up["uploadUrl"], up["authorizationToken"])
            url, token = self._upload
            status, _, body = _request(
                "POST",
                url,
                body=data,
                headers={
                    "Authorization": token,
                    "X-Bz-File-Name": urllib.parse.quote(name),
                    "Content-Type": "b2/x-auto",
                    "X-Bz-Content-Sha1": hashlib.sha1(data).hexdigest(),
                },
            )
            if status == 200:
                return
            self._upload = None  # expired/rotated: fetch a fresh one
            if attempt:
                raise RuntimeError(
                    f"b2 upload {name}: http {status} {body[:200]!r}"
                )

    def _delete(self, name: str) -> None:
        # B2 keeps every uploaded version of a name: deleting only the
        # newest would resurface the previous one. Walk
        # b2_list_file_versions and delete them ALL.
        start_name, start_id = name, None
        while True:
            payload = {
                "bucketId": self.bucket_id,
                "startFileName": start_name,
                "prefix": name,
                "maxFileCount": 100,
            }
            if start_id:
                payload["startFileId"] = start_id
            resp = self._api("b2_list_file_versions", payload)
            for f in resp.get("files", []):
                if f["fileName"] == name:
                    self._api(
                        "b2_delete_file_version",
                        {"fileName": name, "fileId": f["fileId"]},
                    )
            nxt = resp.get("nextFileName")
            if not nxt or nxt != name:
                return
            start_name, start_id = nxt, resp.get("nextFileId")

    def _list(self, prefix: str) -> list[str]:
        names: list[str] = []
        start = None
        while True:
            payload = {
                "bucketId": self.bucket_id,
                "prefix": prefix,
                "maxFileCount": 1000,
            }
            if start:
                payload["startFileName"] = start
            resp = self._api("b2_list_file_names", payload)
            names.extend(f["fileName"] for f in resp.get("files", []))
            start = resp.get("nextFileName")
            if not start:
                return names
