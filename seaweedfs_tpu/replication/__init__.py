from seaweedfs_tpu.replication.replicator import Replicator

__all__ = ["Replicator"]
