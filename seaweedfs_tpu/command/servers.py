"""Server subcommands: master, volume, filer, s3, webdav, server
(all-in-one), shell — the daemon half of the reference CLI
(weed/command/master.go, volume.go, filer.go, s3.go, webdav.go,
server.go:30-100, shell.go)."""

from __future__ import annotations

import argparse
import signal
import threading

from seaweedfs_tpu.command import Command, register
from seaweedfs_tpu.util import wlog


def _tune_gc() -> None:
    """Daemon-mode GC posture: freeze boot-time objects out of the young
    generation and raise the gen-0 threshold so the cyclic collector
    stops running every ~700 allocations mid-request (the request path
    allocates acyclically; measured ~5% of data-plane CPU). Collections
    still happen, just far less often — this is tuning, not disabling."""
    import gc

    gc.collect()
    gc.freeze()
    gc.set_threshold(100_000, 1_000, 1_000)


def _wait_forever() -> int:
    stop = threading.Event()

    def on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    _tune_gc()
    stop.wait()
    return 0


def _wait_with_drain() -> tuple[int, bool]:
    """Volume-daemon wait (docs/HEALTH.md drain runbook): SIGTERM asks
    for a GRACEFUL drain — announce draining, shed new writes, finish
    in-flight requests, deregister — while SIGINT keeps the abrupt
    exit. Returns (rc, drain_requested)."""
    stop = threading.Event()
    drain = threading.Event()

    def on_term(signum, frame):
        drain.set()
        stop.set()

    def on_int(signum, frame):
        stop.set()

    signal.signal(signal.SIGINT, on_int)
    signal.signal(signal.SIGTERM, on_term)
    _tune_gc()
    stop.wait()
    return 0, drain.is_set()


def _configure_tls(component: str) -> None:
    """security.toml [grpc]/[grpc.<component>] → process-wide gRPC TLS
    (security/tls.go LoadServerTLS/LoadClientTLS role)."""
    from seaweedfs_tpu.pb import rpc
    from seaweedfs_tpu.security.tls import load_tls_config
    from seaweedfs_tpu.util.config import load_config

    cfg = load_config("security")
    tls = load_tls_config(cfg, component)
    if tls is not None:
        rpc.set_tls(tls, cfg.get_string("grpc.server_name"))


def _add_trace_flags(p: argparse.ArgumentParser) -> None:
    """Tracing-plane knobs shared by every daemon command
    (docs/TRACING.md): -traceSlowMs writes completed slow traces
    through wlog with the request ID (0 = off); -traceSample N
    head-samples 1-in-N headerless roots (1 = trace everything)."""
    p.add_argument(
        "-traceSlowMs",
        type=float,
        default=None,
        help="log completed root spans slower than this many ms "
        "through wlog with their trace ID (an explicit 0 disables; "
        "unset keeps the WEED_TRACE_SLOW_MS env or 0)",
    )
    p.add_argument(
        "-traceSample",
        type=int,
        default=0,
        help="head-sample 1 in N requests without an inbound trace "
        "header (1 traces every request; 0/default keeps the "
        "WEED_TRACE_SAMPLE env or 1)",
    )


def _add_serve_flags(p: argparse.ArgumentParser) -> None:
    """Serving-core knobs shared by the data-plane daemons
    (docs/SERVING.md), enforced identically by the C epoll loop and
    the threaded mini loop."""
    p.add_argument(
        "-serveIdleMs",
        type=int,
        default=30000,
        help="close keep-alive connections idle longer than this many "
        "milliseconds (0 = never; bounds fd usage under millions of "
        "mostly-idle clients)",
    )
    p.add_argument(
        "-serveMaxReqs",
        type=int,
        default=0,
        help="serve at most N requests per connection, then close with "
        "Connection: close (0 = unlimited; rebalances long-lived "
        "clients across SO_REUSEPORT accept processes)",
    )


def _add_admission_flags(p: argparse.ArgumentParser) -> None:
    """QoS admission-control knobs shared by the serving daemons
    (docs/QOS.md): token bucket per client key + process-wide in-flight
    cap, shedding with 503 + Retry-After instead of collapsing."""
    p.add_argument(
        "-admissionRate",
        type=float,
        default=0.0,
        help="per-client admitted requests/second (token bucket keyed "
        "by S3 access key or remote address; 0 = admission off). With "
        "-serveProcs N each sibling enforces rate/N of the budget",
    )
    p.add_argument(
        "-admissionBurst",
        type=float,
        default=0.0,
        help="per-client token-bucket burst capacity (0 = 2x the rate)",
    )
    p.add_argument(
        "-admissionInflight",
        type=int,
        default=0,
        help="shed with 503 once this many requests are in flight in "
        "this process, regardless of client (queue-length cap; 0 = "
        "uncapped)",
    )
    p.add_argument(
        "-admissionProcs",
        type=int,
        default=0,
        help="process-group size the per-client admission budget is "
        "divided by (0 = the -serveProcs value; set automatically on "
        "spawned siblings, which re-run with -serveProcs 1 and would "
        "otherwise each enforce the FULL budget)",
    )
    p.add_argument(
        "-admissionShmPath",
        default="",
        help="mmap'd token-bucket file ALL the port's accept processes "
        "charge (docs/QOS.md): the GLOBAL per-client rate holds under "
        "any connection spread, and the C serving loop sheds natively. "
        "Auto-created under $TMPDIR when -admissionRate is set with "
        "-serveProcs/-workers > 1; empty with a single process = the "
        "in-process bucket",
    )


def _admission_shm_path(args, group_size: int, port: int) -> str:
    """Resolve the shared admission bucket file for a multi-process
    port group: the operator's -admissionShmPath wins; otherwise one is
    auto-created per (port, lead pid) so every sibling the lead spawns
    attaches to the same bucket while two independent clusters on one
    host never collide. Single-process groups (or rate 0) keep the
    in-process bucket — no file, no mmap."""
    if args.admissionShmPath:
        return args.admissionShmPath
    if args.admissionRate > 0 and group_size > 1:
        import os
        import tempfile

        return os.path.join(
            tempfile.gettempdir(), f"weed-adm-{port}-{os.getpid()}.tb"
        )
    return ""


def _spawn_serve_procs(
    n: int, argv_tail: list[str], extra: list[str] | None = None
) -> list:
    """`-serveProcs N` (docs/SERVING.md): launch N-1 sibling gateway
    processes re-running this subcommand with `-reusePort` so every
    member binds the same port via SO_REUSEPORT and the kernel spreads
    accepted connections across them. Returns Popen handles. `extra`
    rides before the overrides (e.g. -admissionProcs N so siblings keep
    dividing the admission budget by the ORIGINAL group size)."""
    import subprocess
    import sys

    procs = []
    for _ in range(max(0, n - 1)):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", "seaweedfs_tpu"]
                + argv_tail
                + (extra or [])
                + ["-serveProcs", "1", "-reusePort"]
            )
        )
    return procs


def _apply_trace_flags(args) -> None:
    from seaweedfs_tpu import trace

    slow_ms = getattr(args, "traceSlowMs", None)
    if slow_ms is not None:  # unset keeps the WEED_TRACE_SLOW_MS env
        trace.set_slow_threshold_ms(slow_ms)
    if getattr(args, "traceSample", 0) > 0:
        trace.set_sample_every(args.traceSample)
    # weedchaos (docs/CHAOS.md): every daemon command funnels through
    # here before serving, so a WEED_CHAOS_DISK spec in the environment
    # arms the disk-fault shim in subprocess CLI clusters — the chaos
    # scenarios' lever into a real multi-process cluster's disks
    from seaweedfs_tpu.analysis.chaos import install_disk_chaos_from_env

    install_disk_chaos_from_env()


def _load_guard():
    """security.toml → Guard (None when not configured)."""
    from seaweedfs_tpu.security import Guard
    from seaweedfs_tpu.util.config import load_config

    cfg = load_config("security")
    key = cfg.get_string("jwt.signing.key")
    read_key = cfg.get_string("jwt.signing.read.key")
    white = cfg.get("access.white_list") or []
    if isinstance(white, str):
        white = [w for w in white.split(",") if w]
    if not key and not read_key and not white:
        return None
    return Guard(
        white_list=white,
        signing_key=key,
        expires_after_sec=cfg.get_int("jwt.signing.expires_after_seconds", 10),
        read_signing_key=read_key,
        read_expires_after_sec=cfg.get_int(
            "jwt.signing.read.expires_after_seconds", 60
        ),
    )


@register
class MasterCommand(Command):
    name = "master"
    help = "start the cluster master (volume assignment, topology, lookup)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, default=9333, help="listen port")
        p.add_argument(
            "-volumeSizeLimitMB", type=int, default=30 * 1024,
            help="roll to a fresh volume past this size",
        )
        p.add_argument(
            "-defaultReplication", default="000",
            help="replication policy for new volumes like 001",
        )
        p.add_argument(
            "-garbageThreshold", type=float, default=0.3,
            help="deleted-bytes fraction that triggers vacuum",
        )
        p.add_argument(
            "-peers",
            default="",
            help="comma-separated master peers incl. self (HA raft cluster)",
        )
        p.add_argument("-mdir", default="", help="raft/meta data directory")
        p.add_argument(
            "-nodeTimeout",
            type=float,
            default=30.0,
            help="seconds of heartbeat silence before a volume server is "
            "declared dead even if its stream stays open (0 disables)",
        )
        p.add_argument(
            "-repairInterval",
            type=float,
            default=30.0,
            help="seconds between automatic-repair scans (scrub plane: "
            "rebuild missing EC shards, fix under-replication, replace "
            "scrub-flagged corrupt replicas; 0 disables — repair goes "
            "back to manual ec.rebuild / volume.fix.replication)",
        )
        p.add_argument(
            "-repairConcurrency",
            type=int,
            default=2,
            help="global cap on simultaneously running repairs",
        )
        p.add_argument(
            "-repairGrace",
            type=float,
            default=30.0,
            help="seconds damage must persist before repair starts "
            "(rides out shard moves and node restarts)",
        )
        p.add_argument(
            "-telemetryInterval",
            type=float,
            default=10.0,
            help="seconds between leader-side cluster telemetry scrapes "
            "(/metrics from every node into the ring TSDB feeding "
            "/cluster/health, /cluster/alerts, /cluster/top; 0 disables)",
        )
        p.add_argument(
            "-tierInterval",
            type=float,
            default=0.0,
            help="seconds between lifecycle-tiering scans "
            "(docs/TIERING.md): age/temperature rules move cold EC "
            "volumes to the WEED_TIER_BACKEND object store and recall "
            "hot ones; 0 disables — tiering stays manual (tier.move)",
        )
        p.add_argument(
            "-assignPolicy",
            default="p2c",
            choices=("p2c", "random"),
            help="pick-for-write policy (docs/QOS.md): p2c = "
            "power-of-two-choices weighted by the nodes' heartbeat-"
            "reported in-flight/write-queue depth; random = the classic "
            "pure-random pick (also what WEED_QOS=0 forces)",
        )
        p.add_argument("-cpuprofile", default="", help="dump pstats profile here on exit")
        p.add_argument(
            "-sequencer.etcd",
            dest="sequencer_etcd",
            default="",
            help="etcd endpoint(s) for the external-KV sequencer "
            "(sequence/etcd_sequencer.go role); default: file/memory",
        )
        _add_trace_flags(p)
        p.add_argument("-v", type=int, default=0, help="verbosity")

    def run(self, args) -> int:
        from seaweedfs_tpu.server.master_server import MasterServer

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        if args.peers and not args.mdir:
            print("master: -peers requires -mdir (persistent raft state)")
            return 2
        _configure_tls("master")
        sequencer = None
        if args.sequencer_etcd:
            from seaweedfs_tpu.sequence import EtcdSequencer

            sequencer = EtcdSequencer(args.sequencer_etcd)
        server = MasterServer(
            host=args.ip,
            port=args.port,
            volume_size_limit_mb=args.volumeSizeLimitMB,
            default_replication=args.defaultReplication,
            garbage_threshold=args.garbageThreshold,
            guard=_load_guard(),
            peers=args.peers or None,
            raft_dir=args.mdir or None,
            node_timeout=args.nodeTimeout,
            sequencer=sequencer,
            repair_interval=args.repairInterval,
            repair_concurrency=args.repairConcurrency,
            repair_grace=args.repairGrace,
            telemetry_interval=args.telemetryInterval,
            tier_interval=args.tierInterval,
            assign_policy=args.assignPolicy,
        )
        from seaweedfs_tpu.util.profiling import CpuProfile

        # the profiler must wrap start(): threads created before
        # enable() (gRPC executor, raft loops) are never instrumented
        with CpuProfile(args.cpuprofile):
            server.start()
            wlog.info(
                "master listening on %s:%d (grpc %d)",
                args.ip,
                args.port,
                args.port + 10000,
            )
            try:
                return _wait_forever()
            finally:
                server.stop()


@register
class VolumeCommand(Command):
    name = "volume"
    help = "start a volume server (blob data plane)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, default=8080, help="listen port")
        p.add_argument("-dir", default=".", help="comma-separated data directories")
        p.add_argument("-max", default="7", help="comma-separated max volume counts")
        p.add_argument(
            "-mserver", default="127.0.0.1:9333",
            help="comma-separated master address(es)",
        )
        p.add_argument("-dataCenter", default="", help="topology data center name")
        p.add_argument("-rack", default="", help="topology rack name")
        p.add_argument(
            "-publicUrl", default="",
            help="address advertised to clients (default ip:port)",
        )
        p.add_argument(
            "-announce", default="",
            help="host:port advertised to the CLUSTER (heartbeat "
            "ip/port peers and repair verbs dial) when this server is "
            "reached through a proxy/NAT hop; default ip:port",
        )
        p.add_argument(
            "-heartbeat", type=float, default=2.0,
            help="seconds between master heartbeats; the master's "
            "phi-accrual gray-failure detector (docs/HEALTH.md) learns "
            "this cadence, so lower = faster suspect detection",
        )
        p.add_argument(
            "-readRedirect", action="store_true",
            help="302-redirect reads for volumes this server lacks",
        )
        p.add_argument("-cpuprofile", default="", help="dump pstats profile here on exit")
        p.add_argument(
            "-index",
            default="memory",
            choices=("memory", "db"),
            help="needle map kind: memory (CompactMap) | db (persistent sqlite)",
        )
        p.add_argument(
            "-ec.codec",
            dest="ec_codec",
            default="",
            choices=("", "cpu", "native", "tpu"),
            help="EC codec backend; empty = auto (tpu with a JAX device, else native SIMD, else numpy)",
        )
        p.add_argument(
            "-workers",
            type=int,
            default=1,
            help="data-plane processes sharing this port via SO_REUSEPORT "
            "(1 = classic single process; N>1 adds N-1 read workers so "
            "multi-core hosts scale the GIL-bound read path — see "
            "server/volume_workers.py)",
        )
        p.add_argument(
            "-shardWrites",
            action="store_true",
            help="with -workers N: partition WRITE ownership across the "
            "N processes by volume id (vid %% N), each appending its own "
            "volumes' .dat/.idx — multi-core write scaling under the "
            "single-writer-per-volume invariant; admin ops (vacuum, EC "
            "encode, readonly) hand ownership back to the lead first",
        )
        p.add_argument(
            "-scrubInterval",
            type=float,
            default=600.0,
            help="seconds between background integrity sweeps (needle "
            "CRC re-checks + EC parity re-verify; 0 disables)",
        )
        p.add_argument(
            "-scrubRate",
            type=float,
            default=64.0,
            help="scrub bandwidth cap in MB/s (token bucket protecting "
            "foreground read p99; <=0 = unlimited)",
        )
        p.add_argument(
            "-commitWindowUs",
            type=int,
            default=0,
            help="group-commit window in microseconds (docs/QOS.md): "
            "concurrent POSTs against one volume coalesce into one "
            "pwritev + one flush per window; 0 = off (write-per-POST)",
        )
        p.add_argument(
            "-commitBytes",
            type=int,
            default=4 << 20,
            help="group-commit byte cap: a window commits early once "
            "its batched bodies reach this many bytes",
        )
        p.add_argument(
            "-commitBatch",
            type=int,
            default=64,
            help="group-commit batch cap: a window commits early once "
            "this many writes have joined it",
        )
        p.add_argument(
            "-commitFsync",
            action="store_true",
            help="fsync the .dat on every commit point (per POST "
            "without -commitWindowUs, per window with it) — the "
            "durability lever the fsyncs-per-POST bench ratio measures",
        )
        _add_admission_flags(p)
        _add_serve_flags(p)
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.util.config import load_config

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        dirs = args.dir.split(",")
        maxes = [int(m) for m in args.max.split(",")]
        if len(maxes) == 1:
            maxes = maxes * len(dirs)
        _configure_tls("volume")
        workers = max(1, args.workers)
        internal_port = 0
        if workers > 1:
            # loopback listener the read workers proxy through; +20000
            # mirrors the gRPC +10000 convention, wrapping below the
            # ephemeral range when the public port sits too high
            internal_port = args.port + 20000
            if internal_port > 65535:
                internal_port = args.port - 20000
            if not 0 < internal_port <= 65535:
                print(f"volume: no usable internal port for -port {args.port}")
                return 1
        guard = _load_guard()
        shard_writes = args.shardWrites and workers > 1
        admission_shm = _admission_shm_path(args, workers, args.port)
        server = VolumeServer(
            dirs,
            host=args.ip,
            port=args.port,
            master=args.mserver,
            public_url=args.publicUrl,
            data_center=args.dataCenter,
            rack=args.rack,
            max_volume_counts=maxes,
            heartbeat_interval=args.heartbeat,
            read_redirect=args.readRedirect,
            guard=guard,
            ec_codec=args.ec_codec,
            storage_backends=load_config("master").sub("storage.backend"),
            needle_map_kind=args.index,
            reuse_port=workers > 1,
            internal_port=internal_port,
            shard_writes=shard_writes,
            n_writers=workers if shard_writes else 1,
            scrub_interval=args.scrubInterval,
            scrub_rate_mb_s=args.scrubRate,
            serve_idle_ms=args.serveIdleMs,
            serve_max_reqs=args.serveMaxReqs,
            commit_window_us=args.commitWindowUs,
            commit_bytes=args.commitBytes,
            commit_batch=args.commitBatch,
            commit_fsync=args.commitFsync,
            admission_rate=args.admissionRate,
            admission_burst=args.admissionBurst,
            admission_inflight=args.admissionInflight,
            # the read workers enforce admission too: with a shm path
            # all of them charge ONE shared bucket (global rate under
            # any connection spread); without one the group divides
            # the per-client budget by its size — the legacy
            # -serveProcs sibling convention
            admission_procs=args.admissionProcs or workers,
            admission_shm_path=admission_shm,
            announce=args.announce,
        )
        from seaweedfs_tpu.util.profiling import CpuProfile

        with CpuProfile(args.cpuprofile):
            server.start()
            procs = []
            if workers > 1:
                from seaweedfs_tpu.server.volume_workers import spawn_read_workers

                procs = spawn_read_workers(
                    workers - 1,
                    dirs,
                    args.ip,
                    args.port,
                    f"127.0.0.1:{internal_port}",
                    shard_writes=shard_writes,
                    n_writers=workers,
                    master=args.mserver,
                    internal_base=internal_port,
                    admission_rate=args.admissionRate,
                    admission_burst=args.admissionBurst,
                    admission_inflight=args.admissionInflight,
                    admission_procs=args.admissionProcs or workers,
                    admission_shm_path=admission_shm,
                    commit_window_us=args.commitWindowUs,
                    commit_bytes=args.commitBytes,
                    commit_batch=args.commitBatch,
                    commit_fsync=args.commitFsync,
                )
            wlog.info(
                "volume server %s:%d -> master %s (%d worker(s))",
                args.ip, args.port, args.mserver, workers,
            )
            drained = False
            try:
                rc, drained = _wait_with_drain()
                return rc
            finally:
                if drained:
                    # SIGTERM = graceful drain (docs/HEALTH.md): stop
                    # taking assignments, finish in-flight, deregister.
                    # Workers are terminated AFTER the drain window so
                    # their in-flight reads finish while the master
                    # learns of the drain — killing them first would
                    # break the finish-in-flight contract for most of
                    # the read traffic.
                    server.drain()
                    for pr in procs:
                        pr.terminate()
                else:
                    for pr in procs:
                        pr.terminate()
                    server.stop()
                if admission_shm and not args.admissionShmPath:
                    # auto-created bucket file: best-effort removal
                    # (attached mmaps keep working; a crashed lead just
                    # leaves a 8KiB tmp file behind)
                    import contextlib
                    import os

                    with contextlib.suppress(OSError):
                        os.unlink(admission_shm)


@register
class VolumeWorkerCommand(Command):
    name = "volume.worker"
    help = "internal: one SO_REUSEPORT read worker (spawned by volume -workers N)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, required=True, help="listen port")
        p.add_argument(
            "-dir", required=True,
            help="data directory (shared with the lead)",
        )
        p.add_argument("-lead", required=True, help="lead's internal host:port")
        p.add_argument(
            "-workerPort", type=int, default=0,
            help="internal lead port for worker coordination",
        )
        p.add_argument(
            "-shardWrites", action="store_true",
            help="enable per-volume write sharding across workers",
        )
        p.add_argument(
            "-writerIndex", type=int, default=0,
            help="this worker's writer slot (0..writers-1)",
        )
        p.add_argument(
            "-writers", type=int, default=1,
            help="total writer slots in the shard-write group",
        )
        p.add_argument(
            "-mserver", default="",
            help="comma-separated master address(es)",
        )
        p.add_argument(
            "-internalPort", type=int, default=0,
            help="loopback listener port for trusted worker hops",
        )
        p.add_argument(
            "-commitWindowUs", type=int, default=0,
            help="group-commit window (µs) for vids this worker owns "
            "under -shardWrites; 0 = write-per-POST (docs/QOS.md)",
        )
        p.add_argument(
            "-commitBytes", type=int, default=4 << 20,
            help="group-commit byte cap (commit early past this)",
        )
        p.add_argument(
            "-commitBatch", type=int, default=64,
            help="group-commit batch cap (commit early past this)",
        )
        p.add_argument(
            "-commitFsync", action="store_true",
            help="fsync the .dat at every owned-write commit point",
        )
        _add_admission_flags(p)
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.server.volume_workers import VolumeReadWorker

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        worker = VolumeReadWorker(
            args.dir.split(","),
            host=args.ip,
            port=args.port,
            lead=args.lead,
            worker_port=args.workerPort,
            shard_writes=args.shardWrites,
            writer_index=args.writerIndex,
            n_writers=args.writers,
            master=args.mserver,
            internal_port=args.internalPort,
            # same security.toml as the lead: sharded local writes
            # enforce the identical JWT/white-list gate
            guard=_load_guard(),
            admission_rate=args.admissionRate,
            admission_burst=args.admissionBurst,
            admission_inflight=args.admissionInflight,
            # spawn passes the group size explicitly; a bare-launched
            # worker defaults to enforcing the full budget alone
            admission_procs=args.admissionProcs or 1,
            admission_shm_path=args.admissionShmPath,
            commit_window_us=args.commitWindowUs,
            commit_bytes=args.commitBytes,
            commit_batch=args.commitBatch,
            commit_fsync=args.commitFsync,
        )
        worker.start()
        try:
            return _wait_forever()
        finally:
            worker.stop()


@register
class FilerCommand(Command):
    name = "filer"
    help = "start a filer (directory/file namespace over the blob store)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, default=8888, help="listen port")
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="master address host:port",
        )
        p.add_argument(
            "-store", default="memory", help="memory | sqlite | sql | sortedlog | lsm | redis | cassandra | etcd | tikv | mysql | postgres"
        )
        p.add_argument(
            "-storePath", default="",
            help="store path/DSN (sqlite file, redis host, ...)",
        )
        p.add_argument(
            "-collection", default="",
            help="collection for filer-written chunks",
        )
        p.add_argument(
            "-replication", default="",
            help="replication policy for filer-written chunks",
        )
        p.add_argument(
            "-maxMB", type=int, default=32,
            help="split uploads into chunks of this many MB",
        )
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu import notification
        from seaweedfs_tpu.server.filer_server import FilerServer
        from seaweedfs_tpu.util.config import load_config

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        notification.configure(load_config("notification"))
        _configure_tls("filer")
        server = FilerServer(
            args.master.split(","),
            host=args.ip,
            port=args.port,
            store=args.store,
            store_path=args.storePath,
            collection=args.collection,
            replication=args.replication,
            max_mb=args.maxMB,
        )
        server.start()
        wlog.info("filer %s:%d -> master %s", args.ip, args.port, args.master)
        try:
            return _wait_forever()
        finally:
            server.stop()


@register
class S3Command(Command):
    name = "s3"
    help = "start the S3-compatible gateway over a filer"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, default=8333, help="listen port")
        p.add_argument(
            "-filer", default="127.0.0.1:8888",
            help="filer address host:port backing the gateway",
        )
        p.add_argument(
            "-bucketsPath", default="/buckets",
            help="filer directory that holds the buckets",
        )
        p.add_argument("-config", default="", help="identities toml with access/secret keys")
        p.add_argument(
            "-master",
            default="",
            help="comma-separated master(s) to announce this gateway to "
            "(telemetry plane; empty = not scraped by the collector)",
        )
        p.add_argument(
            "-serveProcs",
            type=int,
            default=1,
            help="accept processes sharing this port via SO_REUSEPORT "
            "(N>1 spawns N-1 sibling gateways; the kernel spreads "
            "connections across them — docs/SERVING.md)",
        )
        p.add_argument(
            "-reusePort",
            action="store_true",
            help="bind with SO_REUSEPORT (set automatically on the "
            "siblings -serveProcs spawns; set by hand to run your own "
            "process group behind one port)",
        )
        _add_admission_flags(p)
        _add_serve_flags(p)
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        _configure_tls("client")
        from seaweedfs_tpu.s3api import S3ApiServer
        from seaweedfs_tpu.s3api.auth import Identity, IdentityAccessManagement

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        iam = None
        if args.config:
            from seaweedfs_tpu.util.config import tomllib  # 3.10 fallback parser

            with open(args.config, "rb") as f:
                tree = tomllib.load(f)
            idents = [
                Identity(
                    i.get("name", i["access_key"]),
                    i["access_key"],
                    i["secret_key"],
                    i.get("actions", ("Admin",)),
                )
                for i in tree.get("identities", [])
            ]
            iam = IdentityAccessManagement(idents)
        procs = args.serveProcs
        admission_shm = _admission_shm_path(args, procs, args.port)
        server = S3ApiServer(
            filer=args.filer,
            host=args.ip,
            port=args.port,
            buckets_path=args.bucketsPath,
            iam=iam,
            masters=[m for m in args.master.split(",") if m],
            reuse_port=args.reusePort or procs > 1,
            serve_idle_ms=args.serveIdleMs,
            serve_max_reqs=args.serveMaxReqs,
            admission_rate=args.admissionRate,
            admission_burst=args.admissionBurst,
            admission_inflight=args.admissionInflight,
            admission_procs=args.admissionProcs or procs,
            admission_shm_path=admission_shm,
        )
        server.start()
        import sys

        extra = ["-admissionProcs", str(procs)]
        if admission_shm:
            # siblings must charge the SAME mmap'd bucket the lead
            # created — the flag rides after argv, so it wins the parse
            extra += ["-admissionShmPath", admission_shm]
        children = _spawn_serve_procs(procs, sys.argv[1:], extra)
        wlog.info(
            "s3 gateway %s:%d -> filer %s (%d proc(s))",
            args.ip, args.port, args.filer, procs,
        )
        try:
            return _wait_forever()
        finally:
            for pr in children:
                pr.terminate()
            server.stop()
            if admission_shm and not args.admissionShmPath:
                import contextlib
                import os

                with contextlib.suppress(OSError):
                    os.unlink(admission_shm)


@register
class WebDavCommand(Command):
    name = "webdav"
    help = "start the WebDAV gateway over a filer"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-ip", default="127.0.0.1", help="bind address")
        p.add_argument("-port", type=int, default=7333, help="listen port")
        p.add_argument(
            "-filer", default="127.0.0.1:8888",
            help="filer address host:port backing the gateway",
        )
        p.add_argument(
            "-master",
            default="",
            help="comma-separated master(s) to announce this gateway to "
            "(telemetry plane; empty = not scraped by the collector)",
        )
        p.add_argument(
            "-serveProcs",
            type=int,
            default=1,
            help="accept processes sharing this port via SO_REUSEPORT "
            "(N>1 spawns N-1 sibling gateways; the kernel spreads "
            "connections across them — docs/SERVING.md)",
        )
        p.add_argument(
            "-reusePort",
            action="store_true",
            help="bind with SO_REUSEPORT (set automatically on the "
            "siblings -serveProcs spawns; set by hand to run your own "
            "process group behind one port)",
        )
        _add_admission_flags(p)
        _add_serve_flags(p)
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        _configure_tls("client")
        from seaweedfs_tpu.webdav.webdav_server import WebDavServer

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        procs = args.serveProcs
        admission_shm = _admission_shm_path(args, procs, args.port)
        server = WebDavServer(
            filer=args.filer,
            host=args.ip,
            port=args.port,
            masters=[m for m in args.master.split(",") if m],
            reuse_port=args.reusePort or procs > 1,
            serve_idle_ms=args.serveIdleMs,
            serve_max_reqs=args.serveMaxReqs,
            admission_rate=args.admissionRate,
            admission_burst=args.admissionBurst,
            admission_inflight=args.admissionInflight,
            admission_procs=args.admissionProcs or procs,
            admission_shm_path=admission_shm,
        )
        server.start()
        import sys

        extra = ["-admissionProcs", str(procs)]
        if admission_shm:
            extra += ["-admissionShmPath", admission_shm]
        children = _spawn_serve_procs(procs, sys.argv[1:], extra)
        wlog.info(
            "webdav %s:%d -> filer %s (%d proc(s))",
            args.ip, args.port, args.filer, procs,
        )
        try:
            return _wait_forever()
        finally:
            for pr in children:
                pr.terminate()
            server.stop()
            if admission_shm and not args.admissionShmPath:
                import contextlib
                import os

                with contextlib.suppress(OSError):
                    os.unlink(admission_shm)


@register
class ServerCommand(Command):
    name = "server"
    help = "start master + volume server(s) [+ filer + s3] in one process"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-ip", default="127.0.0.1",
            help="bind address for every embedded daemon",
        )
        p.add_argument(
            "-master.port", dest="master_port", type=int, default=9333,
            help="master listen port",
        )
        p.add_argument(
            "-volume.port", dest="volume_port", type=int, default=8080,
            help="volume-server listen port",
        )
        p.add_argument(
            "-dir", default=".",
            help="data directory for volume + master meta",
        )
        p.add_argument(
            "-master.volumeSizeLimitMB", dest="vsl", type=int, default=30 * 1024,
            help="roll to a fresh volume past this size",
        )
        p.add_argument(
            "-master.defaultReplication", dest="repl", default="000",
            help="replication policy for new volumes like 001",
        )
        p.add_argument(
            "-volume.max", dest="vmax", default="7",
            help="comma-separated max volume counts",
        )
        p.add_argument("-dataCenter", default="", help="topology data center name")
        p.add_argument("-rack", default="", help="topology rack name")
        p.add_argument("-filer", action="store_true", help="also start a filer")
        p.add_argument(
            "-filer.port", dest="filer_port", type=int, default=8888,
            help="filer listen port",
        )
        p.add_argument(
            "-filer.store", dest="filer_store", default="memory",
            help="filer metadata store kind",
        )
        p.add_argument("-s3", action="store_true", help="also start an S3 gateway")
        p.add_argument(
            "-s3.port", dest="s3_port", type=int, default=8333,
            help="S3 gateway listen port",
        )
        p.add_argument(
            "-webdav", action="store_true",
            help="also start a WebDAV gateway",
        )
        p.add_argument(
            "-webdav.port", dest="webdav_port", type=int, default=7333,
            help="WebDAV gateway listen port",
        )
        p.add_argument(
            "-ec.codec",
            dest="ec_codec",
            default="",
            choices=("", "cpu", "native", "tpu"),
            help="EC codec backend; empty = auto (tpu with a JAX device, else native SIMD, else numpy)",
        )
        # scrub/self-healing knobs, same semantics as the standalone
        # master/volume commands (0 disables either plane)
        p.add_argument(
            "-repairInterval", type=float, default=30.0,
            help="seconds between repair-scheduler scans (0 disables)",
        )
        p.add_argument(
            "-repairConcurrency", type=int, default=2,
            help="max repairs in flight",
        )
        p.add_argument(
            "-repairGrace", type=float, default=30.0,
            help="seconds of damage persistence before repairing",
        )
        p.add_argument(
            "-scrubInterval", type=float, default=600.0,
            help="seconds between scrub sweeps (0 disables)",
        )
        p.add_argument(
            "-scrubRate", type=float, default=64.0,
            help="scrub bandwidth cap in MB/s",
        )
        p.add_argument(
            "-telemetryInterval", type=float, default=10.0,
            help="seconds between collector scrape cycles (0 disables)",
        )
        p.add_argument(
            "-tierInterval", type=float, default=0.0,
            help="seconds between lifecycle-tiering scans (0 disables; "
            "docs/TIERING.md)",
        )
        _add_trace_flags(p)
        p.add_argument(
            "-v", type=int, default=0,
            help="log verbosity (0=warning .. 3=trace)",
        )

    def run(self, args) -> int:
        _configure_tls("master")
        from seaweedfs_tpu.server.master_server import MasterServer
        from seaweedfs_tpu.server.volume_server import VolumeServer

        wlog.set_verbosity(args.v)
        _apply_trace_flags(args)
        guard = _load_guard()
        started = []
        master = MasterServer(
            host=args.ip,
            port=args.master_port,
            volume_size_limit_mb=args.vsl,
            default_replication=args.repl,
            guard=guard,
            # the all-in-one server gets the full self-healing plane by
            # default, like the standalone `weed master`
            repair_interval=args.repairInterval,
            repair_concurrency=args.repairConcurrency,
            repair_grace=args.repairGrace,
            telemetry_interval=args.telemetryInterval,
            tier_interval=args.tierInterval,
        )
        master.start()
        started.append(master)
        dirs = args.dir.split(",")
        maxes = [int(m) for m in args.vmax.split(",")]
        if len(maxes) == 1:
            maxes = maxes * len(dirs)
        volume = VolumeServer(
            dirs,
            host=args.ip,
            port=args.volume_port,
            master=f"{args.ip}:{args.master_port}",
            data_center=args.dataCenter,
            rack=args.rack,
            max_volume_counts=maxes,
            guard=guard,
            ec_codec=args.ec_codec,
            scrub_interval=args.scrubInterval,
            scrub_rate_mb_s=args.scrubRate,
        )
        volume.start()
        started.append(volume)
        if args.filer or args.s3 or args.webdav:
            from seaweedfs_tpu import notification
            from seaweedfs_tpu.server.filer_server import FilerServer
            from seaweedfs_tpu.util.config import load_config

            # same notification.toml wiring as the standalone `filer`
            # command — the all-in-one filer must publish events too
            notification.configure(load_config("notification"))
            filer = FilerServer(
                [f"{args.ip}:{args.master_port}"],
                host=args.ip,
                port=args.filer_port,
                store=args.filer_store,
            )
            filer.start()
            started.append(filer)
        if args.s3:
            from seaweedfs_tpu.s3api import S3ApiServer

            s3 = S3ApiServer(
                filer=f"{args.ip}:{args.filer_port}",
                host=args.ip,
                port=args.s3_port,
                masters=[f"{args.ip}:{args.master_port}"],
            )
            s3.start()
            started.append(s3)
        if args.webdav:
            from seaweedfs_tpu.webdav.webdav_server import WebDavServer

            wd = WebDavServer(
                filer=f"{args.ip}:{args.filer_port}",
                host=args.ip,
                port=args.webdav_port,
                masters=[f"{args.ip}:{args.master_port}"],
            )
            wd.start()
            started.append(wd)
        wlog.info("all-in-one server up: %d components", len(started))
        try:
            return _wait_forever()
        finally:
            for s in reversed(started):
                s.stop()


@register
class ShellCommand(Command):
    name = "shell"
    help = "interactive admin shell (ec.*, volume.*, fs.* commands)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="comma-separated master address(es)",
        )
        p.add_argument("-c", dest="script", default="", help="run semicolon-separated commands and exit")

    def run(self, args) -> int:
        import io
        import sys

        from seaweedfs_tpu.shell.shell_runner import run_shell

        _configure_tls("client")
        masters = args.master.split(",")
        if args.script:
            fake_stdin = io.StringIO(
                "\n".join(s.strip() for s in args.script.split(";")) + "\nexit\n"
            )
            run_shell(masters, stdin=fake_stdin, stdout=sys.stdout)
            return 0
        run_shell(masters)
        return 0


@register
class MountCommand(Command):
    name = "mount"
    help = "mount the filer as a FUSE filesystem (command/mount_std.go)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-filer", default="127.0.0.1:8888",
            help="filer address host:port to mount",
        )
        p.add_argument(
            "-dir", required=False, default="",
            help="local mountpoint directory",
        )
        p.add_argument(
            "-filer.path", dest="filer_path", default="/",
            help="filer subtree to mount as the root",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.filesys.fuse_kernel import (
            kernel_fuse_available,
            mount_kernel,
        )
        from seaweedfs_tpu.filesys.mount import mount_fuse
        from seaweedfs_tpu.filesys.wfs import WfsOption

        if not args.dir:
            print("usage: mount -dir=<mountpoint>")
            return 2
        option = WfsOption(args.filer, filer_mount_root_path=args.filer_path)
        if kernel_fuse_available():
            # first choice: the in-repo wire-protocol transport on
            # /dev/fuse (filesys/fuse_kernel.py) — no libfuse needed.
            # /dev/fuse is world-rw on stock Linux but mount(2) needs
            # CAP_SYS_ADMIN; unprivileged users fall through to fusepy
            # (whose fusermount helper is setuid).
            from seaweedfs_tpu.filesys.fuse_kernel import FuseProtocolError

            try:
                km = mount_kernel(option, args.dir)
            except FuseProtocolError as e:
                print(f"kernel mount unavailable ({e}); trying fusepy")
            else:
                print(f"mounted {args.filer}{args.filer_path} on {args.dir}")
                try:
                    km._thread.join()
                except KeyboardInterrupt:
                    km.unmount()
                return 0
        try:
            # second choice: a fusepy binding if one is installed
            mount_fuse(option, args.dir)
        except RuntimeError as e:
            # no /dev/fuse and no binding; the in-process VFS
            # (seaweedfs_tpu.filesys.MountedFileSystem) is the
            # supported surface here
            print(f"mount unavailable: {e}")
            return 1
        return 0
