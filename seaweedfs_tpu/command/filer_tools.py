"""filer.copy and filer.replicate subcommands
(weed/command/filer_copy.go, filer_replicate.go)."""

from __future__ import annotations

import argparse
import os
import urllib.request

from seaweedfs_tpu.command import Command, register


@register
class FilerCopyCommand(Command):
    name = "filer.copy"
    help = "copy local files/directories into the filer namespace"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("sources", nargs="+", help="local files or directories")
        p.add_argument("dest", help="filer destination like http://filer:8888/path/")
        p.add_argument("-collection", default="", help="collection for uploaded chunks")
        p.add_argument("-replication", default="", help="replication policy like 001")
        p.add_argument("-ttl", default="", help="time-to-live like 3m/4h/5d")

    def run(self, args) -> int:
        dest = args.dest
        if not dest.startswith("http://"):
            dest = "http://" + dest
        if not dest.endswith("/"):
            dest += "/"
        copied = 0
        for src in args.sources:
            if os.path.isdir(src):
                base = os.path.dirname(os.path.abspath(src).rstrip("/"))
                for root, _, files in os.walk(src):
                    for fname in files:
                        local = os.path.join(root, fname)
                        rel = os.path.relpath(local, base)
                        copied += self._put(dest + rel, local, args)
            else:
                copied += self._put(dest + os.path.basename(src), src, args)
        print(f"copied {copied} files")
        return 0

    def _put(self, url: str, local: str, args) -> int:
        with open(local, "rb") as f:
            data = f.read()
        params = []
        if args.collection:
            params.append(f"collection={args.collection}")
        if args.replication:
            params.append(f"replication={args.replication}")
        if args.ttl:
            params.append(f"ttl={args.ttl}")
        if params:
            url += "?" + "&".join(params)
        req = urllib.request.Request(url, data=data, method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            return 1 if r.status < 300 else 0


@register
class FilerReplicateCommand(Command):
    name = "filer.replicate"
    help = "consume filer update events from the notification queue and replicate to a sink"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-config", default="", help="replication toml (default: search replication.toml)")

    def run(self, args) -> int:
        from seaweedfs_tpu.replication.replicate_runner import run_replicate

        return run_replicate(config_path=args.config)
