"""Offline/utility subcommands: version, scaffold, upload, download,
backup, compact, fix, export — the tool half of the reference CLI
(weed/command/{upload,download,backup,compact,fix,export,scaffold,
version}.go)."""

from __future__ import annotations

import argparse
import json
import os
import sys

from seaweedfs_tpu.command import Command, register

VERSION = "seaweedfs_tpu 0.1 (TPU-native build)"


@register
class VersionCommand(Command):
    name = "version"
    help = "print version"

    def run(self, args) -> int:
        print(VERSION)
        return 0


@register
class ScaffoldCommand(Command):
    name = "scaffold"
    help = "generate template toml config files"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-config",
            default="security",
            help="security | filer | notification | replication | master",
        )
        p.add_argument("-output", default="", help="write <name>.toml to this dir ('' = stdout)")

    def run(self, args) -> int:
        from seaweedfs_tpu.util.config import SCAFFOLD_TEMPLATES

        text = SCAFFOLD_TEMPLATES.get(args.config)
        if text is None:
            print(f"unknown config {args.config}; have {sorted(SCAFFOLD_TEMPLATES)}")
            return 1
        if args.output:
            path = os.path.join(args.output, f"{args.config}.toml")
            with open(path, "w") as f:
                f.write(text)
            print(f"wrote {path}")
        else:
            print(text)
        return 0


@register
class UploadCommand(Command):
    name = "upload"
    help = "upload local files to the cluster (assign + upload; big files chunked)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("files", nargs="*")
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="master address host:port",
        )
        p.add_argument("-collection", default="", help="collection to upload into")
        p.add_argument("-replication", default="", help="replication policy like 001")
        p.add_argument("-ttl", default="", help="time-to-live like 3m/4h/5d")
        p.add_argument(
            "-maxMB", type=int, default=32,
            help="split uploads into chunks of this many MB",
        )
        p.add_argument(
            "-dir",
            default="",
            help="upload the whole folder recursively (upload.go:41)",
        )
        p.add_argument(
            "-include",
            default="",
            help="glob for files to include under -dir, e.g. *.pdf "
            "(upload.go:42; empty = everything)",
        )

    def run(self, args) -> int:
        import dataclasses
        import fnmatch

        from seaweedfs_tpu.client import operation as op

        paths = list(args.files)
        if args.dir:
            # recursive directory walk, alphabetical like the reference
            for root, dirs, names in os.walk(args.dir):
                dirs.sort()
                for name in sorted(names):
                    if args.include and not fnmatch.fnmatch(
                        name, args.include
                    ):
                        continue
                    paths.append(os.path.join(root, name))
        if not paths:
            print(
                "usage: upload [files...] or upload -dir <folder> "
                "[-include '*.ext']",
                file=sys.stderr,
            )
            return 2
        results = []
        for path in paths:
            with open(path, "rb") as f:
                data = f.read()
            r = op.submit_file(
                args.master,
                os.path.basename(path),
                data,
                collection=args.collection,
                replication=args.replication,
                ttl=args.ttl,
                max_mb=args.maxMB,
            )
            results.append(dataclasses.asdict(r))
        print(json.dumps(results, indent=2))
        return 0


@register
class DownloadCommand(Command):
    name = "download"
    help = "download files by fid"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("fids", nargs="+")
        p.add_argument("-server", default="127.0.0.1:9333", help="master")
        p.add_argument("-dir", default=".", help="output directory for downloads")

    def run(self, args) -> int:
        from seaweedfs_tpu.client import operation as op

        for fid in args.fids:
            url = op.lookup_file_id(args.server, fid)
            data, headers = op.download(url)
            name = headers.get("X-File-Name") or fid.replace(",", "_")
            out = os.path.join(args.dir, name)
            with open(out, "wb") as f:
                f.write(data)
            print(f"{fid} -> {out} ({len(data)} bytes)")
        return 0


@register
class BackupCommand(Command):
    name = "backup"
    help = "incrementally back up one volume from the cluster to local files"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="master address host:port",
        )
        p.add_argument("-volumeId", type=int, required=True, help="volume to back up")
        p.add_argument("-dir", default=".", help="local directory for the backup copy")
        p.add_argument(
            "-collection", default="",
            help="collection the volume belongs to",
        )
        p.add_argument(
            "-ttl",
            default="",
            help="backup volume's TTL when created fresh (backup.go:34; "
            "default: no TTL)",
        )
        p.add_argument(
            "-replication",
            default="",
            help="backup volume's replication setting when created "
            "fresh (backup.go:42)",
        )

    def run(self, args) -> int:
        """Locate the volume, then VolumeIncrementalCopy since our local
        tail, appending raw records and rebuilding the index
        (command/backup.go runBackup semantics)."""
        import grpc

        from seaweedfs_tpu.client import operation as op
        from seaweedfs_tpu.pb import rpc, volume_pb2
        from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
        from seaweedfs_tpu.storage.ttl import TTL
        from seaweedfs_tpu.storage.volume import Volume, volume_base_name

        result = op.lookup(args.master, str(args.volumeId))
        if result.error or not result.locations:
            print(f"volume {args.volumeId} not found: {result.error}")
            return 1
        vol = Volume(
            args.dir,
            args.volumeId,
            args.collection,
            replica_placement=(
                ReplicaPlacement.parse(args.replication)
                if args.replication
                else None
            ),
            ttl=TTL.parse(args.ttl) if args.ttl else None,
        )
        since = vol.last_append_at_ns
        vol.close()
        base = volume_base_name(args.dir, args.collection, args.volumeId)
        url = result.locations[0]["url"]
        appended = 0
        with rpc.dial(rpc.grpc_address(url)) as ch:
            stub = rpc.volume_stub(ch)
            with open(base + ".dat", "ab") as dat:
                for resp in stub.VolumeIncrementalCopy(
                    volume_pb2.VolumeIncrementalCopyRequest(
                        volume_id=args.volumeId, since_ns=since
                    )
                ):
                    dat.write(resp.file_content)
                    appended += len(resp.file_content)
        if appended:
            _rebuild_idx(base)
        print(f"backed up {appended} new bytes into {base}.dat")
        return 0


@register
class CompactCommand(Command):
    name = "compact"
    help = "offline-compact a local volume (drop deleted needles)"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-dir", default=".", help="directory holding the volume files")
        p.add_argument("-volumeId", type=int, required=True, help="volume to compact")
        p.add_argument(
            "-collection", default="",
            help="collection the volume belongs to",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.storage.volume import Volume

        vol = Volume(args.dir, args.volumeId, args.collection)
        before = vol.data_file_size()
        vol.compact()
        vol.commit_compact()
        after = vol.data_file_size()
        vol.close()
        print(f"compacted volume {args.volumeId}: {before} -> {after} bytes")
        return 0


@register
class FixCommand(Command):
    name = "fix"
    help = "rebuild a volume's .idx by scanning its .dat"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-dir", default=".", help="directory holding the volume files")
        p.add_argument("-volumeId", type=int, required=True, help="volume to fix")
        p.add_argument(
            "-collection", default="",
            help="collection the volume belongs to",
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.storage.volume import volume_base_name

        base = volume_base_name(args.dir, args.collection, args.volumeId)
        count = _rebuild_idx(base)
        print(f"rebuilt {base}.idx with {count} entries")
        return 0


def _rebuild_idx(base: str) -> int:
    """Scan <base>.dat and rewrite <base>.idx; a record with size==0 is
    the deletion tombstone delete_needle appends (weed/command/fix.go)."""
    from seaweedfs_tpu.storage import idx as idx_mod, types as t
    from seaweedfs_tpu.storage.volume import scan_volume_file

    entries: dict[int, tuple[int, int]] = {}
    order: list[int] = []
    for needle, offset in scan_volume_file(base + ".dat"):
        if needle.size == 0:
            entries.pop(needle.id, None)
        else:
            if needle.id not in entries:
                order.append(needle.id)
            entries[needle.id] = (t.offset_to_units(offset), needle.size)
    with open(base + ".idx", "wb") as f:
        for key in order:
            if key in entries:
                off_units, size = entries[key]
                f.write(idx_mod.pack_entry(key, off_units, size))
    return len(entries)


@register
class ExportCommand(Command):
    name = "export"
    help = (
        "list needles in a local volume, or export them to a dir / a "
        ".tar (command/export.go)"
    )

    # export.go:44 default tar member name template
    DEFAULT_NAME_FORMAT = "{{.Mime}}/{{.Id}}:{{.Name}}"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument("-dir", default=".", help="directory holding the volume files")
        p.add_argument("-volumeId", type=int, required=True, help="volume to export")
        p.add_argument(
            "-collection", default="",
            help="collection the volume belongs to",
        )
        p.add_argument(
            "-o",
            dest="output",
            default="",
            help="a directory to extract into, a .tar file name, or "
            "'-' for a tar stream on stdout (export.go:57)",
        )
        p.add_argument(
            "-fileNameFormat",
            default=self.DEFAULT_NAME_FORMAT,
            help="tar member name template; fields {{.Name}} {{.Id}} "
            "{{.Mime}} {{.Key}} (export.go:44)",
        )
        p.add_argument(
            "-newer",
            default="",
            help="export only files newer than this RFC3339 time "
            "without timezone, e.g. 2006-01-02T15:04:05 (export.go:59)",
        )

    @classmethod
    def _member_name(cls, fmt: str, needle, vid: int) -> str:
        name = (needle.name or b"").decode("utf-8", "replace")
        mime = (needle.mime or b"").decode("utf-8", "replace")
        return (
            fmt.replace("{{.Name}}", name or f"{needle.id:x}")
            .replace("{{.Id}}", f"{needle.id:x}")
            .replace("{{.Key}}", f"{needle.id:x}")
            .replace("{{.Mime}}", mime or "application/octet-stream")
        )

    def run(self, args) -> int:
        import datetime
        import tarfile

        from seaweedfs_tpu.storage.volume import scan_volume_file, volume_base_name

        newer_than = None
        if args.newer:
            try:
                dt = datetime.datetime.fromisoformat(args.newer)
            except ValueError:
                print(f"cannot parse -newer {args.newer!r}", file=sys.stderr)
                return 2
            if dt.tzinfo is not None:
                # the flag is defined as RFC3339 WITHOUT timezone
                # (export.go:59) — reinterpreting an explicit offset
                # as UTC would silently shift the cutoff
                print(
                    f"-newer {args.newer!r} must not carry a timezone",
                    file=sys.stderr,
                )
                return 2
            newer_than = int(
                dt.replace(tzinfo=datetime.timezone.utc).timestamp()
            )

        base = volume_base_name(args.dir, args.collection, args.volumeId)
        # two passes: resolve final liveness first (later records —
        # overwrites and size==0 tombstones — supersede earlier ones),
        # then emit only each id's surviving record
        final_offset: dict[int, int] = {}
        for needle, off in scan_volume_file(base + ".dat"):
            if needle.size == 0:
                final_offset.pop(needle.id, None)
            else:
                final_offset[needle.id] = off

        tar = None
        to_tar = args.output == "-" or args.output.endswith(".tar")
        if to_tar:
            if args.output == "-":
                tar = tarfile.open(fileobj=sys.stdout.buffer, mode="w|")
            else:
                tar = tarfile.open(args.output, mode="w")
        count = 0
        try:
            for needle, offset in scan_volume_file(base + ".dat"):
                if needle.size == 0 or final_offset.get(needle.id) != offset:
                    continue
                if newer_than is not None and needle.last_modified < newer_than:
                    continue
                name = (needle.name or b"").decode("utf-8", "replace")
                if not to_tar or args.output != "-":
                    print(
                        f"key={needle.id:x} cookie={needle.cookie:08x} "
                        f"size={needle.size} name={name!r} "
                        f"mime={(needle.mime or b'').decode('utf-8', 'replace')!r}"
                    )
                if tar is not None:
                    member = self._member_name(
                        args.fileNameFormat, needle, args.volumeId
                    )
                    if needle.is_gzipped() and not member.endswith(".gz"):
                        # exported bytes stay as stored; the name says
                        # so (export.go:243)
                        member += ".gz"
                    info = tarfile.TarInfo(name=member)
                    info.size = len(needle.data)
                    info.mtime = needle.last_modified or 0
                    import io as _io

                    tar.addfile(info, _io.BytesIO(bytes(needle.data)))
                elif args.output:
                    out = os.path.join(
                        args.output, name or f"{args.volumeId}_{needle.id:x}"
                    )
                    if needle.is_gzipped() and not out.endswith(".gz"):
                        out += ".gz"
                    with open(out, "wb") as f:
                        f.write(needle.data)
                count += 1
        finally:
            if tar is not None:
                tar.close()
        print(f"{count} needles", file=sys.stderr)
        return 0


@register
class WeedloadCommand(Command):
    name = "weedload"
    help = (
        "multi-process closed-loop load harness: assign+PUT / "
        "lookup+GET workers, coordinated-omission-safe histograms, "
        "p50/p99/p99.9 report (telemetry plane, docs/TELEMETRY.md)"
    )

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="master address host:port",
        )
        p.add_argument("-duration", type=float, default=10.0, help="seconds")
        p.add_argument("-writers", type=int, default=2, help="PUT worker processes")
        p.add_argument("-readers", type=int, default=2, help="GET worker processes")
        p.add_argument("-size", type=int, default=1024, help="payload bytes")
        p.add_argument(
            "-rate",
            type=float,
            default=0.0,
            help="per-worker target req/s; >0 paces against a schedule "
            "and measures latency from the SCHEDULED start "
            "(coordinated-omission safe); 0 = unpaced closed loop",
        )
        p.add_argument("-seed", type=int, default=64, help="keys pre-written for GET workers")

    def run(self, args) -> int:
        from seaweedfs_tpu.telemetry.weedload import run_load

        report = run_load(
            args.master,
            duration_s=args.duration,
            writers=args.writers,
            readers=args.readers,
            payload_bytes=args.size,
            rate=args.rate,
            seed_n=args.seed,
        )
        print(json.dumps(report, indent=2))
        errs = sum(report.get(m, {}).get("errors", 0) for m in ("put", "get"))
        ops = sum(report.get(m, {}).get("ops", 0) for m in ("put", "get"))
        # non-zero exit when the run was mostly failures: a load tool
        # that exits 0 while every request 500s hides outages in CI
        return 0 if ops > 0 and errs <= ops else 1
