"""`benchmark` — concurrent write/read load generator with latency
histograms, the reference's perf-testing product feature
(weed/command/benchmark.go:53-66 flags, :377-514 stats printer).
"""

from __future__ import annotations

import argparse
import random
import threading
import time

from seaweedfs_tpu.command import Command, register

PERCENTAGES = (50, 66, 75, 80, 90, 95, 98, 99, 100)


class LatencyStats:
    """Fixed-bucket latency collector mirroring the reference's
    benchmark stats: req/s, MB/s, percentile table, distribution."""

    def __init__(self):
        self._lock = threading.Lock()
        self.latencies_ms: list[float] = []
        self.bytes = 0
        self.completed = 0
        self.failed = 0
        self.start = time.perf_counter()
        # run_benchmark stamps phase end after the worker joins so
        # programmatic callers (bench.py http) can compute req/s from
        # the phase wall, not report() time
        self.ended: float | None = None

    def add(self, latency_sec: float, nbytes: int, ok: bool = True) -> None:
        with self._lock:
            if ok:
                self.completed += 1
                self.bytes += nbytes
                self.latencies_ms.append(latency_sec * 1000.0)
            else:
                self.failed += 1

    def local(self) -> "_LocalStats":
        """Per-worker accumulator: the hot loop records without touching
        the shared lock (the reference streams per-request stats over a
        channel to one aggregator goroutine, benchmark.go:377 — same
        idea: no cross-thread contention per request)."""
        return _LocalStats(self)

    def report(self, title: str, concurrency: int) -> str:
        elapsed = time.perf_counter() - self.start
        lat = sorted(self.latencies_ms)
        n = len(lat)
        lines = [
            f"\n------------ {title} ----------",
            f"Concurrency Level:      {concurrency}",
            f"Time taken for tests:   {elapsed:.3f} seconds",
            f"Complete requests:      {self.completed}",
            f"Failed requests:        {self.failed}",
            f"Total transferred:      {self.bytes} bytes",
            f"Requests per second:    {self.completed / elapsed:.2f} [#/sec]",
            f"Transfer rate:          {self.bytes / 1024.0 / elapsed:.2f} [Kbytes/sec]",
        ]
        if n:
            avg = sum(lat) / n
            std = (sum((x - avg) ** 2 for x in lat) / n) ** 0.5
            lines += [
                "\nConnection Times (ms)",
                "              min      avg        max      std",
                f"Total:        {lat[0]:.1f}      {avg:.1f}       {lat[-1]:.1f}      {std:.1f}",
                "\nPercentage of the requests served within a certain time (ms)",
            ]
            for p in PERCENTAGES:
                idx = min(n - 1, max(0, int(n * p / 100) - 1))
                lines.append(f"   {p}% {lat[idx]:>9.1f} ms")
        return "\n".join(lines)


class _LocalStats:
    __slots__ = ("_parent", "latencies_ms", "bytes", "completed", "failed")

    def __init__(self, parent: LatencyStats):
        self._parent = parent
        self.latencies_ms: list[float] = []
        self.bytes = 0
        self.completed = 0
        self.failed = 0

    def add(self, latency_sec: float, nbytes: int, ok: bool = True) -> None:
        if ok:
            self.completed += 1
            self.bytes += nbytes
            self.latencies_ms.append(latency_sec * 1000.0)
        else:
            self.failed += 1

    def merge(self) -> None:
        p = self._parent
        with p._lock:
            p.completed += self.completed
            p.bytes += self.bytes
            p.failed += self.failed
            p.latencies_ms.extend(self.latencies_ms)


@register
class BenchmarkCommand(Command):
    name = "benchmark"
    help = "load-test the cluster: concurrent writes then random reads"

    def add_arguments(self, p: argparse.ArgumentParser) -> None:
        p.add_argument(
            "-master", default="127.0.0.1:9333",
            help="master address host:port",
        )
        p.add_argument(
            "-c", dest="concurrency", type=int, default=16,
            help="concurrent worker threads",
        )
        p.add_argument(
            "-n", dest="num", type=int, default=1024 * 1024,
            help="total files to write/read",
        )
        p.add_argument("-size", type=int, default=1024, help="payload bytes per file")
        p.add_argument(
            "-collection", default="benchmark",
            help="collection to write into",
        )
        p.add_argument(
            "-replication", default="000",
            help="replication policy like 001",
        )
        # the reference's -write=true/-read=false spelling: single-dash
        # flags get no --no- negative form from BooleanOptionalAction,
        # so write-only / read-only runs need the =bool style
        def _bool(v: str) -> bool:
            return v.lower() not in ("false", "0", "no")

        p.add_argument(
            "-write", type=_bool, nargs="?", const=True, default=True,
            help="=false skips the write phase (read-only run)",
        )
        p.add_argument(
            "-read", type=_bool, nargs="?", const=True, default=True,
            help="=false skips the read phase (write-only run)",
        )
        p.add_argument(
            "-deletePercent", type=int, default=0,
            help="percentage of written files to delete during reads",
        )
        p.add_argument(
            "-cpuprofile", default="", help="dump pstats profile here on exit"
        )

    def run(self, args) -> int:
        from seaweedfs_tpu.command.servers import _tune_gc
        from seaweedfs_tpu.util.profiling import CpuProfile

        _tune_gc()  # the load generator is as hot as the daemons
        with CpuProfile(args.cpuprofile):
            return self._run(args)

    def _run(self, args) -> int:
        stats, fids = run_benchmark(
            master=args.master,
            concurrency=args.concurrency,
            num=args.num,
            size=args.size,
            collection=args.collection,
            replication=args.replication,
            do_write=args.write,
            do_read=args.read,
            delete_percent=args.deletePercent,
        )
        for title, s in stats:
            print(s.report(title, args.concurrency))
        return 0


def run_benchmark(
    master: str,
    concurrency: int = 4,
    num: int = 1024,
    size: int = 1024,
    collection: str = "benchmark",
    replication: str = "000",
    do_write: bool = True,
    do_read: bool = True,
    delete_percent: int = 0,
):
    """Programmatic entry (also used by tests); returns
    ([(title, LatencyStats)], written_fids)."""
    from seaweedfs_tpu.client import operation as op

    results = []
    fids: list[str] = []
    fid_lock = threading.Lock()

    if do_write:
        stats = LatencyStats()
        counter = iter(range(num))
        counter_lock = threading.Lock()
        rng = random.Random(1)
        payload = bytes(rng.randrange(256) for _ in range(size))

        def writer():
            local = stats.local()
            local_fids = []
            while True:
                with counter_lock:
                    try:
                        next(counter)
                    except StopIteration:
                        break
                t0 = time.perf_counter()
                try:
                    ar = op.assign(
                        master, collection=collection, replication=replication
                    )
                    ur = op.upload(
                        f"{ar.url}/{ar.fid}", payload, filename="bench.bin", jwt=ar.auth
                    )
                    ok = not ur.error
                    if ok:
                        if delete_percent and random.randrange(100) < delete_percent:
                            op.delete_files(master, [ar.fid])
                        else:
                            # deleted fids stay out of the read pool so
                            # the read phase doesn't report their 404s
                            # as failures
                            local_fids.append(ar.fid)
                except Exception:
                    ok = False
                local.add(time.perf_counter() - t0, size, ok)
            local.merge()
            with fid_lock:
                fids.extend(local_fids)

        threads = [threading.Thread(target=writer) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats.ended = time.perf_counter()
        results.append((f"Writing Benchmark ({num} x {size}B)", stats))

    if do_read and fids:
        stats = LatencyStats()
        counter = iter(range(num))
        counter_lock = threading.Lock()

        def reader():
            rng = random.Random(threading.get_ident())
            local = stats.local()
            while True:
                with counter_lock:
                    try:
                        next(counter)
                    except StopIteration:
                        break
                fid = rng.choice(fids)
                t0 = time.perf_counter()
                try:
                    url = op.lookup_file_id(master, fid)
                    data, _ = op.download(url)
                    local.add(time.perf_counter() - t0, len(data), True)
                except Exception:
                    local.add(time.perf_counter() - t0, 0, False)
            local.merge()

        threads = [threading.Thread(target=reader) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats.ended = time.perf_counter()
        results.append((f"Random Read Benchmark ({num} reads)", stats))

    return results, fids
