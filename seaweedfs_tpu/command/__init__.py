"""CLI subcommand registry — the `weed` entry point's role
(weed/weed.go:38 + weed/command/command.go:10-29).

Each module registers a Command; `python -m seaweedfs_tpu <name>`
dispatches here. The reference's 19 subcommands and their flags are
mirrored where they make sense for this framework; FUSE mount is gated
on a fuse binding being importable.
"""

from __future__ import annotations

import argparse

COMMANDS: dict[str, "Command"] = {}


class Command:
    name = ""
    help = ""

    def add_arguments(self, parser: argparse.ArgumentParser) -> None:
        pass

    def run(self, args: argparse.Namespace) -> int:
        raise NotImplementedError


def register(cls):
    COMMANDS[cls.name] = cls()
    return cls


def main(argv: list[str] | None = None) -> int:
    # import for registration side effects
    from seaweedfs_tpu.command import (  # noqa: F401
        servers,
        tools,
        benchmark,
        filer_tools,
    )

    parser = argparse.ArgumentParser(
        prog="seaweedfs_tpu",
        description="TPU-native SeaweedFS-capability distributed object store",
    )
    sub = parser.add_subparsers(dest="command")
    for name, cmd in sorted(COMMANDS.items()):
        p = sub.add_parser(name, help=cmd.help)
        cmd.add_arguments(p)
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 2
    return COMMANDS[args.command].run(args) or 0
