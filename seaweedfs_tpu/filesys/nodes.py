"""Dir / File / FileHandle — the FUSE operation surface.

Behavioral port of reference weed/filesys/dir.go, file.go,
filehandle.go, dirty_page.go (libfuse types replaced with plain
Python methods; the mount shim or a real FUSE adapter drives these).

Key behaviors preserved:
  * writes buffer in ContinuousIntervals; when the buffer exceeds
    chunk_size_limit the largest continuous run is flushed as one
    chunk (dirty_page.go AddPage/saveExistingLargestPageToStorage);
    oversized writes flush everything and go to storage directly
    (flushAndSave)
  * reads merge the entry's chunk views with unflushed dirty pages,
    dirty data winning (filehandle.go Read → readFromChunks +
    readFromDirtyPages)
  * flush uploads remaining dirty runs then persists the entry with
    the accumulated chunk list (filehandle.go Flush → CreateEntry);
    the filer's visible-interval algebra resolves overlaps on read
  * truncate drops chunks past the new size (file.go Setattr)
  * rename is the filer's AtomicRenameEntry tx (dir_rename.go)
  * hard links are not in the v0 reference; symlinks are
    (dir_link.go Symlink/Readlink via attributes.symlink_target)
"""

from __future__ import annotations

import time

from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.filesys.page_writer import ContinuousIntervals
from seaweedfs_tpu.filesys.wfs import WFS
from seaweedfs_tpu.pb import filer_pb2 as fpb

S_IFDIR = 0o040000
S_IFREG = 0o100000
S_IFLNK = 0o120000


def _now() -> int:
    return int(time.time())


class FsError(OSError):
    pass


class NotFound(FsError):
    pass


class NotEmpty(FsError):
    pass


class Dir:
    def __init__(self, wfs: WFS, path: str):
        self.wfs = wfs
        self.path = path.rstrip("/") or "/"

    # ------------------------------------------------------------------
    def lookup(self, name: str):
        entry = self.wfs.lookup_entry(self.path, name)
        if entry is None:
            raise NotFound(f"{self.path}/{name}")
        child = f"{self.path}/{name}" if self.path != "/" else f"/{name}"
        if entry.is_directory:
            return Dir(self.wfs, child)
        return File(self.wfs, self, name, entry)

    def readdir(self) -> list[fpb.Entry]:
        return self.wfs.list_entries(self.path)

    def mkdir(self, name: str, mode: int = 0o755) -> "Dir":
        entry = fpb.Entry(
            name=name,
            is_directory=True,
            attributes=fpb.Attributes(
                mtime=_now(),
                crtime=_now(),
                file_mode=S_IFDIR | (mode & 0o777),
                uid=0,
                gid=0,
            ),
        )
        self.wfs.create_entry(self.path, entry)
        child = f"{self.path}/{name}" if self.path != "/" else f"/{name}"
        return Dir(self.wfs, child)

    def create(self, name: str, mode: int = 0o644) -> tuple["File", "FileHandle"]:
        entry = fpb.Entry(
            name=name,
            is_directory=False,
            attributes=fpb.Attributes(
                mtime=_now(),
                crtime=_now(),
                file_mode=S_IFREG | (mode & 0o777),
                collection=self.wfs.option.collection,
                replication=self.wfs.option.replication,
                ttl_sec=self.wfs.option.ttl_sec,
            ),
        )
        self.wfs.create_entry(self.path, entry)
        f = File(self.wfs, self, name, entry)
        return f, f.open()

    def symlink(self, name: str, target: str) -> "File":
        entry = fpb.Entry(
            name=name,
            is_directory=False,
            attributes=fpb.Attributes(
                mtime=_now(),
                crtime=_now(),
                file_mode=S_IFLNK | 0o777,
                symlink_target=target,
            ),
        )
        self.wfs.create_entry(self.path, entry)
        return File(self.wfs, self, name, entry)

    def remove(self, name: str, must_be_empty_dir: bool = False) -> None:
        entry = self.wfs.lookup_entry(self.path, name)
        if entry is None:
            raise NotFound(f"{self.path}/{name}")
        if entry.is_directory and must_be_empty_dir:
            child = f"{self.path}/{name}" if self.path != "/" else f"/{name}"
            if self.wfs.list_entries(child):
                raise NotEmpty(child)
        self.wfs.delete_entry(
            self.path,
            name,
            is_delete_data=True,
            is_recursive=entry.is_directory,
        )

    def rename(self, old_name: str, new_dir: "Dir", new_name: str) -> None:
        self.wfs.atomic_rename(self.path, old_name, new_dir.path, new_name)


class File:
    def __init__(self, wfs: WFS, dir: Dir, name: str, entry: fpb.Entry):
        self.wfs = wfs
        self.dir = dir
        self.name = name
        self.entry = entry

    @property
    def fullpath(self) -> str:
        return f"{self.dir.path}/{self.name}" if self.dir.path != "/" else f"/{self.name}"

    def reload(self) -> None:
        entry = self.wfs.lookup_entry(self.dir.path, self.name)
        if entry is None:
            raise NotFound(self.fullpath)
        self.entry = entry

    def attr(self) -> fpb.Attributes:
        return self.entry.attributes

    @property
    def size(self) -> int:
        # file_size wins once set: truncate may clamp below the chunk
        # total (a kept chunk can span past the new EOF); entries
        # written without an explicit size fall back to the chunk total
        explicit = self.entry.attributes.file_size
        if explicit > 0:
            return explicit
        return filechunks.total_size(list(self.entry.chunks))

    def readlink(self) -> str:
        target = self.entry.attributes.symlink_target
        if not target:
            raise FsError(f"{self.fullpath} is not a symlink")
        return target

    def open(self) -> "FileHandle":
        return FileHandle(self)

    def truncate(self, size: int) -> None:
        """file.go Setattr size branch: drop chunks wholly past the new
        size and clamp file_size."""
        kept = [c for c in self.entry.chunks if c.offset < size]
        del self.entry.chunks[:]
        self.entry.chunks.extend(kept)
        self.entry.attributes.file_size = size
        self.entry.attributes.mtime = _now()
        self.save()

    def set_xattr(self, name: str, value: bytes) -> None:
        self.entry.extended[name] = value
        self.save()

    def get_xattr(self, name: str) -> bytes:
        if name not in self.entry.extended:
            raise NotFound(f"xattr {name}")
        return self.entry.extended[name]

    def list_xattr(self) -> list[str]:
        return sorted(self.entry.extended)

    def remove_xattr(self, name: str) -> None:
        if name in self.entry.extended:
            del self.entry.extended[name]
            self.save()

    def add_chunks(self, chunks) -> None:
        self.entry.chunks.extend(chunks)

    def save(self) -> None:
        self.wfs.update_entry(self.dir.path, self.entry)


class FileHandle:
    """filehandle.go FileHandle + dirty_page.go ContinuousDirtyPages."""

    def __init__(self, f: File):
        self.f = f
        self.dirty = ContinuousIntervals()
        self._dirty_max_end = 0

    # ------------------------------------------------------------------
    def write(self, offset: int, data: bytes) -> int:
        limit = self.f.wfs.option.chunk_size_limit
        if len(data) > limit:
            # more than the buffer can hold: flush existing pages, then
            # save this write straight to storage (flushAndSave)
            self._flush_all_dirty()
            chunk = self.f.wfs.save_data_as_chunk(bytes(data), offset)
            self.f.add_chunks([chunk])
        else:
            self.dirty.add_interval(data, offset)
            while self.dirty.total_size() > limit:
                self._flush_largest()
        self._dirty_max_end = max(self._dirty_max_end, offset + len(data))
        return len(data)

    def read(self, offset: int, size: int) -> bytes:
        """Chunk views first, dirty pages on top (dirty wins)."""
        file_size = max(self.f.size, self._dirty_max_end)
        if offset >= file_size:
            return b""
        size = min(size, file_size - offset)
        buf = bytearray(self.f.wfs.read_chunks(self.f.entry.chunks, offset, size))
        for run in self.dirty.runs:
            lo = max(offset, run.offset)
            hi = min(offset + size, run.end)
            if lo < hi:
                run.read_into(buf, offset, lo, hi)
        return bytes(buf)

    def flush(self) -> None:
        """Upload remaining dirty runs, then persist the entry
        (filehandle.go Flush)."""
        self._flush_all_dirty()
        attrs = self.f.entry.attributes
        attrs.mtime = _now()
        attrs.file_size = max(
            self.f.size, attrs.file_size, self._dirty_max_end
        )
        self.f.save()

    def release(self) -> None:
        self.flush()

    # ------------------------------------------------------------------
    def _flush_largest(self) -> None:
        run = self.dirty.remove_largest_run()
        if run is None:
            return
        chunk = self.f.wfs.save_data_as_chunk(run.to_bytes(), run.offset)
        self.f.add_chunks([chunk])

    def _flush_all_dirty(self) -> None:
        while True:
            run = self.dirty.remove_largest_run()
            if run is None:
                return
            chunk = self.f.wfs.save_data_as_chunk(run.to_bytes(), run.offset)
            self.f.add_chunks([chunk])
