"""FUSE-style file-system layer over the filer (reference weed/filesys/).

Components:
  page_writer   ContinuousIntervals — dirty-page interval buffering
                (dirty_page_interval.go:90)
  wfs           WFS — filer gRPC client, entry cache, chunk IO
                (wfs.go:46-70)
  nodes         Dir / File / FileHandle — the FUSE operation surface
                (dir.go, file.go, filehandle.go)
  mount         MountedFileSystem — libfuse-free in-process POSIX-style
                facade over the node layer, plus an optional real FUSE
                adapter when a fuse binding is importable
                (command/mount_std.go role)
"""

from seaweedfs_tpu.filesys.mount import MountedFileSystem  # noqa: F401
from seaweedfs_tpu.filesys.page_writer import ContinuousIntervals  # noqa: F401
from seaweedfs_tpu.filesys.wfs import WFS, WfsOption  # noqa: F401
