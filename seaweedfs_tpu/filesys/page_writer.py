"""Dirty-page interval buffering for the write path.

Behavioral port of reference weed/filesys/dirty_page_interval.go:
ContinuousIntervals keeps written-but-unflushed byte ranges as a set
of *continuous runs*, each run a chain of non-overlapping nodes in
offset order. AddInterval resolves overlap by slicing existing runs
down to their uncovered left/right remainders, then splices the new
node onto an adjacent run (or bridges two runs into one). Reads give
the newest data for any covered range; the largest run is flushed
first when the buffer exceeds the chunk-size limit
(dirty_page.go saveExistingLargestPageToStorage).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class _Node:
    offset: int
    data: bytes

    @property
    def end(self) -> int:
        return self.offset + len(self.data)


@dataclass
class IntervalRun:
    """One continuous byte range built from ordered adjacent nodes
    (IntervalLinkedList)."""

    nodes: list[_Node] = field(default_factory=list)

    @property
    def offset(self) -> int:
        return self.nodes[0].offset

    @property
    def end(self) -> int:
        return self.nodes[-1].end

    @property
    def size(self) -> int:
        return self.end - self.offset

    def read_into(self, buf: bytearray, buf_start: int, start: int, stop: int) -> None:
        """Copy this run's bytes overlapping [start, stop) into buf
        (positioned so that file offset `buf_start` is buf[0])."""
        for node in self.nodes:
            lo = max(start, node.offset)
            hi = min(stop, node.end)
            if lo < hi:
                buf[lo - buf_start : hi - buf_start] = node.data[
                    lo - node.offset : hi - node.offset
                ]

    def sub_run(self, start: int, stop: int) -> "IntervalRun":
        """The [start, stop) slice of this run (subList)."""
        nodes = []
        for node in self.nodes:
            lo = max(start, node.offset)
            hi = min(stop, node.end)
            if lo < hi:
                nodes.append(_Node(lo, node.data[lo - node.offset : hi - node.offset]))
        return IntervalRun(nodes)

    def to_bytes(self) -> bytes:
        return b"".join(n.data for n in self.nodes)


class ContinuousIntervals:
    """The dirty-page buffer (ContinuousIntervals, dirty_page_interval.go)."""

    def __init__(self) -> None:
        self.runs: list[IntervalRun] = []

    def total_size(self) -> int:
        return sum(r.size for r in self.runs)

    def add_interval(self, data: bytes, offset: int) -> None:
        """Insert a write of `data` at `offset`, newest-wins."""
        new_node = _Node(offset, bytes(data))
        end = new_node.end

        kept: list[IntervalRun] = []
        for run in self.runs:
            if run.end <= offset or end <= run.offset:
                kept.append(run)  # disjoint: keep whole
                continue
            # keep the uncovered left remainder
            if run.offset < offset:
                kept.append(run.sub_run(run.offset, offset))
            # keep the uncovered right remainder
            if end < run.end:
                kept.append(run.sub_run(end, run.end))
            # fully covered parts are dropped
        self.runs = kept

        prev = next_ = None
        for run in self.runs:
            if run.end == offset:
                prev = run
            elif run.offset == end:
                next_ = run

        if prev is not None and next_ is not None:
            prev.nodes.append(new_node)
            prev.nodes.extend(next_.nodes)
            self.runs.remove(next_)
        elif prev is not None:
            prev.nodes.append(new_node)
        elif next_ is not None:
            next_.nodes.insert(0, new_node)
        else:
            self.runs.append(IntervalRun([new_node]))

    def read_data(self, size: int, start_offset: int) -> tuple[int, int, bytearray]:
        """Fill up to `size` bytes from `start_offset`; returns
        (covered_offset, covered_size, buf) where buf holds the window
        [start_offset, start_offset+size) with dirty bytes copied in
        (uncovered gaps stay zero, same contract as ReadData)."""
        buf = bytearray(size)
        min_off = None
        max_stop = 0
        for run in self.runs:
            lo = max(start_offset, run.offset)
            hi = min(start_offset + size, run.end)
            if lo <= hi:
                run.read_into(buf, start_offset, lo, hi)
                min_off = lo if min_off is None else min(min_off, lo)
                max_stop = max(max_stop, hi)
        if min_off is None:
            return 0, 0, buf
        return min_off, max_stop - min_off, buf

    def remove_largest_run(self) -> IntervalRun | None:
        """Pop the largest continuous run for flushing
        (RemoveLargestIntervalLinkedList)."""
        if not self.runs:
            return None
        largest = max(self.runs, key=lambda r: r.size)
        if largest.size <= 0:
            return None
        self.runs.remove(largest)
        return largest
