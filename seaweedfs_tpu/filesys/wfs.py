"""WFS — the mounted file system's filer client and chunk IO engine.

Role match of reference weed/filesys/wfs.go:46-70: holds the mount
options, a filer gRPC channel, a TTL'd entry-attribute cache, and the
data-plane helpers the nodes use:

  * metadata verbs → filer gRPC (LookupDirectoryEntry, ListEntries,
    Create/Update/DeleteEntry, AtomicRenameEntry)
  * chunk writes   → filer AssignVolume then volume-server HTTP POST
    with the assign-issued write JWT (dirty_page.go saveToStorage)
  * chunk reads    → filer LookupVolume then volume-server HTTP GET,
    assembled through the filer chunk algebra (filehandle.go
    readFromChunks → filer2.ViewFromChunks)
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request

import grpc

from seaweedfs_tpu.client import operation as op
from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.pb import filer_pb2 as fpb
from seaweedfs_tpu.pb import rpc
from seaweedfs_tpu.pb.rpc import grpc_address


class WfsOption:
    """Mount options (wfs.go Option)."""

    def __init__(
        self,
        filer: str,
        filer_mount_root_path: str = "/",
        collection: str = "",
        replication: str = "",
        ttl_sec: int = 0,
        chunk_size_limit: int = 4 * 1024 * 1024,
        entry_cache_ttl: float = 1.0,
    ):
        self.filer = filer  # "host:port" (HTTP); gRPC = port + 10000
        self.filer_mount_root_path = filer_mount_root_path.rstrip("/") or "/"
        self.collection = collection
        self.replication = replication
        self.ttl_sec = ttl_sec
        self.chunk_size_limit = chunk_size_limit
        self.entry_cache_ttl = entry_cache_ttl


class WFS:
    def __init__(self, option: WfsOption):
        self.option = option
        self._channel = rpc.dial(grpc_address(option.filer))
        self._stub = rpc.filer_stub(self._channel)
        # full path -> (entry, expires); invalidated on every mutation
        self._entry_cache: dict[str, tuple[fpb.Entry, float]] = {}

    def close(self) -> None:
        self._channel.close()

    # ------------------------------------------------------------------
    # metadata
    def lookup_entry(self, directory: str, name: str) -> fpb.Entry | None:
        path = f"{directory.rstrip('/')}/{name}"
        cached = self._entry_cache.get(path)
        if cached and cached[1] > time.monotonic():
            return cached[0]
        try:
            resp = self._stub.LookupDirectoryEntry(
                fpb.LookupDirectoryEntryRequest(directory=directory, name=name)
            )
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.NOT_FOUND:
                return None
            raise
        if not resp.entry.name:
            return None
        self._entry_cache[path] = (
            resp.entry,
            time.monotonic() + self.option.entry_cache_ttl,
        )
        return resp.entry

    def list_entries(self, directory: str) -> list[fpb.Entry]:
        return [
            r.entry
            for r in self._stub.ListEntries(
                fpb.ListEntriesRequest(directory=directory)
            )
        ]

    def create_entry(self, directory: str, entry: fpb.Entry) -> None:
        self._stub.CreateEntry(
            fpb.CreateEntryRequest(directory=directory, entry=entry)
        )
        self._invalidate(f"{directory.rstrip('/')}/{entry.name}")

    def update_entry(self, directory: str, entry: fpb.Entry) -> None:
        self._stub.UpdateEntry(
            fpb.UpdateEntryRequest(directory=directory, entry=entry)
        )
        self._invalidate(f"{directory.rstrip('/')}/{entry.name}")

    def delete_entry(
        self,
        directory: str,
        name: str,
        is_delete_data: bool = True,
        is_recursive: bool = False,
    ) -> None:
        self._stub.DeleteEntry(
            fpb.DeleteEntryRequest(
                directory=directory,
                name=name,
                is_delete_data=is_delete_data,
                is_recursive=is_recursive,
            )
        )
        self._invalidate(f"{directory.rstrip('/')}/{name}")

    def atomic_rename(
        self, old_dir: str, old_name: str, new_dir: str, new_name: str
    ) -> None:
        self._stub.AtomicRenameEntry(
            fpb.AtomicRenameEntryRequest(
                old_directory=old_dir,
                old_name=old_name,
                new_directory=new_dir,
                new_name=new_name,
            )
        )
        self._invalidate(f"{old_dir.rstrip('/')}/{old_name}")
        self._invalidate(f"{new_dir.rstrip('/')}/{new_name}")

    def _invalidate(self, path: str) -> None:
        self._entry_cache.pop(path, None)

    # ------------------------------------------------------------------
    # chunk data plane
    def save_data_as_chunk(self, data: bytes, offset: int) -> fpb.FileChunk:
        """Assign a fid and upload one chunk (dirty_page.go
        saveToStorage)."""
        resp = self._stub.AssignVolume(
            fpb.AssignVolumeRequest(
                count=1,
                collection=self.option.collection,
                replication=self.option.replication,
                ttl_sec=self.option.ttl_sec,
            )
        )
        ur = op.upload(f"{resp.url}/{resp.fid}", data, jwt=resp.auth)
        if ur.error:
            raise IOError(f"upload chunk: {ur.error}")
        return filechunks.make_chunk(
            resp.fid, offset, len(data), time.time_ns(), e_tag=ur.etag
        )

    def _volume_url(self, vid: str) -> str:
        resp = self._stub.LookupVolume(
            fpb.LookupVolumeRequest(volume_ids=[vid])
        )
        locs = resp.locations_map.get(vid)
        if locs is None or not locs.locations:
            raise IOError(f"volume {vid} not found")
        return locs.locations[0].url

    def read_chunks(self, chunks, offset: int, size: int) -> bytes:
        """Assemble [offset, offset+size) from the entry's chunk list
        through the visible-interval algebra; gaps read as zeros
        (sparse-file semantics, filer2/stream.go)."""
        buf = bytearray(size)
        for view in filechunks.view_from_chunks(list(chunks), offset, size):
            vid = view.fid.split(",")[0]
            url = self._volume_url(vid)
            try:
                with urllib.request.urlopen(
                    f"http://{url}/{view.fid}", timeout=30
                ) as r:
                    blob = r.read()
            except urllib.error.HTTPError as e:
                raise IOError(f"read chunk {view.fid}: {e}") from e
            piece = blob[view.offset : view.offset + view.size]
            start = view.logic_offset - offset
            buf[start : start + len(piece)] = piece
        return bytes(buf)
