"""Mount layer: libfuse-free in-process facade + optional FUSE adapter.

MountedFileSystem exposes POSIX-style calls (open/read/write/mkdir/
listdir/stat/rename/unlink/truncate/symlink/xattr) over the Dir/File/
FileHandle node layer — the full VFS without a kernel mount, so the
write-buffering/flush/rename semantics are testable in-process
(command/mount_std.go role; the v0 reference can only test these
through a real kernel mount, which it does not do in CI either).

mount_fuse() bridges the same node layer to a real kernel mountpoint
when a `fuse` binding (fusepy) is importable; this environment ships
none, so it is gated with a clear error rather than a dead stub.
"""

from __future__ import annotations

import posixpath

from seaweedfs_tpu.filesys.nodes import (
    Dir,
    FileHandle,
    FsError,
    NotFound,
    S_IFDIR,
)
from seaweedfs_tpu.filesys.wfs import WFS, WfsOption


class OpenFile:
    """A python-file-like wrapper with a cursor over a FileHandle."""

    def __init__(self, handle: FileHandle, append: bool = False):
        self._h = handle
        self._pos = handle.f.size if append else 0
        self.closed = False

    def read(self, size: int = -1) -> bytes:
        if size < 0:
            size = max(self._h.f.size, self._h._dirty_max_end) - self._pos
            size = max(size, 0)
        data = self._h.read(self._pos, size)
        self._pos += len(data)
        return data

    def write(self, data: bytes) -> int:
        n = self._h.write(self._pos, data)
        self._pos += n
        return n

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 0:
            self._pos = pos
        elif whence == 1:
            self._pos += pos
        elif whence == 2:
            self._pos = max(self._h.f.size, self._h._dirty_max_end) + pos
        return self._pos

    def tell(self) -> int:
        return self._pos

    def flush(self) -> None:
        self._h.flush()

    def close(self) -> None:
        if not self.closed:
            self._h.release()
            self.closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MountedFileSystem:
    """The in-process mount: path-string API over the node layer."""

    def __init__(self, option: WfsOption):
        self.wfs = WFS(option)
        self.root = option.filer_mount_root_path

    def close(self) -> None:
        self.wfs.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------------
    def _full(self, path: str) -> str:
        path = posixpath.normpath("/" + path.strip("/"))
        if self.root != "/":
            return self.root + ("" if path == "/" else path)
        return path

    def _split(self, path: str) -> tuple[str, str]:
        full = self._full(path)
        d, name = posixpath.split(full)
        return d or "/", name

    def _dir(self, path: str) -> Dir:
        return Dir(self.wfs, self._full(path))

    # ------------------------------------------------------------------
    def open(self, path: str, mode: str = "r") -> OpenFile:
        """Modes: r (read), w (create/truncate), a (append), r+ (rw)."""
        d, name = self._split(path)
        parent = Dir(self.wfs, d)
        entry = self.wfs.lookup_entry(d, name)
        if "w" in mode:
            if entry is not None:
                parent.remove(name)
            _, handle = parent.create(name)
            return OpenFile(handle)
        if entry is None:
            if "a" in mode:
                _, handle = parent.create(name)
                return OpenFile(handle)
            raise NotFound(path)
        node = parent.lookup(name)
        if isinstance(node, Dir):
            raise FsError(f"{path} is a directory")
        return OpenFile(node.open(), append=("a" in mode))

    def read_file(self, path: str) -> bytes:
        with self.open(path, "r") as f:
            return f.read()

    def write_file(self, path: str, data: bytes) -> None:
        with self.open(path, "w") as f:
            f.write(data)

    # ------------------------------------------------------------------
    def mkdir(self, path: str, mode: int = 0o755) -> None:
        d, name = self._split(path)
        Dir(self.wfs, d).mkdir(name, mode)

    def makedirs(self, path: str) -> None:
        parts = [p for p in self._full(path).split("/") if p]
        cur = ""
        for p in parts:
            parent, cur = cur or "/", f"{cur}/{p}"
            if self.wfs.lookup_entry(parent, p) is None:
                Dir(self.wfs, parent).mkdir(p)

    def listdir(self, path: str = "/") -> list[str]:
        return [e.name for e in Dir(self.wfs, self._full(path)).readdir()]

    def stat(self, path: str):
        d, name = self._split(path)
        if name == "":
            # the root
            return type("Stat", (), {"is_dir": True, "size": 0, "mode": S_IFDIR})()
        entry = self.wfs.lookup_entry(d, name)
        if entry is None:
            raise NotFound(path)
        from seaweedfs_tpu.filer import filechunks

        size = entry.attributes.file_size or filechunks.total_size(
            list(entry.chunks)
        )
        return type(
            "Stat",
            (),
            {
                "is_dir": entry.is_directory,
                "size": size,
                "mode": entry.attributes.file_mode,
                "mtime": entry.attributes.mtime,
                "uid": entry.attributes.uid,
                "gid": entry.attributes.gid,
            },
        )()

    def exists(self, path: str) -> bool:
        try:
            self.stat(path)
            return True
        except NotFound:
            return False

    def unlink(self, path: str) -> None:
        d, name = self._split(path)
        Dir(self.wfs, d).remove(name)

    def rmdir(self, path: str) -> None:
        d, name = self._split(path)
        Dir(self.wfs, d).remove(name, must_be_empty_dir=True)

    def rename(self, old: str, new: str) -> None:
        od, on = self._split(old)
        nd, nn = self._split(new)
        Dir(self.wfs, od).rename(on, Dir(self.wfs, nd), nn)

    def truncate(self, path: str, size: int) -> None:
        d, name = self._split(path)
        node = Dir(self.wfs, d).lookup(name)
        if isinstance(node, Dir):
            raise FsError(f"{path} is a directory")
        node.truncate(size)

    def symlink(self, target: str, link_path: str) -> None:
        d, name = self._split(link_path)
        Dir(self.wfs, d).symlink(name, target)

    def readlink(self, path: str) -> str:
        d, name = self._split(path)
        node = Dir(self.wfs, d).lookup(name)
        if isinstance(node, Dir):
            raise FsError(f"{path} is a directory")
        return node.readlink()

    # xattr ------------------------------------------------------------
    def setxattr(self, path: str, name: str, value: bytes) -> None:
        d, fname = self._split(path)
        node = Dir(self.wfs, d).lookup(fname)
        node.set_xattr(name, value)

    def getxattr(self, path: str, name: str) -> bytes:
        d, fname = self._split(path)
        return Dir(self.wfs, d).lookup(fname).get_xattr(name)

    def listxattr(self, path: str) -> list[str]:
        d, fname = self._split(path)
        return Dir(self.wfs, d).lookup(fname).list_xattr()


def mount_fuse(option: WfsOption, mountpoint: str, foreground: bool = True):
    """Kernel mount via fusepy when available (weed mount role).

    The adapter maps the fusepy Operations callbacks onto
    MountedFileSystem; it is import-gated because this environment
    ships no FUSE binding (the in-process facade above carries the
    test coverage either way)."""
    try:
        import errno

        import fuse
    except ImportError as e:
        raise RuntimeError(
            "no FUSE binding (fusepy) available; use MountedFileSystem "
            "for the in-process VFS, or install fusepy for a kernel mount"
        ) from e

    mfs = MountedFileSystem(option)

    class _Ops(fuse.Operations):
        def __init__(self):
            self._handles: dict[int, OpenFile] = {}
            self._next = 1

        # --- metadata ---
        def getattr(self, path, fh=None):
            try:
                st = mfs.stat(path)
            except NotFound:
                raise fuse.FuseOSError(errno.ENOENT)
            mode = st.mode or (S_IFDIR | 0o755 if st.is_dir else 0o100644)
            return {
                "st_mode": mode,
                "st_size": st.size,
                "st_mtime": getattr(st, "mtime", 0),
                "st_uid": getattr(st, "uid", 0),
                "st_gid": getattr(st, "gid", 0),
                "st_nlink": 2 if st.is_dir else 1,
            }

        def readdir(self, path, fh):
            return [".", ".."] + mfs.listdir(path)

        def mkdir(self, path, mode):
            mfs.mkdir(path, mode)

        def rmdir(self, path):
            mfs.rmdir(path)

        def unlink(self, path):
            mfs.unlink(path)

        def rename(self, old, new):
            mfs.rename(old, new)

        def truncate(self, path, length, fh=None):
            mfs.truncate(path, length)

        def symlink(self, link_path, target):
            mfs.symlink(target, link_path)

        def readlink(self, path):
            return mfs.readlink(path)

        # --- data ---
        def create(self, path, mode, fi=None):
            f = mfs.open(path, "w")
            fh = self._next
            self._next += 1
            self._handles[fh] = f
            return fh

        def open(self, path, flags):
            import os as _os

            mode = "r+" if flags & (_os.O_RDWR | _os.O_WRONLY) else "r"
            f = mfs.open(path, mode)
            fh = self._next
            self._next += 1
            self._handles[fh] = f
            return fh

        def read(self, path, size, offset, fh):
            f = self._handles[fh]
            f.seek(offset)
            return f.read(size)

        def write(self, path, data, offset, fh):
            f = self._handles[fh]
            f.seek(offset)
            return f.write(data)

        def flush(self, path, fh):
            if fh in self._handles:
                self._handles[fh].flush()

        def release(self, path, fh):
            f = self._handles.pop(fh, None)
            if f is not None:
                f.close()

        # --- xattr ---
        def getxattr(self, path, name, position=0):
            try:
                return mfs.getxattr(path, name)
            except NotFound:
                raise fuse.FuseOSError(getattr(errno, "ENODATA", errno.ENOENT))

        def setxattr(self, path, name, value, options, position=0):
            mfs.setxattr(path, name, value)

        def listxattr(self, path):
            return mfs.listxattr(path)

    return fuse.FUSE(_Ops(), mountpoint, foreground=foreground, nothreads=True)
