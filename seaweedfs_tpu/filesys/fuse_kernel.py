"""Kernel FUSE transport: the wire protocol on /dev/fuse, no libfuse.

`weed mount` in the reference attaches the WFS to a real mountpoint
through the FUSE kernel module (command/mount_std.go:27 via
bazil.org/fuse, itself a from-scratch wire-protocol implementation —
the same choice made here). This module speaks that protocol directly:

  * mount(2) with fstype "fuse", passing the opened /dev/fuse fd and
    rootmode/user_id/group_id options (what fusermount does under the
    hood; this process runs with CAP_SYS_ADMIN in the target images);
  * a single-threaded request loop reading fuse_in_header-framed
    requests and dispatching ~25 opcodes onto the existing
    MountedFileSystem path API (filesys/mount.py) — the node layer,
    dirty-page pipeline, and filer RPCs underneath are exactly the
    ones the in-process facade exercises in CI;
  * nodeids are handed out per path and remapped on rename, mirroring
    bazil/fs's NodeRef bookkeeping (wfs.go:46-70 registers the same
    maps).

Struct layouts follow include/uapi/linux/fuse.h at interface 7.31
(declared in our INIT reply; the kernel feature-gates accordingly).
Gated at runtime on /dev/fuse being openable — sandboxes without the
device keep the in-process MountedFileSystem surface.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import stat as stat_mod
import struct
import threading

from collections import deque
from concurrent.futures import ThreadPoolExecutor

from seaweedfs_tpu.filesys.mount import MountedFileSystem, OpenFile
from seaweedfs_tpu.filesys.nodes import NotEmpty, NotFound
from seaweedfs_tpu.util import wlog

# --- wire structs (uapi/linux/fuse.h), little-endian ----------------------

_IN_HDR = struct.Struct("<IIQQIIII")  # len opcode unique nodeid uid gid pid pad
_OUT_HDR = struct.Struct("<IiQ")  # len error unique
_ATTR = struct.Struct("<QQQQQQIIIIIIIIII")  # 88 bytes (7.9+ with blksize)
_ENTRY_OUT = struct.Struct("<QQQQII")  # nodeid gen entry_valid attr_valid nsecs
_INIT_IN = struct.Struct("<IIII")
_INIT_OUT = struct.Struct("<IIIIHHIIHHI28s")  # 64 bytes (7.23+ layout)
_GETATTR_IN = struct.Struct("<IIQ")
_SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")
_MKDIR_IN = struct.Struct("<II")
_RENAME_IN = struct.Struct("<Q")
_OPEN_IN = struct.Struct("<II")
_OPEN_OUT = struct.Struct("<QII")  # fh, open_flags, padding — 16 bytes
_READ_IN = struct.Struct("<QQIIQII")
_WRITE_IN = struct.Struct("<QQIIQII")
_WRITE_OUT = struct.Struct("<II")
_RELEASE_IN = struct.Struct("<QIIQ")
_FLUSH_IN = struct.Struct("<QIIQ")
_FSYNC_IN = struct.Struct("<QII")
_KSTATFS = struct.Struct("<QQQQQQIIII24x")
_GETXATTR_IN = struct.Struct("<II")
_CREATE_IN = struct.Struct("<IIII")
_DIRENT_HDR = struct.Struct("<QQII")

# opcodes
LOOKUP, FORGET, GETATTR, SETATTR, READLINK, SYMLINK = 1, 2, 3, 4, 5, 6
MKDIR, UNLINK, RMDIR, RENAME, LINK, OPEN, READ, WRITE = 9, 10, 11, 12, 13, 14, 15, 16
STATFS, RELEASE, FSYNC, SETXATTR, GETXATTR, LISTXATTR = 17, 18, 20, 21, 22, 23
REMOVEXATTR, FLUSH, INIT, OPENDIR, READDIR, RELEASEDIR = 24, 25, 26, 27, 28, 29
FSYNCDIR, ACCESS, CREATE, INTERRUPT, DESTROY, RENAME2 = 30, 34, 35, 36, 38, 45
BATCH_FORGET = 42
_NO_REPLY = {FORGET, BATCH_FORGET, INTERRUPT}

FATTR_MODE, FATTR_UID, FATTR_GID, FATTR_SIZE = 1 << 0, 1 << 1, 1 << 2, 1 << 3

_MAX_WRITE = 128 * 1024
_TTL_SEC = 1  # entry/attr cache validity handed to the kernel


class FuseProtocolError(RuntimeError):
    pass


def _libc():
    return ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6", use_errno=True)


def kernel_fuse_available() -> bool:
    """True when this process can open /dev/fuse (the runtime gate)."""
    try:
        fd = os.open("/dev/fuse", os.O_RDWR)
    except OSError:
        return False
    os.close(fd)
    return True


class _NodeStrand:
    """FIFO of pending requests for one nodeid (the per-node ordered
    queue that keeps concurrent dispatch safe: ops on the same node —
    WRITE sequences on a file, LOOKUP vs UNLINK on a name — run in
    arrival order, while different nodes run in parallel)."""

    __slots__ = ("queue", "active")

    def __init__(self):
        self.queue: deque = deque()
        self.active = False


class KernelFuseMount:
    """One kernel mountpoint served by a MountedFileSystem.

    Requests are decoded on the reader thread and dispatched onto a
    small thread pool (bazil.org/fuse spawns a goroutine per request
    behind the reference's wfs, fs/serve.go — same concurrency model,
    bounded): a READ blocked on a chunk fetch over HTTP no longer
    stalls an unrelated GETATTR. Per-nodeid strands keep same-node
    ordering; FORGET/BATCH_FORGET mutate only the node tables and run
    inline on the reader thread under the same lock the pool uses."""

    POOL_WORKERS = 8

    def __init__(self, mfs: MountedFileSystem, mountpoint: str):
        self.mfs = mfs
        self.mountpoint = os.path.abspath(mountpoint)
        self._fd = -1
        self._nodes: dict[int, str] = {1: "/"}  # nodeid -> mfs path
        self._ids: dict[str, int] = {"/": 1}
        self._nlookup: dict[int, int] = {}  # kernel reference counts
        self._next_node = 2
        self._handles: dict[int, OpenFile] = {}
        self._dirbufs: dict[int, bytes] = {}
        self._next_fh = 1
        self._alive = False
        self._thread: threading.Thread | None = None
        # concurrency plumbing (see class docstring)
        self._maps_lock = threading.RLock()  # node/handle table guard
        self._strands: dict[int, _NodeStrand] = {}
        self._strand_lock = threading.Lock()
        self._pool: ThreadPoolExecutor | None = None

    # --- mount / unmount --------------------------------------------------
    def mount(self) -> None:
        self._fd = os.open("/dev/fuse", os.O_RDWR)
        opts = (
            f"fd={self._fd},rootmode=40000,"
            f"user_id={os.getuid()},group_id={os.getgid()},"
            f"max_read={_MAX_WRITE}"
        ).encode()
        libc = _libc()
        ret = libc.mount(
            b"seaweedfs", self.mountpoint.encode(), b"fuse.seaweedfs", 0, opts
        )
        if ret != 0:
            err = ctypes.get_errno()
            os.close(self._fd)
            self._fd = -1
            raise FuseProtocolError(
                f"mount({self.mountpoint}): {os.strerror(err)} "
                "(needs CAP_SYS_ADMIN; as non-root use fusermount)"
            )
        self._alive = True

    def unmount(self) -> None:
        self._alive = False
        libc = _libc()
        MNT_DETACH = 2
        # order matters: umount first (wakes the serve thread's blocked
        # read with ENODEV), join it, and only THEN close the fd — a
        # close while the thread may still enter os.read would race the
        # fd number being recycled into an unrelated descriptor
        libc.umount2(self.mountpoint.encode(), MNT_DETACH)
        stuck = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            stuck = self._thread.is_alive()
            # weedlint: ignore[race-check-then-act] — mount lifecycle is single-owner: only the mounting thread calls serve_background/unmount; the serve thread never writes _thread, so there is no second writer to race
            self._thread = None
        if self._fd >= 0 and not stuck:
            # a stuck serve thread (wedged backend RPC) keeps the fd
            # leaked rather than closed under it — see serve_forever
            try:
                os.close(self._fd)
            except OSError:
                pass
            # weedlint: ignore[race-check-then-act] — same single-owner lifecycle: _fd is written by mount() and unmount() on the owner thread; the serve thread only reads it
            self._fd = -1

    def serve_background(self) -> None:
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()

    # --- request loop -----------------------------------------------------
    def serve_forever(self) -> None:
        bufsize = _MAX_WRITE + 4096
        self._pool = ThreadPoolExecutor(
            max_workers=self.POOL_WORKERS, thread_name_prefix="fuse"
        )
        try:
            while self._alive:
                try:
                    req = os.read(self._fd, bufsize)
                except OSError as e:
                    if e.errno == errno.ENODEV:  # unmounted
                        break
                    if e.errno in (errno.EINTR, errno.EAGAIN):
                        continue
                    break
                if len(req) < _IN_HDR.size:
                    continue
                (_len, opcode, unique, nodeid, uid, gid, _pid, _pad) = (
                    _IN_HDR.unpack_from(req)
                )
                body = req[_IN_HDR.size : _len]
                if opcode in _NO_REPLY or opcode in (INIT, DESTROY):
                    # node-table-only (or handshake) ops: inline on the
                    # reader thread, under the same lock the pool uses
                    self._handle_one(opcode, nodeid, unique, body)
                    if opcode == DESTROY:
                        break
                    continue
                self._enqueue(nodeid, (opcode, nodeid, unique, body))
        finally:
            # drain in-flight handlers BEFORE unmount() may close the
            # fuse fd: a pending _reply on a recycled fd number would
            # write into an unrelated descriptor
            self._pool.shutdown(wait=True)

    # --- concurrent dispatch (per-nodeid strands) --------------------------
    def _enqueue(self, nodeid: int, item: tuple) -> None:
        with self._strand_lock:
            strand = self._strands.get(nodeid)
            if strand is None:
                strand = self._strands[nodeid] = _NodeStrand()
            strand.queue.append(item)
            if strand.active:
                return  # the draining worker will pick it up
            strand.active = True
        self._pool.submit(self._drain_strand, nodeid, strand)

    def _drain_strand(self, nodeid: int, strand: _NodeStrand) -> None:
        while True:
            with self._strand_lock:
                if not strand.queue:
                    strand.active = False
                    if self._strands.get(nodeid) is strand:
                        del self._strands[nodeid]
                    return
                item = strand.queue.popleft()
            self._handle_one(*item)

    def _handle_one(self, opcode: int, nodeid: int, unique: int, body: bytes) -> None:
        try:
            out = self._dispatch(opcode, nodeid, body)
        except NotFound:
            out = -errno.ENOENT
        except NotEmpty:
            out = -errno.ENOTEMPTY
        except FileExistsError:
            out = -errno.EEXIST
        except IsADirectoryError:
            out = -errno.EISDIR
        except KeyError:
            out = -errno.ENOENT
        except OSError as e:
            out = -(e.errno or errno.EIO)
        except Exception as e:  # noqa: BLE001 — a 500 is EIO, not a crash
            wlog.warning("fuse op %d failed: %s", opcode, e)
            out = -errno.EIO
        if opcode in _NO_REPLY:
            return
        if isinstance(out, int):
            self._reply_err(unique, out)
        else:
            self._reply(unique, out)

    def _reply(self, unique: int, payload: bytes) -> None:
        try:
            os.write(
                self._fd,
                _OUT_HDR.pack(_OUT_HDR.size + len(payload), 0, unique) + payload,
            )
        except OSError:
            pass  # request aborted (e.g. interrupted read)

    def _reply_err(self, unique: int, negerrno: int) -> None:
        try:
            os.write(self._fd, _OUT_HDR.pack(_OUT_HDR.size, negerrno, unique))
        except OSError:
            pass

    # --- node bookkeeping ---------------------------------------------------
    def _path(self, nodeid: int) -> str:
        with self._maps_lock:
            return self._nodes[nodeid]

    def _node_for(self, path: str) -> int:
        with self._maps_lock:
            nid = self._ids.get(path)
            if nid is None:
                nid = self._next_node
                self._next_node += 1
                self._ids[path] = nid
                self._nodes[nid] = path
            return nid

    def _child(self, nodeid: int, name: str) -> str:
        parent = self._path(nodeid)
        return (parent.rstrip("/") + "/" + name) if name else parent

    def _rekey(self, old: str, new: str) -> None:
        """Rename moved a subtree: remap every known path under it."""
        prefix = old.rstrip("/") + "/"
        with self._maps_lock:
            for nid, p in list(self._nodes.items()):
                if p == old or p.startswith(prefix):
                    np = new + p[len(old) :]
                    del self._ids[p]
                    self._ids[np] = nid
                    self._nodes[nid] = np

    # --- attr marshalling ---------------------------------------------------
    def _attr_bytes(self, path: str, nodeid: int) -> bytes:
        st = self.mfs.stat(path)
        size = st.size
        mode = st.mode or 0
        if st.is_dir:
            mode = stat_mod.S_IFDIR | (mode & 0o7777 or 0o755)
        elif not stat_mod.S_IFMT(mode):
            mode |= stat_mod.S_IFREG
        if not (mode & 0o7777):
            mode |= 0o644
        mtime = int(getattr(st, "mtime", 0) or 0)
        return _ATTR.pack(
            nodeid,  # ino
            size,
            (size + 511) // 512,  # blocks
            mtime,
            mtime,
            mtime,
            0,
            0,
            0,
            mode,
            2 if st.is_dir else 1,
            getattr(st, "uid", 0) or 0,
            getattr(st, "gid", 0) or 0,
            0,  # rdev
            4096,  # blksize
            0,
        )

    def _entry_out(self, path: str) -> bytes:
        # node creation and the lookup-count bump must be ONE critical
        # section: an inline FORGET interleaving between them could
        # reclaim the nodeid while this reply hands it to the kernel.
        # Each entry reply the kernel keeps counts as one lookup; the
        # matching FORGET(nlookup) releases them (bazil fs NodeRef role)
        with self._maps_lock:
            nid = self._node_for(path)
            self._nlookup[nid] = self._nlookup.get(nid, 0) + 1
        return (
            _ENTRY_OUT.pack(nid, 0, _TTL_SEC, _TTL_SEC, 0, 0)
            + self._attr_bytes(path, nid)
        )

    def _forget(self, nodeid: int, nlookup: int) -> None:
        if nodeid == 1:
            return
        with self._maps_lock:
            left = self._nlookup.get(nodeid, 0) - nlookup
            if left > 0:
                self._nlookup[nodeid] = left
                return
            self._nlookup.pop(nodeid, None)
            path = self._nodes.pop(nodeid, None)
            if path is not None and self._ids.get(path) == nodeid:
                del self._ids[path]

    def _attr_out(self, path: str, nodeid: int) -> bytes:
        return struct.pack("<QII", _TTL_SEC, 0, 0) + self._attr_bytes(path, nodeid)

    # --- dispatch -----------------------------------------------------------
    def _dispatch(self, opcode: int, nodeid: int, body: bytes):
        if opcode == INIT:
            major, minor, _ra, kflags = _INIT_IN.unpack_from(body)
            if major < 7:
                raise FuseProtocolError(f"kernel FUSE {major}.{minor} too old")
            FUSE_BIG_WRITES = 1 << 5  # WRITEs up to max_write, not 1 page
            FUSE_MAX_PAGES = 1 << 22  # honor our max_pages field
            # reply flags must be a subset of what the kernel offered
            flags = kflags & (FUSE_BIG_WRITES | FUSE_MAX_PAGES)
            return _INIT_OUT.pack(
                7, 31, 128 * 1024, flags, 12, 10, _MAX_WRITE, 1,
                _MAX_WRITE // 4096, 0, 0, b"",
            )
        if opcode == LOOKUP:
            name = body.rstrip(b"\0").decode()
            path = self._child(nodeid, name)
            if not self.mfs.exists(path):
                raise NotFound(path)
            return self._entry_out(path)
        if opcode == FORGET:
            (nlookup,) = struct.unpack_from("<Q", body)
            self._forget(nodeid, nlookup)
            return b""  # no reply sent (see _NO_REPLY)
        if opcode == BATCH_FORGET:
            count, _dummy = struct.unpack_from("<II", body)
            off = 8
            for _ in range(count):
                nid, nlookup = struct.unpack_from("<QQ", body, off)
                off += 16
                self._forget(nid, nlookup)
            return b""
        if opcode == INTERRUPT:
            return b""
        if opcode == GETATTR:
            _gflags, _d, fh = _GETATTR_IN.unpack_from(body)
            return self._attr_out(self._path(nodeid), nodeid)
        if opcode == SETATTR:
            return self._setattr(nodeid, body)
        if opcode == READLINK:
            return self.mfs.readlink(self._path(nodeid)).encode()
        if opcode == SYMLINK:
            name, target = body.split(b"\0")[:2]
            path = self._child(nodeid, name.decode())
            self.mfs.symlink(target.decode(), path)
            return self._entry_out(path)
        if opcode == MKDIR:
            mode, _umask = _MKDIR_IN.unpack_from(body)
            name = body[_MKDIR_IN.size :].rstrip(b"\0").decode()
            path = self._child(nodeid, name)
            self.mfs.mkdir(path, mode & 0o7777)
            return self._entry_out(path)
        if opcode == UNLINK:
            self.mfs.unlink(self._child(nodeid, body.rstrip(b"\0").decode()))
            return b""
        if opcode == RMDIR:
            self.mfs.rmdir(self._child(nodeid, body.rstrip(b"\0").decode()))
            return b""
        if opcode in (RENAME, RENAME2):
            if opcode == RENAME2:
                hdr = struct.Struct("<QII")
                newdir, rflags, _pad = hdr.unpack_from(body)
            else:
                hdr = _RENAME_IN
                newdir, rflags = hdr.unpack_from(body)[0], 0
            oldname, newname = body[hdr.size :].split(b"\0")[:2]
            old = self._child(nodeid, oldname.decode())
            new = self._child(newdir, newname.decode())
            RENAME_NOREPLACE, RENAME_EXCHANGE = 1, 2
            if rflags & ~RENAME_NOREPLACE:
                return -errno.EINVAL  # EXCHANGE/WHITEOUT unsupported
            if rflags & RENAME_NOREPLACE and self.mfs.exists(new):
                return -errno.EEXIST
            # rename + table rekey are one critical section so no
            # concurrent op resolves a nodeid to the stale path between
            # them (ops that resolved earlier match bazil's model: the
            # kernel's VFS rename locking shields path resolution)
            with self._maps_lock:
                self.mfs.rename(old, new)
                self._rekey(old, new)
            return b""
        if opcode in (OPEN, OPENDIR):
            flags, _ = _OPEN_IN.unpack_from(body)
            return self._open(opcode, nodeid, flags)
        if opcode == READ:
            fh, offset, size, *_ = _READ_IN.unpack_from(body)
            f = self._handles[fh]
            f.seek(offset)
            return f.read(size)
        if opcode == WRITE:
            fh, offset, size, *_ = _WRITE_IN.unpack_from(body)
            data = body[_WRITE_IN.size : _WRITE_IN.size + size]
            f = self._handles[fh]
            f.seek(offset)
            return _WRITE_OUT.pack(f.write(data), 0)
        if opcode == STATFS:
            return _KSTATFS.pack(1 << 30, 1 << 29, 1 << 29, 1 << 20, 1 << 19, 0,
                                 4096, 255, 4096, 0)
        if opcode in (RELEASE, RELEASEDIR):
            fh, *_ = _RELEASE_IN.unpack_from(body)
            # handle-table pops under _maps_lock: strands for DIFFERENT
            # nodeids run on pool threads concurrently, and an OPEN
            # allocating a handle must never interleave a half-done
            # release (weedlint unguarded-write, OPERATIONS.md round 9)
            with self._maps_lock:
                self._dirbufs.pop(fh, None)
                f = self._handles.pop(fh, None)
            if f is not None:
                f.close()
            return b""
        if opcode == FLUSH:
            fh, *_ = _FLUSH_IN.unpack_from(body)
            f = self._handles.get(fh)
            if f is not None:
                f.flush()
            return b""
        if opcode in (FSYNC, FSYNCDIR):
            fh, *_ = _FSYNC_IN.unpack_from(body)
            f = self._handles.get(fh)
            if f is not None:
                f.flush()
            return b""
        if opcode == READDIR:
            fh, offset, size, *_ = _READ_IN.unpack_from(body)
            buf = self._dirbufs.get(fh)
            if buf is None or offset == 0:
                buf = self._dirents(nodeid)
                # same _maps_lock discipline as RELEASE's pop of this
                # table (weedlint unguarded-write, OPERATIONS.md round 9)
                with self._maps_lock:
                    self._dirbufs[fh] = buf
            # whole records only: the kernel cannot parse a dirent cut
            # mid-record, so stop at the last boundary that fits
            end = offset
            while end < len(buf):
                namelen = _DIRENT_HDR.unpack_from(buf, end)[2]
                rec = _DIRENT_HDR.size + namelen
                rec += -rec % 8
                if end + rec - offset > size:
                    break
                end += rec
            return buf[offset:end]
        if opcode == ACCESS:
            return b""  # permission model is the filer's, not the kernel's
        if opcode == CREATE:
            flags, mode, _umask, _of = _CREATE_IN.unpack_from(body)
            name = body[_CREATE_IN.size :].rstrip(b"\0").decode()
            path = self._child(nodeid, name)
            # CREATE must enforce O_EXCL/O_TRUNC itself: with no cached
            # negative dentry the kernel forwards O_CREAT opens on files
            # that already exist, and only O_TRUNC may clobber them
            if self.mfs.exists(path):
                if flags & os.O_EXCL:
                    return -errno.EEXIST
                if flags & os.O_TRUNC:
                    self.mfs.truncate(path, 0)
                f = self.mfs.open(path, "r+")
            else:
                f = self.mfs.open(path, "w")
            with self._maps_lock:
                fh = self._next_fh
                self._next_fh += 1
                self._handles[fh] = f
            return self._entry_out(path) + _OPEN_OUT.pack(fh, 0, 0)
        if opcode == SETXATTR:
            xattr_hdr = struct.Struct("<II")
            vsize, _flags = xattr_hdr.unpack_from(body)
            rest = body[xattr_hdr.size :]
            name, rest = rest.split(b"\0", 1)
            self.mfs.setxattr(self._path(nodeid), name.decode(), rest[:vsize])
            return b""
        if opcode == GETXATTR:
            vsize, _pad = _GETXATTR_IN.unpack_from(body)
            name = body[_GETXATTR_IN.size :].rstrip(b"\0").decode()
            try:
                value = self.mfs.getxattr(self._path(nodeid), name)
            except (KeyError, NotFound, AttributeError):
                # AttributeError: Dir nodes carry no xattrs — `ls -la`
                # probes security.* on every directory
                return -errno.ENODATA
            if vsize == 0:
                return struct.pack("<II", len(value), 0)
            if len(value) > vsize:
                return -errno.ERANGE
            return value
        if opcode == LISTXATTR:
            vsize, _pad = _GETXATTR_IN.unpack_from(body)
            try:
                xnames = self.mfs.listxattr(self._path(nodeid))
            except (NotFound, AttributeError):
                xnames = []
            names = b"".join(n.encode() + b"\0" for n in xnames)
            if vsize == 0:
                return struct.pack("<II", len(names), 0)
            if len(names) > vsize:
                return -errno.ERANGE
            return names
        if opcode == REMOVEXATTR:
            name = body.rstrip(b"\0").decode()
            d, fname = self.mfs._split(self._path(nodeid))
            from seaweedfs_tpu.filesys.nodes import Dir

            Dir(self.mfs.wfs, d).lookup(fname).remove_xattr(name)
            return b""
        if opcode == DESTROY:
            return b""
        return -errno.ENOSYS

    def _open(self, opcode: int, nodeid: int, flags: int):
        path = self._path(nodeid)
        if opcode == OPENDIR:
            buf = self._dirents(nodeid)
            with self._maps_lock:
                fh = self._next_fh
                self._next_fh += 1
                self._dirbufs[fh] = buf
            return _OPEN_OUT.pack(fh, 0, 0)
        acc = flags & os.O_ACCMODE
        if flags & os.O_TRUNC:
            self.mfs.truncate(path, 0)
        mode = "r" if acc == os.O_RDONLY else "r+"
        f = self.mfs.open(path, mode)
        with self._maps_lock:
            fh = self._next_fh
            self._next_fh += 1
            self._handles[fh] = f
        return _OPEN_OUT.pack(fh, 0, 0)

    def _setattr(self, nodeid: int, body: bytes):
        (valid, _pad, fh, size, _lock, _at, mt, _ct, _ans, _mns, _cns,
         mode, _u4, uid, gid, _u5) = _SETATTR_IN.unpack_from(body)
        path = self._path(nodeid)
        if valid & FATTR_SIZE:
            f = self._handles.get(fh)
            if f is not None:
                f.flush()
            self.mfs.truncate(path, size)
        if valid & (FATTR_MODE | FATTR_UID | FATTR_GID):
            from seaweedfs_tpu.filesys.nodes import Dir

            d, fname = self.mfs._split(path)
            if fname:
                node = Dir(self.mfs.wfs, d).lookup(fname)
                ent = node.entry if hasattr(node, "entry") else None
                if ent is not None:
                    if valid & FATTR_MODE:
                        ent.attributes.file_mode = mode
                    if valid & FATTR_UID:
                        ent.attributes.uid = uid
                    if valid & FATTR_GID:
                        ent.attributes.gid = gid
                    if hasattr(node, "save"):
                        node.save()
        return self._attr_out(path, nodeid)

    def _dirents(self, nodeid: int) -> bytes:
        from seaweedfs_tpu.filesys.nodes import Dir

        path = self._path(nodeid)
        entries = [(".", nodeid, 4), ("..", 1, 4)]
        for e in Dir(self.mfs.wfs, self.mfs._full(path)).readdir():
            mode = e.attributes.file_mode
            dtype = (
                4
                if e.is_directory
                else (10 if stat_mod.S_ISLNK(mode) else 8)
            )
            child = self._child(nodeid, e.name)
            entries.append((e.name, self._node_for(child), dtype))
        out = bytearray()
        for name, ino, dtype in entries:
            nb = name.encode()
            reclen = _DIRENT_HDR.size + len(nb)
            padded = reclen + (-reclen % 8)
            # `off` is the kernel's resume cookie: the byte offset of
            # the NEXT record in this buffer (READDIR slices by it)
            out += _DIRENT_HDR.pack(ino, len(out) + padded, len(nb), dtype)
            out += nb + b"\0" * (padded - reclen)
        return bytes(out)


def mount_kernel(option, mountpoint: str) -> KernelFuseMount:
    """Mount and serve in a background thread; returns the mount for
    unmount(). Raises FuseProtocolError when /dev/fuse is unusable."""
    mfs = MountedFileSystem(option)
    km = KernelFuseMount(mfs, mountpoint)
    km.mount()
    km.serve_background()
    return km
