"""Replica placement policy: "xyz" digit string.

Bit-compatible with reference weed/storage/super_block/replica_placement.go:
digit 0 = extra copies in different data centers, digit 1 = different
racks (same DC), digit 2 = same rack.  Stored in the superblock as the
decimal byte x*100 + y*10 + z.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ReplicaPlacement:
    diff_data_center_count: int = 0
    diff_rack_count: int = 0
    same_rack_count: int = 0

    @staticmethod
    def parse(t: str) -> "ReplicaPlacement":
        # Characters past index 2 are ignored, matching the reference's
        # switch that only handles positions 0-2; digits outside 0..2
        # are rejected anywhere in the string, as the reference does.
        counts = [0, 0, 0]
        for i, c in enumerate(t):
            v = ord(c) - ord("0")
            if not 0 <= v <= 2:
                raise ValueError(f"unknown replication type {t!r}")
            if i <= 2:
                counts[i] = v
        return ReplicaPlacement(counts[0], counts[1], counts[2])

    @staticmethod
    def from_byte(b: int) -> "ReplicaPlacement":
        return ReplicaPlacement.parse(f"{b:03d}")

    def to_byte(self) -> int:
        return (
            self.diff_data_center_count * 100
            + self.diff_rack_count * 10
            + self.same_rack_count
        )

    @property
    def copy_count(self) -> int:
        return (
            self.diff_data_center_count
            + self.diff_rack_count
            + self.same_rack_count
            + 1
        )

    def __str__(self) -> str:
        return (
            f"{self.diff_data_center_count}"
            f"{self.diff_rack_count}"
            f"{self.same_rack_count}"
        )
