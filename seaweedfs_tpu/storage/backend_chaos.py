"""weedchaos shim for the backend SPI: wrap any BackendStorage in a
fault injector (the DiskChaos analogue for the remote tier). A
ChaosBackendStorage registered in place of the real one makes every
tier upload/download/ranged-read go through seeded fault draws —
`eio` raises, `slow` sleeps — so tests can prove degraded reads and
tier retries behave under a misbehaving object store without touching
the backend implementations themselves."""

from __future__ import annotations

import random
import threading
import time

from seaweedfs_tpu.storage import backend as b

_OPS = ("read", "upload", "download", "delete")


class BackendFault:
    """One fault rule: mode ∈ eio|slow, ops ⊆ read,upload,download,delete,
    probability in [0,1], delay for slow."""

    def __init__(
        self,
        mode: str,
        ops: tuple[str, ...] = ("read",),
        probability: float = 1.0,
        delay_s: float = 0.05,
    ):
        if mode not in ("eio", "slow"):
            raise ValueError(f"backend fault mode {mode!r} not eio|slow")
        for op in ops:
            if op not in _OPS:
                raise ValueError(f"backend fault op {op!r} not in {_OPS}")
        self.mode = mode
        self.ops = tuple(ops)
        self.probability = probability
        self.delay_s = delay_s


class _ChaosFile(b.BackendStorageFile):
    def __init__(self, chaos: "ChaosBackendStorage", inner: b.BackendStorageFile):
        self.chaos = chaos
        self.inner = inner

    def read_at(self, length: int, offset: int) -> bytes:
        self.chaos._maybe_fault("read")
        return self.inner.read_at(length, offset)

    def write_at(self, data: bytes, offset: int) -> int:
        return self.inner.write_at(data, offset)

    def truncate(self, size: int) -> None:
        self.inner.truncate(size)

    def close(self) -> None:
        self.inner.close()

    def get_stat(self) -> tuple[int, float]:
        return self.inner.get_stat()

    def name(self) -> str:
        return self.inner.name()


class ChaosBackendStorage(b.BackendStorage):
    """Registers under the SAME name as the wrapped backend, so code
    resolving `dir.default` through get_backend() transparently hits
    the shim. injected/raised counters are the test observables."""

    def __init__(
        self,
        inner: b.BackendStorage,
        faults: list[BackendFault] | None = None,
        seed: int = 0,
    ):
        self.inner = inner
        self.storage_type = inner.storage_type
        self.id = inner.id
        self.faults: list[BackendFault] = list(faults or [])
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected = 0  # total fault draws that hit (slow + eio)
        self.raised = 0  # eio subset

    def heal(self) -> None:
        with self._lock:
            self.faults = []

    def _maybe_fault(self, op: str) -> None:
        with self._lock:
            faults = list(self.faults)
            draws = [self._rng.random() for _ in faults]
        for fault, draw in zip(faults, draws):
            if op not in fault.ops or draw >= fault.probability:
                continue
            with self._lock:
                self.injected += 1
            if fault.mode == "slow":
                time.sleep(fault.delay_s)
            else:
                with self._lock:
                    self.raised += 1
                raise IOError(
                    f"chaos backend: injected EIO on {op} ({self.name})"
                )

    def to_properties(self) -> dict:
        return self.inner.to_properties()

    def new_storage_file(self, key: str, file_size: int) -> _ChaosFile:
        return _ChaosFile(self, self.inner.new_storage_file(key, file_size))

    def copy_file(self, local_path: str, attributes: dict, progress=None):
        self._maybe_fault("upload")
        return self.inner.copy_file(local_path, attributes, progress)

    def download_file(self, local_path: str, key: str, progress=None) -> int:
        self._maybe_fault("download")
        return self.inner.download_file(local_path, key, progress)

    def delete_file(self, key: str) -> None:
        self._maybe_fault("delete")
        self.inner.delete_file(key)
