"""Volume: one append-only .dat + .idx pair and its life cycle.

Behavioral match of reference weed/storage/volume.go +
volume_read_write.go + volume_loading.go + volume_checking.go:

  * creation writes an 8-byte superblock (version, replica placement,
    TTL, compaction revision);
  * writes append a needle record, update the needle map, and append an
    .idx entry; duplicate identical writes are no-ops (isFileUnchanged);
    a write to an existing id must present the same cookie;
  * deletes append a tombstone needle (empty data, fresh AppendAtNs)
    and a tombstone .idx entry pointing at it;
  * reads check not-found / tombstone / TTL expiry and verify CRC;
  * loading replays the .idx and validates its tail against the .dat
    (CheckVolumeDataIntegrity);
  * vacuum/compaction copies live needles to <name>.cpd/.cpx scratch
    files and atomically swaps them in, bumping the superblock
    compaction revision (volume_vacuum.go).

File naming (volume.go FileName): <dir>/<collection>_<vid> or
<dir>/<vid> when the collection is empty.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import (
    CorruptNeedle,
    Needle,
    get_actual_size,
)
from seaweedfs_tpu.storage.needle_map import CompactNeedleMap, NeedleValue
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.super_block import CURRENT_VERSION, SuperBlock
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.util import durable, wlog

try:
    # Invalidates the C serving loop's plan cache (fd/offset/headers
    # keyed by path) on any mutation; no-op import cycle risk: the
    # util module only pulls os/socket/threading at top level.
    from seaweedfs_tpu.util.native_serve import (
        bump_generation as _serve_cache_bump,
    )
except Exception:  # pragma: no cover - stripped install
    def _serve_cache_bump():  # type: ignore[misc]
        return 0


class NeedleNotFound(KeyError):
    pass


class VolumeReadOnly(RuntimeError):
    pass


class CookieMismatch(ValueError):
    pass


def volume_base_name(directory: str, collection: str, vid: int) -> str:
    if collection:
        return os.path.join(directory, f"{collection}_{vid}")
    return os.path.join(directory, str(vid))


class _FileLikeOverBackend:
    """File-object protocol (seek/read/tell) over a BackendStorageFile,
    so the Volume read path works unchanged on remote-tier volumes.
    Writes raise: tiered volumes are sealed."""

    def __init__(self, bsf):
        self._bsf = bsf
        self._pos = 0

    def seek(self, offset: int, whence: int = os.SEEK_SET) -> int:
        if whence == os.SEEK_END:
            size, _ = self._bsf.get_stat()
            self._pos = size + offset
        elif whence == os.SEEK_CUR:
            self._pos += offset
        else:
            self._pos = offset
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            size, _ = self._bsf.get_stat()
            n = max(0, size - self._pos)
        if n == 0:
            return b""
        data = self._bsf.read_at(n, self._pos)
        self._pos += len(data)
        return data

    def write(self, data: bytes) -> int:
        raise VolumeReadOnly("remote-tier volume is sealed")

    def truncate(self, size: int) -> None:
        raise VolumeReadOnly("remote-tier volume is sealed")

    def flush(self) -> None:
        pass

    def close(self) -> None:
        self._bsf.close()


class Volume:
    def __init__(
        self,
        directory: str,
        vid: int,
        collection: str = "",
        replica_placement: ReplicaPlacement | None = None,
        ttl: TTL | None = None,
        version: int = CURRENT_VERSION,
        create: bool = True,
        needle_map_kind: str = "memory",
        repair: bool = False,
    ):
        self.id = vid
        self.collection = collection
        self.dir = directory
        self.base_name = volume_base_name(directory, collection, vid)
        self.read_only = False
        self.last_append_at_ns = 0
        self._lock = threading.RLock()
        # "memory" (CompactNeedleMap) or "db" (persistent sqlite map —
        # the reference's -index=leveldb variant, needle_map_leveldb.go)
        self.needle_map_kind = needle_map_kind
        # `repair` = crash recovery is allowed to REWRITE the files:
        # roll a half-committed vacuum swap forward/back (.cpm marker)
        # and heal the .idx/.dat tails (truncate torn entries/records,
        # re-index durable .dat records whose idx entries were lost).
        # ONLY the exclusive owner may pass it (DiskLocation at server
        # startup): a -workers follower opening a LIVE volume would
        # otherwise "heal away" the entry the writer is appending
        # right now. docs/ANALYSIS.md v3 has the crash-state model.
        if repair:
            self._recover_compaction()

        dat_path = self.base_name + ".dat"
        # tier metadata: a .vif with remote files means the sealed .dat
        # lives in a remote backend (volume_info.go MaybeLoadVolumeInfo)
        from seaweedfs_tpu.storage import volume_info as vif

        self.volume_info, has_remote = vif.maybe_load_volume_info(
            self.base_name + ".vif"
        )
        exists = os.path.exists(dat_path)
        if has_remote and not exists:
            self._open_remote_dat()
            self.read_only = True
            self.super_block = SuperBlock.read_from(self._dat)
            self.nm = self._load_needle_map()
            self._followed = self.nm.index_file_size()
            return
        if has_remote:
            # keep_local_dat_file case: a local copy exists alongside
            # the remote one — it must stay sealed or the copies diverge
            self.read_only = True
        if not exists:
            if not create:
                raise FileNotFoundError(dat_path)
            self.super_block = SuperBlock(
                version=version,
                replica_placement=replica_placement or ReplicaPlacement(),
                ttl=ttl or TTL(),
            )
            with open(dat_path, "wb") as f:
                f.write(self.super_block.to_bytes())
        self._dat = open(dat_path, "r+b")
        self._bind_fd()
        if exists:
            self.super_block = SuperBlock.read_from(self._dat)
        if repair and exists and not self.read_only:
            self._repair_tail()
            self._bind_fd()  # the heal may have truncated the .dat
        self.nm = self._load_needle_map()
        # how much of the on-disk .idx this process's map reflects —
        # refresh_from_idx replays from here when ANOTHER process is
        # the volume's writer (-shardWrites followers/handback)
        self._followed = self.nm.index_file_size()
        if exists:
            self._check_integrity()

    def _load_needle_map(self):
        if self.needle_map_kind == "db":
            from seaweedfs_tpu.storage.needle_map import DbNeedleMap

            return DbNeedleMap.load(self.base_name + ".idx")
        return CompactNeedleMap.load(self.base_name + ".idx")

    # --- remote tier (backend.go + volume_grpc_tier_*.go) ---
    def _open_remote_dat(self) -> None:
        from seaweedfs_tpu.storage import backend as bk

        bk.ensure_builtin_factories()
        rf = self.volume_info.files[0]
        storage = bk.get_backend(rf.backend_name)
        if storage is None:
            raise RuntimeError(
                f"volume {self.id}: remote backend {rf.backend_name!r} is "
                f"not configured (storage.backend config)"
            )
        self._dat = _FileLikeOverBackend(
            storage.new_storage_file(rf.key, rf.file_size)
        )
        self._fd = None

    def has_remote_file(self) -> bool:
        return self.volume_info.has_remote_file()

    def tier_upload(
        self, backend_name: str, keep_local: bool = False, progress=None
    ) -> tuple[str, int]:
        """Move this (sealed) volume's .dat to a remote backend
        (VolumeTierMoveDatToRemote, volume_grpc_tier_upload.go:14)."""
        from seaweedfs_tpu.storage import backend as bk
        from seaweedfs_tpu.storage import volume_info as vif

        bk.ensure_builtin_factories()
        storage = bk.get_backend(backend_name)
        if storage is None:
            raise RuntimeError(
                f"destination {backend_name!r} not found; configured: "
                f"{sorted(bk.BACKEND_STORAGES)}"
            )
        for rf in self.volume_info.files:
            if rf.backend_name == storage.name:
                raise RuntimeError(f"destination {backend_name} already exists")
        with self._lock:
            was_read_only = self.read_only
            self.read_only = True
            self._dat.flush()
            dat_path = self.base_name + ".dat"
            attributes = {
                "volumeId": str(self.id),
                "collection": self.collection,
                "ext": ".dat",
            }
            try:
                key, size = storage.copy_file(dat_path, attributes, progress)
            except Exception:
                # failed upload must not leave the volume wedged
                # rejecting writes with no .vif written
                self.read_only = was_read_only
                raise
            self.volume_info.files.append(
                vif.RemoteFile(
                    backend_type=storage.storage_type,
                    backend_id=storage.id,
                    key=key,
                    file_size=size,
                    modified_time=int(time.time()),
                    extension=".dat",
                )
            )
            vif.save_volume_info(self.base_name + ".vif", self.volume_info)
            if not keep_local:
                self._dat.close()
                os.remove(dat_path)
                self._open_remote_dat()
            return key, size

    def tier_download(self, keep_remote: bool = False, progress=None) -> int:
        """Bring a tiered volume's .dat back to local disk
        (VolumeTierMoveDatFromRemote, volume_grpc_tier_download.go)."""
        from seaweedfs_tpu.storage import backend as bk
        from seaweedfs_tpu.storage import volume_info as vif

        if not self.volume_info.has_remote_file():
            raise RuntimeError(f"volume {self.id} has no remote file")
        bk.ensure_builtin_factories()
        rf = self.volume_info.files[0]
        storage = bk.get_backend(rf.backend_name)
        if storage is None:
            raise RuntimeError(f"backend {rf.backend_name!r} not configured")
        with self._lock:
            dat_path = self.base_name + ".dat"
            size = storage.download_file(dat_path, rf.key, progress)
            self._dat.close()
            self._dat = open(dat_path, "r+b")
            self._bind_fd()
            if not keep_remote:
                storage.delete_file(rf.key)
                self.volume_info.files.remove(rf)
            if self.volume_info.has_remote_file():
                vif.save_volume_info(self.base_name + ".vif", self.volume_info)
            else:
                self.volume_info.files.clear()
                try:
                    os.remove(self.base_name + ".vif")
                except FileNotFoundError:
                    pass
            self.read_only = False
            return size

    # --- properties ---
    @property
    def version(self) -> int:
        return self.super_block.version

    @property
    def ttl(self) -> TTL:
        return self.super_block.ttl

    def data_file_size(self) -> int:
        self._dat.seek(0, os.SEEK_END)
        return self._dat.tell()

    def content_size(self) -> int:
        return self.nm.content_size()

    def deleted_size(self) -> int:
        return self.nm.deleted_size()

    def file_count(self) -> int:
        return self.nm.file_count

    def deleted_count(self) -> int:
        return self.nm.deletion_count

    def max_file_key(self) -> int:
        return self.nm.max_file_key

    def stats_snapshot(self) -> dict:
        """Consistent stat row for the heartbeat, under the volume
        lock: commit_compact swaps .dat/.idx and the needle map while
        holding self._lock, and an unlocked data_file_size() there
        seeks a CLOSED file — callers must never sample these fields
        without the lock, and this method is the supported way to get
        them from outside the class."""
        with self._lock:
            return {
                "size": self.data_file_size(),
                "file_count": self.file_count(),
                "delete_count": self.deleted_count(),
                "deleted_byte_count": self.deleted_size(),
                "read_only": self.read_only,
            }

    def garbage_level(self) -> float:
        """Fraction of the .dat occupied by deleted records
        (volume_vacuum.go garbageLevel)."""
        size = self.data_file_size()
        if size == 0:
            return 0.0
        return self.nm.deleted_size() / size

    # --- integrity (volume_checking.go:14) ---
    def _check_integrity(self) -> None:
        idx_size = self.nm.index_file_size()
        if idx_size == 0:
            return
        with open(self.base_name + ".idx", "rb") as f:
            f.seek(idx_size - t.NEEDLE_MAP_ENTRY_SIZE)
            from seaweedfs_tpu.storage import idx as idx_codec

            key, offset, size = idx_codec.unpack_entry(f.read(t.NEEDLE_MAP_ENTRY_SIZE))
        if offset == 0:
            return
        if size == t.TOMBSTONE_FILE_SIZE:
            size = 0  # the tombstone .dat record is an empty-data needle
        actual = t.units_to_offset(offset)
        record_end = actual + get_actual_size(size, self.version)
        if record_end > self.data_file_size():
            raise CorruptNeedle(
                f"volume {self.id}: last index entry [key {key}] ends at "
                f"{record_end} past .dat size {self.data_file_size()}"
            )
        # recover lastAppendAtNs from the last record (v3)
        blob = self._read_at(actual, get_actual_size(size, self.version))
        try:
            n = Needle.from_bytes(blob, self.version, size=size)
            self.last_append_at_ns = n.append_at_ns
        except CorruptNeedle:
            raise

    # --- crash recovery (docs/ANALYSIS.md v3) ---
    def _recover_compaction(self) -> None:
        """Resolve a crash that interrupted commit_compact's two-rename
        swap. The `.cpm` marker is the commit point: it is written (and
        made durable, after the scratch bytes were) BEFORE either
        rename, and removed after both — so at recovery,

          marker present  →  the new generation is complete on disk
                             under .cpd/.cpx or already partly swapped
                             in: roll the swap FORWARD (both renames
                             are idempotent re-runs);
          marker absent   →  the commit point was never reached: the
                             old generation is authoritative, stale
                             scratch files are deleted (roll BACK).

        Either way the recovered volume is wholly old or wholly new —
        never the new .dat under the old .idx that a bare two-rename
        sequence can leave behind."""
        marker = self.base_name + ".cpm"
        cpd = self.base_name + ".cpd"
        cpx = self.base_name + ".cpx"
        if os.path.exists(marker):
            # the marker outliving the swap means the db needle map's
            # sqlite table may still index the OLD idx (its clean
            # checkpoint can coincidentally match the new idx size, so
            # load would skip the rebuild): drop it in EVERY
            # marker-present state, not just the cpx-pending one —
            # commit_compact removes the sdb before the marker, so a
            # crash between renames and that removal lands here with
            # cpd/cpx already gone
            sdb = self.base_name + ".idx.sdb"
            if os.path.exists(sdb):
                os.remove(sdb)
            if os.path.exists(cpx):
                wlog.warning(
                    "volume %d: rolling interrupted vacuum commit "
                    "forward from scratch files", self.id,
                )
                if os.path.exists(cpd):
                    os.replace(cpd, self.base_name + ".dat")
                os.replace(cpx, self.base_name + ".idx")
            elif os.path.exists(cpd):
                # cannot happen under the commit order (cpd is renamed
                # first), but never leave a scratch .dat to trip the
                # next compact
                os.remove(cpd)
            os.remove(marker)
            durable.fsync_dir(self.dir)
            return
        removed = False
        for p in (cpd, cpx):
            if os.path.exists(p):
                os.remove(p)
                removed = True
        if removed:
            wlog.warning(
                "volume %d: removed uncommitted compaction scratch "
                "files (crash before the commit point)", self.id,
            )
            durable.fsync_dir(self.dir)

    def _repair_tail(self) -> None:
        """Heal the .idx/.dat tails after an unclean shutdown so the
        recovery invariants hold (docs/ANALYSIS.md v3):

          * the .idx never references bytes past (or torn inside) the
            .dat: trailing entries that fail the bounds or CRC gate are
            truncated away, torn (non-16-multiple) tails first;
          * no acked needle is lost: the .dat is fsynced before a write
            is acked but its .idx entries are not — records past the
            idx-covered region are re-indexed from the .dat (the `weed
            fix` role, run incrementally at load);
          * no torn record surfaces as valid: the tail scan stops at
            the first record that fails the CRC gate and truncates the
            .dat there (those bytes were never acked: the ack's fsync
            would have made them whole).

        A .dat record with EMPTY data re-indexes as a tombstone — the
        scan convention of scan_volume_file and the reference's `weed
        fix`; a zero-byte PUT overwritten by this is the known
        ambiguity (idx entries, which disambiguate, were lost)."""
        idx_path = self.base_name + ".idx"
        try:
            idx_size = os.path.getsize(idx_path)
        except OSError:
            idx_size = 0
        entry = t.NEEDLE_MAP_ENTRY_SIZE
        dat_size = self.data_file_size()
        from seaweedfs_tpu.storage import idx as idx_codec

        usable = idx_size - idx_size % entry
        covered_end = self.super_block.block_size()
        if usable:
            with open(idx_path, "rb") as f:
                while usable >= entry:
                    f.seek(usable - entry)
                    key, offset, size = idx_codec.unpack_entry(
                        f.read(entry)
                    )
                    if offset == 0:
                        # record-less tombstone entry (compaction diff
                        # shape): self-contained, nothing to validate —
                        # but it also does not advance coverage
                        usable_probe = usable - entry
                        covered = self._entry_end_if_valid(
                            f, usable_probe, entry
                        )
                        covered_end = max(covered_end, covered)
                        break
                    norm = 0 if size == t.TOMBSTONE_FILE_SIZE else size
                    end = t.units_to_offset(offset) + get_actual_size(
                        norm, self.version
                    )
                    if end <= dat_size:
                        blob = self._read_at(
                            t.units_to_offset(offset),
                            get_actual_size(norm, self.version),
                        )
                        try:
                            Needle.from_bytes(
                                blob, self.version, size=norm
                            )
                            covered_end = max(covered_end, end)
                            break
                        except (CorruptNeedle, ValueError):
                            pass
                    usable -= entry
        if usable < idx_size:
            wlog.warning(
                "volume %d: truncating .idx tail %d -> %d bytes "
                "(entries referencing torn/missing .dat records)",
                self.id, idx_size, usable,
            )
            os.truncate(idx_path, usable)
            durable.fsync_path(idx_path)
        # --- re-index durable .dat records the idx lost -------------
        scan = covered_end
        regen: list[bytes] = []
        while scan + t.NEEDLE_HEADER_SIZE <= dat_size:
            header = self._read_at(scan, t.NEEDLE_HEADER_SIZE)
            cookie, nid, nsize = Needle.parse_header(header)
            if cookie == 0 and nid == 0 and nsize == 0:
                break  # zero fill: a write hole / preallocation, not data
            rec_len = get_actual_size(nsize, self.version)
            if scan + rec_len > dat_size:
                break  # torn tail record
            blob = self._read_at(scan, rec_len)
            try:
                n = Needle.from_bytes(blob, self.version, size=nsize)
            except (CorruptNeedle, ValueError):
                break  # CRC gate: torn write that landed partially
            regen.append(
                idx_codec.pack_entry(
                    nid,
                    t.offset_to_units(scan),
                    t.TOMBSTONE_FILE_SIZE if not n.data else nsize,
                )
            )
            self.last_append_at_ns = max(
                self.last_append_at_ns, n.append_at_ns
            )
            scan += rec_len
        if scan < dat_size:
            wlog.warning(
                "volume %d: truncating torn .dat tail %d -> %d bytes",
                self.id, dat_size, scan,
            )
            os.truncate(self.base_name + ".dat", scan)
            durable.fsync_path(self.base_name + ".dat")
        if regen:
            wlog.warning(
                "volume %d: re-indexed %d .dat record(s) whose .idx "
                "entries were lost in the crash", self.id, len(regen),
            )
            with open(idx_path, "ab") as f:
                f.write(b"".join(regen))
                f.flush()
                os.fsync(f.fileno())

    def _entry_end_if_valid(self, f, pos: int, entry: int) -> int:
        """End offset of the record referenced by the last non-
        tombstone entry at/below `pos` (walking back), 0 when none —
        coverage probe for _repair_tail when the tail entry itself is
        a record-less tombstone."""
        from seaweedfs_tpu.storage import idx as idx_codec

        dat_size = self.data_file_size()
        while pos >= entry:
            f.seek(pos - entry)
            key, offset, size = idx_codec.unpack_entry(f.read(entry))
            if offset != 0:
                norm = 0 if size == t.TOMBSTONE_FILE_SIZE else size
                end = t.units_to_offset(offset) + get_actual_size(
                    norm, self.version
                )
                return min(end, dat_size)
            pos -= entry
        return 0

    def _bind_fd(self) -> None:
        """Arm the pread/pwrite fast path on the freshly (re)opened
        .dat: positionless IO needs no seek syscall, no flush, and no
        buffered-layer bookkeeping on the data plane."""
        self._fd = self._dat.fileno()
        self._append_end = os.fstat(self._fd).st_size

    def _read_at(self, offset: int, length: int) -> bytes:
        if self._fd is not None:
            return os.pread(self._fd, length, offset)
        self._dat.seek(offset)
        return self._dat.read(length)

    def _append_blob(self, blob) -> int:
        if self._fd is None:
            self._dat.seek(0, os.SEEK_END)
            offset = self._dat.tell()
            if offset % t.NEEDLE_PADDING_SIZE != 0:
                pad = t.NEEDLE_PADDING_SIZE - offset % t.NEEDLE_PADDING_SIZE
                self._dat.write(bytes(pad))
                offset += pad
            self._dat.write(blob)
            self._dat.flush()
            return offset
        offset = self._append_end
        if offset % t.NEEDLE_PADDING_SIZE != 0:
            # realign, matching the reference's defensive padding
            pad = t.NEEDLE_PADDING_SIZE - offset % t.NEEDLE_PADDING_SIZE
            if os.pwrite(self._fd, bytes(pad), offset) != pad:
                raise OSError(f"volume {self.id}: short pad write at {offset}")
            offset += pad
        # a short write (ENOSPC/RLIMIT) must raise BEFORE the needle map
        # records the offset, else a truncated record is indexed as live
        written = os.pwrite(self._fd, blob, offset)
        if written != len(blob):
            raise OSError(
                f"volume {self.id}: short append at {offset}: "
                f"{written}/{len(blob)} bytes"
            )
        self._append_end = offset + len(blob)
        return offset

    def _now_ns(self) -> int:
        ns = time.time_ns()
        if ns <= self.last_append_at_ns:
            ns = self.last_append_at_ns + 1
        return ns

    # --- write path (volume_read_write.go:66 writeNeedle) ---
    def write_needle(
        self, n: Needle, stages: dict | None = None
    ) -> tuple[int, int, bool]:
        """Returns (offset, size, is_unchanged).

        `stages` (tracing plane) collects "crc" — the single-pass
        record serialization, whose cost is the CRC32-C + body memcpy
        the C hot loop times under the same name — and "pwrite", the
        positioned append. Names match write_path.WRITE_STAGES."""
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.id} is read-only")
            if self._is_file_unchanged(n):
                return 0, n.size, True
            if n.ttl is None and self.ttl.count != 0:
                n.set_has_ttl()
                n.ttl = self.ttl

            existing = self.nm.get(n.Id if hasattr(n, "Id") else n.id)
            if existing is not None and existing.size != t.TOMBSTONE_FILE_SIZE:
                old = self._read_needle_at(existing)
                if old is not None and old.cookie != n.cookie:
                    raise CookieMismatch(
                        f"mismatching cookie {n.cookie:08x} for needle {n.id}"
                    )

            n.append_at_ns = self._now_ns()
            if stages is None:
                blob = n.encode_record(self.version)
                offset = self._append_blob(blob)
            else:
                import time as _time

                t0 = _time.perf_counter()
                blob = n.encode_record(self.version)
                t1 = _time.perf_counter()
                offset = self._append_blob(blob)
                stages["crc"] = t1 - t0
                stages["pwrite"] = _time.perf_counter() - t1
            self.last_append_at_ns = n.append_at_ns

            if existing is None or existing.actual_offset < offset:
                self.nm.put(n.id, t.offset_to_units(offset), n.size)
            # after the record and map entry are visible: a plan stamped
            # with the pre-bump generation can no longer be inserted
            _serve_cache_bump()
            return offset, n.size, False

    def commit(self) -> None:
        """One durability flush of the .dat (fsync on the pread/pwrite
        fast path). The QoS write path calls this per POST when
        `-commitFsync` is set, or once per group-commit window — the
        weed_commit_flush_total counter is what the fsyncs-per-POST
        bench ratio reads (docs/QOS.md)."""
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        from seaweedfs_tpu.stats.metrics import COMMIT_FLUSHES

        if self._fd is not None:
            os.fsync(self._fd)
        else:
            self._dat.flush()
        COMMIT_FLUSHES.inc()

    def write_needles(
        self,
        entries: list[tuple[Needle, dict | None]],
        durable: bool = False,
    ) -> list:
        """Group commit (docs/QOS.md): the batch counterpart of
        write_needle with identical per-needle semantics — dedup,
        cookie checks, TTL injection, monotonic append_at_ns — but all
        encoded records land with ONE pwritev and at most ONE
        durability flush. Returns one outcome per entry: an
        (offset, size, unchanged) tuple, or the exception instance the
        caller must raise for that needle (per-needle failures must not
        fail batchmates). Byte-identical on disk to the same needles
        written serially through write_needle, by construction: the
        same encode runs at the same offsets in the same order.

        A short pwritev raises for the whole batch BEFORE any needle
        map update — same invariant as the serial path (a truncated
        record must never be indexed as live)."""
        results: list = [None] * len(entries)
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.id} is read-only")
            if self._fd is None:
                # buffered (remote-tier shim) volumes have no pwritev
                # fast path; serial appends keep semantics identical
                for i, (n, stages) in enumerate(entries):
                    try:
                        results[i] = self.write_needle(n, stages=stages)
                    except (VolumeReadOnly, CookieMismatch) as e:
                        results[i] = e
                if durable:
                    self._flush_locked()
                return results
            start = self._append_end
            if start % t.NEEDLE_PADDING_SIZE:
                pad = t.NEEDLE_PADDING_SIZE - start % t.NEEDLE_PADDING_SIZE
                if os.pwrite(self._fd, bytes(pad), start) != pad:
                    raise OSError(f"volume {self.id}: short pad write at {start}")
                start += pad
                self._append_end = start
            blobs: list[bytes] = []
            metas: list[tuple[int, Needle, int]] = []  # (entry idx, n, offset)
            cursor = start
            seen_ids: set[int] = set()
            deferred: list[int] = []
            for i, (n, stages) in enumerate(entries):
                if n.id in seen_ids:
                    # a batchmate already writes this id: the serial
                    # path's dedup/cookie checks compare against THAT
                    # record's map entry, which doesn't exist until the
                    # batch commits — defer this entry to a serial
                    # write after the pwritev so the checks see what
                    # they would have seen serially
                    deferred.append(i)
                    continue
                if self._is_file_unchanged(n):
                    results[i] = (0, n.size, True)
                    continue
                if n.ttl is None and self.ttl.count != 0:
                    n.set_has_ttl()
                    n.ttl = self.ttl
                existing = self.nm.get(n.id)
                if existing is not None and existing.size != t.TOMBSTONE_FILE_SIZE:
                    old = self._read_needle_at(existing)
                    if old is not None and old.cookie != n.cookie:
                        results[i] = CookieMismatch(
                            f"mismatching cookie {n.cookie:08x} for needle {n.id}"
                        )
                        continue
                n.append_at_ns = self._now_ns()
                self.last_append_at_ns = n.append_at_ns
                if stages is None:
                    blob = n.encode_record(self.version)
                else:
                    t0 = time.perf_counter()
                    blob = n.encode_record(self.version)
                    stages["crc"] = time.perf_counter() - t0
                blobs.append(blob)
                metas.append((i, n, cursor))
                seen_ids.add(n.id)
                cursor += len(blob)
            if blobs:
                t0 = time.perf_counter()
                written = os.pwritev(self._fd, blobs, start)
                if written != cursor - start:
                    raise OSError(
                        f"volume {self.id}: short batch append at {start}: "
                        f"{written}/{cursor - start} bytes"
                    )
                pwrite_s = time.perf_counter() - t0
                self._append_end = cursor
                for i, n, offset in metas:
                    existing = self.nm.get(n.id)
                    if existing is None or existing.actual_offset < offset:
                        self.nm.put(n.id, t.offset_to_units(offset), n.size)
                    stages = entries[i][1]
                    if stages is not None:
                        # the one syscall serviced the whole batch; each
                        # rider reports the shared wall time
                        stages["pwrite"] = pwrite_s
                    results[i] = (offset, n.size, False)
            for i in deferred:
                # the RLock is already held; these run the exact serial
                # path against the now-committed batch state
                n, stages = entries[i]
                try:
                    results[i] = self.write_needle(n, stages=stages)
                except (VolumeReadOnly, CookieMismatch) as e:
                    results[i] = e
            if durable and blobs:
                self._flush_locked()
            if blobs:
                _serve_cache_bump()  # deferred entries bump via write_needle
        return results

    def _is_file_unchanged(self, n: Needle) -> bool:
        if str(self.ttl):
            return False
        nv = self.nm.get(n.id)
        if nv is None or nv.offset == 0 or nv.size == t.TOMBSTONE_FILE_SIZE:
            return False
        old = self._read_needle_at(nv)
        return (
            old is not None
            and old.cookie == n.cookie
            and old.data == n.data
        )

    def _read_needle_at(self, nv: NeedleValue) -> Optional[Needle]:
        try:
            blob = self._read_at(
                nv.actual_offset, get_actual_size(nv.size, self.version)
            )
            return Needle.from_bytes(blob, self.version, size=nv.size)
        except (CorruptNeedle, ValueError):
            return None

    # --- delete path (volume_read_write.go:115 deleteNeedle) ---
    def delete_needle(self, n: Needle) -> int:
        """Appends a tombstone record; returns the freed byte count."""
        with self._lock:
            if self.read_only:
                raise VolumeReadOnly(f"volume {self.id} is read-only")
            nv = self.nm.get(n.id)
            if nv is None or nv.size == t.TOMBSTONE_FILE_SIZE:
                return 0
            freed = nv.size
            n.data = b""
            n.append_at_ns = self._now_ns()
            blob = n.encode_record(self.version)
            offset = self._append_blob(blob)
            self.last_append_at_ns = n.append_at_ns
            self.nm.delete(n.id, t.offset_to_units(offset))
            _serve_cache_bump()
            return freed

    # --- read path (volume_read_write.go:139 readNeedle) ---
    def read_needle(self, needle_id: int, cookie: int | None = None) -> Needle:
        with self._lock:
            nv = self.nm.get(needle_id)
            if nv is None or nv.offset == 0:
                raise NeedleNotFound(f"needle {needle_id} not found")
            if nv.size == t.TOMBSTONE_FILE_SIZE:
                raise NeedleNotFound(f"needle {needle_id} already deleted")
            blob = self._read_at(
                nv.actual_offset, get_actual_size(nv.size, self.version)
            )
        n = Needle.from_bytes(blob, self.version, size=nv.size)
        if cookie is not None and n.cookie != cookie:
            raise CookieMismatch(
                f"cookie mismatch for needle {needle_id}"
            )
        if n.has_ttl() and n.ttl is not None and n.ttl.minutes and n.has_last_modified_date():
            expires = n.last_modified + n.ttl.minutes * 60
            if time.time() >= expires:
                raise NeedleNotFound(f"needle {needle_id} expired")
        return n

    def has_needle(self, needle_id: int) -> bool:
        nv = self.nm.get(needle_id)
        return nv is not None and nv.offset != 0 and nv.size != t.TOMBSTONE_FILE_SIZE

    # --- vacuum (volume_vacuum.go) ---
    def compact(self) -> None:
        """Copy live needles to .cpd/.cpx scratch files WITHOUT blocking
        writes (volume_vacuum.go:78-133 Compact2 shape): the lock is
        held only to snapshot the needle map and the current .dat size.
        The .dat is append-only, so every record below the snapshot
        offset is immutable and can be copied lock-free; anything
        appended afterwards (new needles, tombstones, overwrites) is
        replayed by the catch-up diff inside commit_compact
        (makeupDiff, volume_vacuum.go:157)."""
        with self._lock:
            snapshot = list(self.nm.items())
            self._dat.flush()
            self._compact_snapshot_size = self.data_file_size()
            self._compact_snapshot_idx = self.nm.index_file_size()
            sb = self.super_block
        cpd = self.base_name + ".cpd"
        cpx = self.base_name + ".cpx"
        new_sb = SuperBlock(
            version=sb.version,
            replica_placement=sb.replica_placement,
            ttl=sb.ttl,
            compaction_revision=sb.compaction_revision + 1,
            extra=sb.extra,
        )
        snapshot_size = self._compact_snapshot_size
        # the copy runs WITHOUT the volume lock, so it must not touch
        # self._dat: concurrent (locked) writers and readers seek that
        # shared handle, and interleaved seeks would corrupt either the
        # copy or the live file — use a private read-only fd instead
        # (records below the snapshot offset are immutable, append-only)
        with open(cpd, "wb") as dat_out, open(cpx, "wb") as idx_out, open(
            self.base_name + ".dat", "rb"
        ) as dat_in:
            dat_out.write(new_sb.to_bytes())
            from seaweedfs_tpu.storage import idx as idx_codec

            for nv in sorted(snapshot, key=lambda v: v.key):
                if nv.offset == 0 or nv.size == t.TOMBSTONE_FILE_SIZE:
                    continue
                if nv.actual_offset >= snapshot_size:
                    continue  # appended post-snapshot; the diff replays it
                dat_in.seek(nv.actual_offset)
                blob = dat_in.read(get_actual_size(nv.size, self.version))
                new_offset = dat_out.tell()
                dat_out.write(blob)
                idx_out.write(
                    idx_codec.pack_entry(
                        nv.key, t.offset_to_units(new_offset), nv.size
                    )
                )

    def _makeup_diff(self, cpd_path: str, cpx_path: str) -> None:
        """Replay .idx entries appended since the compact snapshot onto
        the scratch files (makeupDiff, volume_vacuum.go:157 — which
        walks the idx tail: the idx distinguishes tombstones from
        legitimate zero-byte needles, where raw .dat records cannot).
        Runs under the volume lock inside commit_compact."""
        from seaweedfs_tpu.storage import idx as idx_codec

        idx_start = getattr(self, "_compact_snapshot_idx", None)
        if idx_start is None:
            # .cpd/.cpx exist but the snapshot boundary is gone (e.g.
            # process restarted between compact and commit): committing
            # would silently drop every post-snapshot write
            raise RuntimeError(
                "compaction scratch files are stale (no snapshot in this "
                "process); run compact again or cleanup_compact"
            )
        idx_path = self.base_name + ".idx"
        with open(idx_path, "rb") as f:
            f.seek(idx_start)
            tail = f.read()
        with open(cpd_path, "r+b") as dat_out, open(cpx_path, "ab") as idx_out:
            dat_out.seek(0, os.SEEK_END)
            for key, offset_units, size in idx_codec.iter_entries(tail):
                if offset_units == 0 or size == t.TOMBSTONE_FILE_SIZE:
                    # append a tombstone RECORD too: the new .dat must
                    # agree with its .idx, or a .dat-scan rebuild
                    # (weed fix / export role) resurrects the needle
                    # (the reference appends a fake delete needle here)
                    tomb = Needle(cookie=0, id=key, data=b"")
                    tomb.append_at_ns = self._now_ns()
                    dat_out.write(tomb.to_bytes(self.version))
                    idx_out.write(
                        idx_codec.pack_entry(key, 0, t.TOMBSTONE_FILE_SIZE)
                    )
                    continue
                blob = self._read_at(
                    t.units_to_offset(offset_units),
                    get_actual_size(size, self.version),
                )
                new_offset = dat_out.tell()
                dat_out.write(blob)
                idx_out.write(
                    idx_codec.pack_entry(
                        key, t.offset_to_units(new_offset), size
                    )
                )
        self._compact_snapshot_idx = None
        self._compact_snapshot_size = None

    def commit_compact(self) -> None:
        """Replay the catch-up diff, then swap .cpd/.cpx in as the live
        files (volume_vacuum.go:157 makeupDiff + commit).

        The swap is TWO renames, so it rides a durable commit-marker
        protocol (the crash enumerator's known suspect — a crash
        between the renames used to leave the new .dat under the old
        .idx, unopenable): scratch bytes are fsynced, then the `.cpm`
        marker is published (THE commit point), then both renames land,
        then the marker is removed. _recover_compaction rolls a crash
        anywhere in that window forward (marker present) or back
        (marker absent); docs/ANALYSIS.md v3 has the state table."""
        with self._lock:
            cpd = self.base_name + ".cpd"
            cpx = self.base_name + ".cpx"
            if not (os.path.exists(cpd) and os.path.exists(cpx)):
                raise FileNotFoundError("no compaction scratch files to commit")
            self._makeup_diff(cpd, cpx)
            # rename-visible-before-data guard: the new generation's
            # BYTES must be durable before any rename can publish them
            durable.fsync_path(cpd)
            durable.fsync_path(cpx)
            marker = self.base_name + ".cpm"
            with open(marker, "wb") as mf:
                mf.write(b"commit\n")
                mf.flush()
                os.fsync(mf.fileno())
            durable.fsync_dir(self.dir)  # commit point: marker durable
            self._dat.close()
            self.nm.close()
            os.replace(cpd, self.base_name + ".dat")
            os.replace(cpx, self.base_name + ".idx")
            durable.fsync_dir(self.dir)  # both renames durable
            # the db needle map's sqlite table indexes the OLD idx —
            # and nm.close() above checkpointed it CLEAN with the old
            # watermark, which can coincidentally equal the compacted
            # idx size, so load would skip the rebuild and serve
            # pre-compaction offsets against the swapped .dat. Remove
            # it INSIDE the marker window: every crash state then
            # either keeps the marker (recovery deletes the table) or
            # has already lost the table here.
            sdb = self.base_name + ".idx.sdb"
            if os.path.exists(sdb):
                os.remove(sdb)
            os.remove(marker)
            durable.fsync_dir(self.dir)
            self._dat = open(self.base_name + ".dat", "r+b")
            self._bind_fd()
            self.super_block = SuperBlock.read_from(self._dat)
            # rebuild the map from the fresh index (the stale sqlite
            # table was removed inside the marker window above)
            self.nm = self._load_needle_map()
            self._followed = self.nm.index_file_size()
            # the fd-swap is THE plan-cache hazard: any cached
            # (fd, offset) pair now points into the pre-compaction file
            _serve_cache_bump()

    def refresh_from_idx(self) -> None:
        """Catch this process's map (and append offset) up with .idx
        entries appended by ANOTHER process — the write-sharding
        follower/handback path (`volume -workers N -shardWrites`): the
        lead calls this for worker-owned volumes before reads and
        heartbeats, and once at ownership handback before any
        file-rewriting admin op (compaction snapshots the IN-MEMORY
        map, so a stale map there would silently drop every entry the
        owner appended — index_file_size() is fstat-based and cannot
        catch it). Only whole 16-byte entries are replayed: a stat
        racing the owner's append may see a torn tail entry."""
        with self._lock:
            try:
                size = os.path.getsize(self.base_name + ".idx")
            except OSError:
                return
            pos = self._followed
            if size <= pos:
                return
            with open(self.base_name + ".idx", "rb") as f:
                f.seek(pos)
                tail = f.read(size - pos)
            from seaweedfs_tpu.storage import idx as idx_codec

            usable = len(tail) - (len(tail) % 16)
            for key, offset, entry_size in idx_codec.iter_entries(tail[:usable]):
                self.nm._replay(key, offset, entry_size)
            self._followed = pos + usable
            if usable:
                _serve_cache_bump()
            # the other process also grew the .dat: re-arm the pwrite
            # append cursor so a post-handback write lands at the tail
            # instead of overwriting the owner's records
            self._append_end = os.fstat(self._fd).st_size

    def cleanup_compact(self) -> None:
        # under the volume lock: the snapshot markers are written by
        # compact()/commit_compact() under it, and an abort racing a
        # late commit must not clear the boundary mid-makeup-diff
        # (weedlint unguarded-write finding, OPERATIONS.md round 9)
        with self._lock:
            self._compact_snapshot_idx = None
            self._compact_snapshot_size = None
            # .cpm too: an abort must never leave a commit marker that
            # a later restart would "roll forward" over fresh data
            for ext in (".cpd", ".cpx", ".cpm"):
                path = self.base_name + ext
                if os.path.exists(path):
                    os.remove(path)

    # --- lifecycle ---
    def close(self) -> None:
        with self._lock:
            self.nm.close()
            self._dat.close()
            _serve_cache_bump()  # unmount: cached fds are now stale

    def destroy(self) -> None:
        with self._lock:
            self.close()
            for ext in (".dat", ".idx", ".cpd", ".cpx", ".cpm"):
                path = self.base_name + ext
                if os.path.exists(path):
                    os.remove(path)


def scan_volume_file(dat_path: str):
    """Walk every record in a .dat sequentially, yielding
    (needle, byte_offset). Deletion tombstones appear as needles with
    size == 0 (the record delete_needle appends). The scanner role of
    the reference's storage.ScanVolumeFile used by `weed fix`/`export`."""
    with open(dat_path, "rb") as f:
        sb = SuperBlock.read_from(f)
        version = sb.version
        offset = sb.block_size()
        f.seek(offset)
        while True:
            header = f.read(t.NEEDLE_HEADER_SIZE)
            if len(header) < t.NEEDLE_HEADER_SIZE:
                return
            _, _, size = Needle.parse_header(header)
            rest_len = get_actual_size(size, version) - t.NEEDLE_HEADER_SIZE
            rest = f.read(rest_len)
            if len(rest) < rest_len:
                return  # torn tail record
            yield Needle.from_bytes(header + rest, version), offset
            offset += t.NEEDLE_HEADER_SIZE + rest_len
