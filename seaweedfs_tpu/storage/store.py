"""Store: the per-server storage engine over N disk locations.

Behavioral match of reference weed/storage/store.go + store_ec.go
(local parts): vid→Volume dispatch for write/read/delete, volume
allocate/mount/delete, EC volume lookup and shard mount/unmount, and
heartbeat assembly (the master-facing volume + EC-shard inventory).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from seaweedfs_tpu.ec.ec_volume import EcVolume
from seaweedfs_tpu.storage.disk_location import DiskLocation
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume


@dataclass
class VolumeInfo:
    """One volume's heartbeat row (pb VolumeInformationMessage)."""

    id: int
    size: int
    collection: str
    file_count: int
    delete_count: int
    deleted_byte_count: int
    read_only: bool
    replica_placement: int
    version: int
    ttl: int

    @staticmethod
    def from_volume(v: Volume) -> "VolumeInfo":
        # stats_snapshot holds the volume lock: the heartbeat thread
        # must not race commit_compact's .dat/.idx + needle-map swap —
        # an unlocked data_file_size() there seeks a CLOSED file, the
        # raised ValueError kills the heartbeat stream, the master's
        # liveness sweep drops the node's volumes, and the next
        # /dir/assign 500s ("no writable volumes"). Root cause of the
        # torn-read/vacuum stack-test flake (CHANGES PR 3); found
        # chasing the weedlint unguarded-write class, OPERATIONS.md
        # round 9.
        return VolumeInfo(
            id=v.id,
            collection=v.collection,
            replica_placement=v.super_block.replica_placement.to_byte(),
            version=v.version,
            ttl=v.ttl.to_uint32(),
            **v.stats_snapshot(),
        )


@dataclass
class EcShardInfo:
    """One EC volume's heartbeat row (pb VolumeEcShardInformationMessage):
    vid + bitmask of locally mounted shards."""

    id: int
    collection: str
    ec_index_bits: int


@dataclass
class ScrubStatInfo:
    """One volume's scrub-plane heartbeat row (pb ScrubStat) as the
    master's topology stores it per data node."""

    volume_id: int
    is_ec: bool
    last_sweep_unix: int
    scanned_bytes: int
    corruptions_found: int
    quarantined_shard_bits: int
    last_error: str


@dataclass
class Heartbeat:
    max_file_key: int
    volumes: list[VolumeInfo] = field(default_factory=list)
    ec_shards: list[EcShardInfo] = field(default_factory=list)


class Store:
    def __init__(
        self,
        directories: list[str],
        max_volume_counts: list[int] | None = None,
        ec_backend: str | None = None,
        needle_map_kind: str = "memory",
    ):
        counts = max_volume_counts or [7] * len(directories)
        self.ec_backend = ec_backend  # `ec.codec`: cpu|native|tpu|None=auto
        self.needle_map_kind = needle_map_kind
        # metric label for this server's scrub gauges ("host:port"; the
        # volume server sets it right after construction)
        self.node_label = ""
        # invoked after any change to the heartbeat-visible inventory
        # (volume add/delete/mount/unmount, readonly flips, EC shard
        # mount/unmount). The volume server points this at its
        # heartbeat wake-up so deltas reach the master immediately —
        # the role of the reference's NewVolumesChan/NewEcShardsChan
        # pushes (store.go:110-120, volume_grpc_client_to_master.go:150-170).
        # The ordering guarantee of the EC migration pipeline (shards
        # mounted and REGISTERED before the volume is deleted) depends
        # on this, not on the periodic tick.
        self.notify_change: callable = lambda: None
        # scrub plane: vid → {shard id → reason} for every EC shard
        # quarantined on this server (truncation caught by a foreground
        # read, or corruption found by the background scrubber). Rides
        # heartbeats as ScrubStat.quarantined_shard_bits and the volume
        # server's /status JSON.
        self.quarantined: dict[int, dict[int, str]] = {}
        self.locations = [
            DiskLocation(
                d, c, ec_backend=ec_backend, needle_map_kind=needle_map_kind
            )
            for d, c in zip(directories, counts)
        ]
        for loc in self.locations:
            loc.load_existing_volumes()
            for ev in loc.ec_volumes.values():
                ev.on_quarantine = self.note_quarantine

    # --- volume management (store.go:165-226) ---
    def has_volume(self, vid: int) -> bool:
        return self.find_volume(vid) is not None

    def find_volume(self, vid: int) -> Optional[Volume]:
        for loc in self.locations:
            v = loc.find_volume(vid)
            if v is not None:
                return v
        return None

    def find_free_location(self) -> Optional[DiskLocation]:
        best, most_free = None, 0
        for loc in self.locations:
            free = loc.max_volume_count - len(loc.volumes)
            if free > most_free:
                best, most_free = loc, free
        return best

    def add_volume(
        self,
        vid: int,
        collection: str = "",
        replica_placement: str = "000",
        ttl: str = "",
        version: int = 3,
    ) -> Volume:
        if self.has_volume(vid):
            raise ValueError(f"volume {vid} already exists")
        loc = self.find_free_location()
        if loc is None:
            raise RuntimeError("no free disk location")
        v = Volume(
            loc.directory,
            vid,
            collection,
            replica_placement=ReplicaPlacement.parse(replica_placement),
            ttl=TTL.parse(ttl),
            version=version,
            needle_map_kind=self.needle_map_kind,
        )
        loc.volumes[vid] = v
        self.notify_change()
        return v

    def delete_volume(self, vid: int) -> bool:
        for loc in self.locations:
            if loc.delete_volume(vid):
                self.notify_change()
                return True
        return False

    def mount_volume(self, vid: int) -> bool:
        for loc in self.locations:
            if loc.mount_volume(vid):
                self.notify_change()
                return True
        return False

    def unmount_volume(self, vid: int) -> bool:
        for loc in self.locations:
            if loc.unmount_volume(vid):
                self.notify_change()
                return True
        return False

    def mark_volume_readonly(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = True
        self.notify_change()
        return True

    def mark_volume_writable(self, vid: int) -> bool:
        v = self.find_volume(vid)
        if v is None:
            return False
        v.read_only = False
        self.notify_change()
        return True

    # --- needle IO (store.go:227-264) ---
    def write_needle(
        self, vid: int, n: Needle, stages: dict | None = None
    ) -> tuple[int, bool]:
        v = self.find_volume(vid)
        if v is None:
            raise NeedleNotFound(f"volume {vid} not found")
        _, size, unchanged = v.write_needle(n, stages=stages)
        return size, unchanged

    def read_needle(self, vid: int, needle_id: int, cookie: int | None = None) -> Needle:
        v = self.find_volume(vid)
        if v is not None:
            return v.read_needle(needle_id, cookie)
        ev = self.find_ec_volume(vid)
        if ev is not None:
            return ev.read_needle(needle_id)
        raise NeedleNotFound(f"volume {vid} not found")

    def delete_needle(self, vid: int, n: Needle) -> int:
        v = self.find_volume(vid)
        if v is None:
            raise NeedleNotFound(f"volume {vid} not found")
        return v.delete_needle(n)

    # --- EC (store_ec.go local parts) ---
    def find_ec_volume(self, vid: int) -> Optional[EcVolume]:
        for loc in self.locations:
            ev = loc.ec_volumes.get(vid)
            if ev is not None:
                return ev  # type: ignore[return-value]
        return None

    def mount_ec_shards(self, vid: int, collection: str, shard_ids: list[int]) -> EcVolume:
        ev = self.find_ec_volume(vid)
        if ev is None:
            loc = self.locations[0]
            ev = EcVolume(loc.directory, vid, collection, backend=self.ec_backend)
            ev.on_quarantine = self.note_quarantine
            loc.ec_volumes[vid] = ev
        for sid in shard_ids:
            ev.mount_shard(sid)
            self.clear_quarantine(vid, sid)
        self.notify_change()
        return ev

    def unmount_ec_shards(self, vid: int, shard_ids: list[int]) -> None:
        ev = self.find_ec_volume(vid)
        if ev is None:
            return
        for sid in shard_ids:
            ev.unmount_shard(sid)
        if not ev.shards and getattr(ev, "remote", None) is None:
            for loc in self.locations:
                loc.ec_volumes.pop(vid, None)
            # the whole EC volume left this node: its local quarantine
            # records are moot
            if self.quarantined.pop(vid, None):
                self._update_quarantine_gauge()
        self.notify_change()

    # --- scrub-plane quarantine registry ---
    def note_quarantine(self, vid: int, shard_id: int, reason: str) -> None:
        """EcVolume.on_quarantine target: record + force a delta beat
        so the master hears about the lost shard NOW, not on the tick."""
        self.quarantined.setdefault(vid, {})[shard_id] = reason
        self._update_quarantine_gauge()
        self.notify_change()

    def clear_quarantine(self, vid: int, shard_id: int) -> None:
        per_vid = self.quarantined.get(vid)
        if per_vid and per_vid.pop(shard_id, None) is not None:
            if not per_vid:
                self.quarantined.pop(vid, None)
            self._update_quarantine_gauge()

    def quarantined_shard_bits(self, vid: int) -> int:
        bits = 0
        for sid in self.quarantined.get(vid, ()):
            bits |= 1 << sid
        return bits

    def _update_quarantine_gauge(self) -> None:
        from seaweedfs_tpu.stats.metrics import SCRUB_QUARANTINED

        SCRUB_QUARANTINED.set(
            sum(len(d) for d in self.quarantined.values()),
            self.node_label,
        )

    # --- heartbeat (store.go CollectHeartbeat) ---
    def collect_heartbeat(self) -> Heartbeat:
        hb = Heartbeat(max_file_key=0)
        for loc in self.locations:
            # list() snapshots: allocate/delete/mount RPCs (and the
            # repair scheduler's VolumeCopy) mutate these dicts from
            # other threads; iterating the live dict here killed the
            # heartbeat STREAM with "dictionary changed size" — the
            # master then saw the node flap
            for v in list(loc.volumes.values()):
                hb.max_file_key = max(hb.max_file_key, v.max_file_key())
                hb.volumes.append(VolumeInfo.from_volume(v))
            for vid, ev in list(loc.ec_volumes.items()):
                bits = 0
                # serving ids = local mounts ∪ tiered remote shards: a
                # fully tiered volume must keep routing to this node
                # and must not read as "missing shards" to the repair
                # scheduler (docs/TIERING.md)
                for sid in ev.serving_shard_ids():  # type: ignore[attr-defined]
                    bits |= 1 << sid
                hb.ec_shards.append(
                    EcShardInfo(vid, ev.collection, bits)  # type: ignore[attr-defined]
                )
        return hb

    def close(self) -> None:
        for loc in self.locations:
            loc.close()
