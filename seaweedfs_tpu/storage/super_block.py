"""Volume superblock: the first 8 bytes of every .dat file.

Bit-compatible with reference weed/storage/super_block/super_block.go:16-67:
  byte 0     version (1, 2 or 3)
  byte 1     replica placement byte
  bytes 2-3  TTL
  bytes 4-5  compaction revision (big-endian uint16)
  bytes 6-7  extra-size (uint16) — length of a trailing protobuf blob
             (we preserve unknown extra bytes opaquely)
"""

from __future__ import annotations

from dataclasses import dataclass, field

from seaweedfs_tpu.storage.replica_placement import ReplicaPlacement
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.util import bytesutil

SUPER_BLOCK_SIZE = 8

VERSION1 = 1
VERSION2 = 2
VERSION3 = 3
CURRENT_VERSION = VERSION3


@dataclass
class SuperBlock:
    version: int = CURRENT_VERSION
    replica_placement: ReplicaPlacement = field(default_factory=ReplicaPlacement)
    ttl: TTL = field(default_factory=TTL)
    compaction_revision: int = 0
    extra: bytes = b""

    def block_size(self) -> int:
        if self.version in (VERSION2, VERSION3):
            return SUPER_BLOCK_SIZE + len(self.extra)
        return SUPER_BLOCK_SIZE

    def to_bytes(self) -> bytes:
        header = bytearray(SUPER_BLOCK_SIZE)
        header[0] = self.version
        header[1] = self.replica_placement.to_byte()
        header[2:4] = self.ttl.to_bytes()
        header[4:6] = bytesutil.put_u16(self.compaction_revision)
        if self.extra:
            if len(self.extra) > 256 * 256 - 2:
                raise ValueError(f"super block extra too large: {len(self.extra)}")
            header[6:8] = bytesutil.put_u16(len(self.extra))
            return bytes(header) + self.extra
        return bytes(header)

    @staticmethod
    def from_bytes(header: bytes) -> "SuperBlock":
        """Parse a superblock; `header` must contain the full 8-byte block
        plus any declared extra bytes (truncation raises)."""
        if len(header) < SUPER_BLOCK_SIZE:
            raise ValueError("cannot read volume superblock: file too short")
        version = header[0]
        if version not in (VERSION1, VERSION2, VERSION3):
            raise ValueError(f"unsupported volume version {version}")
        extra_size = bytesutil.get_u16(header, 6)
        if len(header) < SUPER_BLOCK_SIZE + extra_size:
            raise ValueError(
                f"superblock declares {extra_size} extra bytes but only "
                f"{len(header) - SUPER_BLOCK_SIZE} present"
            )
        return SuperBlock(
            version=version,
            replica_placement=ReplicaPlacement.from_byte(header[1]),
            ttl=TTL.from_bytes(header[2:4]),
            compaction_revision=bytesutil.get_u16(header, 4),
            extra=bytes(header[SUPER_BLOCK_SIZE : SUPER_BLOCK_SIZE + extra_size]),
        )

    @staticmethod
    def read_from(f) -> "SuperBlock":
        """Read a superblock from the start of an open binary file."""
        f.seek(0)
        header = f.read(SUPER_BLOCK_SIZE)
        if len(header) != SUPER_BLOCK_SIZE:
            raise ValueError("cannot read volume superblock: file too short")
        extra_size = bytesutil.get_u16(header, 6)
        return SuperBlock.from_bytes(header + (f.read(extra_size) if extra_size else b""))
