"""Storage-backend SPI: where a volume's sealed .dat bytes live.

Behavioral match of reference weed/storage/backend/backend.go:15-46:

  BackendStorageFile  random-access surface over one volume's data
                      (local disk file, or ranged reads against a
                      remote object store)
  BackendStorage      a configured remote tier (e.g. one S3 bucket):
                      copy a sealed .dat up, stream it back down,
                      open a BackendStorageFile over the remote copy
  registry            type → factory; "s3.default"-style instance
                      names built from TOML config
                      (LoadConfiguration, backend.go:47-76)

The hot volume path stays on plain local files; remote tiers serve
sealed (read-only) volumes — the warm/cold tier the
VolumeTierMoveDatToRemote/FromRemote RPCs manage.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

ProgressFn = Optional[Callable[[int, float], None]]


class BackendStorageFile:
    """Random-access file surface (io.ReaderAt/WriterAt analogue)."""

    def read_at(self, length: int, offset: int) -> bytes:
        raise NotImplementedError

    def write_at(self, data: bytes, offset: int) -> int:
        raise NotImplementedError

    def truncate(self, size: int) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def get_stat(self) -> tuple[int, float]:
        """(size bytes, mtime seconds)."""
        raise NotImplementedError

    def name(self) -> str:
        raise NotImplementedError


class DiskFile(BackendStorageFile):
    """Local-disk backend (backend/disk_file.go)."""

    def __init__(self, path: str):
        self.path = path
        if not os.path.exists(path):
            with open(path, "wb"):
                pass
        self.f = open(path, "r+b")

    def read_at(self, length: int, offset: int) -> bytes:
        self.f.seek(offset)
        return self.f.read(length)

    def write_at(self, data: bytes, offset: int) -> int:
        self.f.seek(offset)
        n = self.f.write(data)
        return n

    def truncate(self, size: int) -> None:
        self.f.truncate(size)

    def flush(self) -> None:
        self.f.flush()

    def close(self) -> None:
        self.f.close()

    def get_stat(self) -> tuple[int, float]:
        st = os.fstat(self.f.fileno())
        return st.st_size, st.st_mtime

    def name(self) -> str:
        return self.path


class BackendStorage:
    """One configured remote tier (backend.go BackendStorage)."""

    storage_type = ""
    id = ""

    @property
    def name(self) -> str:
        return f"{self.storage_type}.{self.id}"

    def to_properties(self) -> dict:
        raise NotImplementedError

    def new_storage_file(self, key: str, file_size: int) -> BackendStorageFile:
        raise NotImplementedError

    def copy_file(
        self, local_path: str, attributes: dict, progress: ProgressFn = None
    ) -> tuple[str, int]:
        """Upload; returns (remote key, size)."""
        raise NotImplementedError

    def download_file(
        self, local_path: str, key: str, progress: ProgressFn = None
    ) -> int:
        raise NotImplementedError

    def delete_file(self, key: str) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# registry (backend.go BackendStorageFactories / BackendStorages)

_FACTORIES: dict[str, Callable[..., BackendStorage]] = {}
BACKEND_STORAGES: dict[str, BackendStorage] = {}


def register_backend_factory(
    storage_type: str, factory: Callable[..., BackendStorage]
) -> None:
    _FACTORIES[storage_type] = factory


def backend_name_to_type_id(name: str) -> tuple[str, str]:
    """"s3.default" → ("s3", "default"); bare "s3" → ("s3", "default")."""
    if "." in name:
        t, _, i = name.partition(".")
        return t, i
    return name, "default"


def register_backend(storage: BackendStorage) -> None:
    BACKEND_STORAGES[storage.name] = storage


def get_backend(name: str) -> BackendStorage | None:
    t, i = backend_name_to_type_id(name)
    return BACKEND_STORAGES.get(f"{t}.{i}")


def load_backend_config(cfg: dict) -> None:
    """Build backend instances from a config tree shaped like the
    reference's storage.toml:

        {"s3": {"default": {"enabled": True, "endpoint": ..., ...}}}
    """
    for storage_type, instances in (cfg or {}).items():
        factory = _FACTORIES.get(storage_type)
        if factory is None:
            raise ValueError(f"backend storage type {storage_type!r} not found")
        for instance_id, props in (instances or {}).items():
            if not props.get("enabled"):
                continue
            register_backend(factory(instance_id, props))


def _ensure_builtin_factories() -> None:
    from seaweedfs_tpu.storage import backend_dir  # noqa: F401
    from seaweedfs_tpu.storage import backend_s3  # noqa: F401


_ensure_builtin_factories_done = False


def ensure_builtin_factories() -> None:
    global _ensure_builtin_factories_done
    if not _ensure_builtin_factories_done:
        _ensure_builtin_factories()
        _ensure_builtin_factories_done = True
