"""External file ids: "<vid>,<key_hex><cookie_hex>".

Matches reference weed/storage/needle/file_id.go: the key is hex with
leading zeros stripped (minimum one nibble pair), the cookie is always
8 hex chars appended.
"""

from __future__ import annotations

import re

from dataclasses import dataclass

from seaweedfs_tpu.storage.types import parse_cookie, parse_needle_id

_HEX_RE = re.compile(r"[0-9a-fA-F]+\Z")


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{format_needle_id_cookie(self.key, self.cookie)}"

    @staticmethod
    def parse(fid: str) -> "FileId":
        comma = fid.find(",")
        if comma <= 0:
            raise ValueError(f"unknown file id {fid!r}")
        vid_str = fid[:comma]
        if not vid_str.isdigit():
            raise ValueError(f"unknown volume id in {fid!r}")
        vid = int(vid_str)
        key, cookie = parse_needle_id_cookie(fid[comma + 1 :])
        return FileId(vid, key, cookie)


def format_needle_id_cookie(key: int, cookie: int) -> str:
    """needle.go:173 formatNeedleIdCookie — key hex (zero-stripped,
    even-length) + 8-char cookie hex."""
    key_hex = f"{key:016x}"
    cookie_hex = f"{cookie:08x}"
    non_zero = 0
    while non_zero < len(key_hex) - 1 and key_hex[non_zero] == "0":
        non_zero += 1
    non_zero -= non_zero & 1  # keep whole byte pairs
    return key_hex[non_zero:] + cookie_hex


_MAX_KEY_COOKIE_LEN = (8 + 4) * 2  # (NeedleIdSize + CookieSize) hex chars


def parse_path_fid(vid_str: str, fid_str: str) -> "FileId":
    """fid string with the optional `_delta` appendix → FileId
    (needle.go:149 ParsePath): `01637037d6_3` reads needle id+3 —
    the addressing scheme chunked uploads use for the sub-fids minted
    from one assign with count=N."""
    if not vid_str.isdigit():
        raise ValueError(f"unknown volume id in {vid_str!r}")
    delta = 0
    sep = fid_str.rfind("_")
    if sep > 0:
        delta_str = fid_str[sep + 1 :]
        if not delta_str.isdigit():
            raise ValueError(f"bad fid delta in {fid_str!r}")
        fid_str, delta = fid_str[:sep], int(delta_str)
    key, cookie = parse_needle_id_cookie(fid_str)
    return FileId(int(vid_str), key + delta, cookie)


def parse_url_path(path: str) -> tuple[str, str, str, str, bool]:
    """Volume-server URL → (vid, fid, filename, ext, is_vid_only),
    the reference's public addressing forms (server/common.go:152
    parseURLPath):

      /3,01637037d6[.ext]          comma form (+optional extension)
      /3/01637037d6[.ext]          slash form
      /3/01637037d6/my photo.jpg   slash form with an explicit filename
      /3                           volume id only

    Percent-escapes are decoded PER SEGMENT after splitting (the
    filename may encode "/" or "," without changing the structure —
    Go's mux decodes the same way)."""
    from urllib.parse import unquote
    vid = fid = filename = ext = ""
    is_vid_only = False
    slashes = path.count("/")
    if slashes == 3:
        _, vid, fid, filename = path.split("/")
        filename = unquote(filename)
        i = filename.rfind(".")
        if i > 0:
            ext = filename[i:]
    elif slashes == 2:
        _, vid, fid = path.split("/")
        i = fid.rfind(".")
        if i > 0:
            fid, ext = fid[:i], fid[i:]
    else:
        sep = path.rfind("/")
        tail = path[sep + 1 :]
        comma = tail.rfind(",")
        if comma <= 0:
            return tail, "", "", "", True
        dot = tail.rfind(".")
        vid = tail[:comma]
        if dot > 0:
            fid, ext = tail[comma + 1 : dot], tail[dot:]
        else:
            fid = tail[comma + 1 :]
    return vid, fid, filename, ext, is_vid_only


def parse_needle_id_cookie(key_cookie: str) -> tuple[int, int]:
    """needle.go:181 ParseNeedleIdCookie (incl. the max-length check).

    One strict-hex validation over the whole string (Go ParseUint
    semantics: no sign/prefix/underscore), then plain slicing — this
    runs once per data-plane request, so it avoids the two-regex
    two-call shape of parse_needle_id + parse_cookie."""
    n = len(key_cookie)
    if n <= 8:
        raise ValueError(f"needle id too short: {key_cookie!r}")
    if n > _MAX_KEY_COOKIE_LEN:
        raise ValueError(f"key hash too long: {key_cookie!r}")
    if not _HEX_RE.match(key_cookie):
        # delegate for the exact per-field error text
        split = n - 8
        return parse_needle_id(key_cookie[:split]), parse_cookie(key_cookie[split:])
    split = n - 8
    return int(key_cookie[:split], 16), int(key_cookie[split:], 16)
