"""External file ids: "<vid>,<key_hex><cookie_hex>".

Matches reference weed/storage/needle/file_id.go: the key is hex with
leading zeros stripped (minimum one nibble pair), the cookie is always
8 hex chars appended.
"""

from __future__ import annotations

import re

from dataclasses import dataclass

from seaweedfs_tpu.storage.types import parse_cookie, parse_needle_id

_HEX_RE = re.compile(r"[0-9a-fA-F]+\Z")


@dataclass(frozen=True)
class FileId:
    volume_id: int
    key: int
    cookie: int

    def __str__(self) -> str:
        return f"{self.volume_id},{format_needle_id_cookie(self.key, self.cookie)}"

    @staticmethod
    def parse(fid: str) -> "FileId":
        comma = fid.find(",")
        if comma <= 0:
            raise ValueError(f"unknown file id {fid!r}")
        vid_str = fid[:comma]
        if not vid_str.isdigit():
            raise ValueError(f"unknown volume id in {fid!r}")
        vid = int(vid_str)
        key, cookie = parse_needle_id_cookie(fid[comma + 1 :])
        return FileId(vid, key, cookie)


def format_needle_id_cookie(key: int, cookie: int) -> str:
    """needle.go:173 formatNeedleIdCookie — key hex (zero-stripped,
    even-length) + 8-char cookie hex."""
    key_hex = f"{key:016x}"
    cookie_hex = f"{cookie:08x}"
    non_zero = 0
    while non_zero < len(key_hex) - 1 and key_hex[non_zero] == "0":
        non_zero += 1
    non_zero -= non_zero & 1  # keep whole byte pairs
    return key_hex[non_zero:] + cookie_hex


_MAX_KEY_COOKIE_LEN = (8 + 4) * 2  # (NeedleIdSize + CookieSize) hex chars


def parse_needle_id_cookie(key_cookie: str) -> tuple[int, int]:
    """needle.go:181 ParseNeedleIdCookie (incl. the max-length check).

    One strict-hex validation over the whole string (Go ParseUint
    semantics: no sign/prefix/underscore), then plain slicing — this
    runs once per data-plane request, so it avoids the two-regex
    two-call shape of parse_needle_id + parse_cookie."""
    n = len(key_cookie)
    if n <= 8:
        raise ValueError(f"needle id too short: {key_cookie!r}")
    if n > _MAX_KEY_COOKIE_LEN:
        raise ValueError(f"key hash too long: {key_cookie!r}")
    if not _HEX_RE.match(key_cookie):
        # delegate for the exact per-field error text
        split = n - 8
        return parse_needle_id(key_cookie[:split]), parse_cookie(key_cookie[split:])
    split = n - 8
    return int(key_cookie[:split], 16), int(key_cookie[split:], 16)
