"""DiskLocation: one data directory of volumes and EC shards.

Behavioral match of reference weed/storage/disk_location.go +
disk_location_ec.go: scan the directory for `[collection_]<vid>.dat`
volumes and `[collection_]<vid>.ec00-13` shard sets, load them, and
serve vid→Volume / vid→EcVolume lookups. (The reference loads with an
8-way worker pool; volumes here load sequentially — directory scan is
not a hot path for this build.)
"""

from __future__ import annotations

import os
import re
from typing import Optional

from seaweedfs_tpu.storage.volume import Volume

_DAT_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.dat$")
_EC_RE = re.compile(r"^(?:(?P<collection>.+)_)?(?P<vid>\d+)\.ec(?P<shard>\d{2})$")


def parse_volume_file_name(name: str) -> tuple[str, int] | None:
    """volume file name → (collection, vid), or None if not a .dat."""
    m = _DAT_RE.match(name)
    if not m:
        return None
    return m.group("collection") or "", int(m.group("vid"))


def parse_ec_shard_file_name(name: str) -> tuple[str, int, int] | None:
    m = _EC_RE.match(name)
    if not m:
        return None
    return m.group("collection") or "", int(m.group("vid")), int(m.group("shard"))


class DiskLocation:
    def __init__(
        self,
        directory: str,
        max_volume_count: int = 7,
        ec_backend: str | None = None,
        needle_map_kind: str = "memory",
    ):
        self.directory = directory
        self.max_volume_count = max_volume_count
        self.ec_backend = ec_backend  # `ec.codec` for EC volumes here
        self.needle_map_kind = needle_map_kind  # -index memory|db
        self.volumes: dict[int, Volume] = {}
        # vid -> EcVolume; populated by load_existing_volumes and the
        # EC mount RPCs (seaweedfs_tpu/ec/ec_volume.py)
        self.ec_volumes: dict[int, object] = {}

    def load_existing_volumes(self) -> None:
        os.makedirs(self.directory, exist_ok=True)
        for name in sorted(os.listdir(self.directory)):
            parsed = parse_volume_file_name(name)
            if parsed is None and name.endswith(".vif"):
                # remote-tiered volume: .dat lives in a backend; the
                # .vif + .idx are enough to load it read-only
                parsed = parse_volume_file_name(name[:-4] + ".dat")
            if parsed is None:
                continue
            collection, vid = parsed
            if vid in self.volumes:
                continue
            try:
                # repair: server startup is the exclusive owner of
                # these files — the one safe point to roll a crashed
                # vacuum commit forward/back and heal torn tails
                # (docs/ANALYSIS.md v3); follower/worker opens must
                # never pass it
                self.volumes[vid] = Volume(
                    self.directory,
                    vid,
                    collection,
                    create=False,
                    needle_map_kind=self.needle_map_kind,
                    repair=True,
                )
            except (OSError, ValueError):
                continue  # unloadable volume; reference logs and skips
        self._load_ec_shards()

    def _load_ec_shards(self) -> None:
        from seaweedfs_tpu.ec.ec_volume import EcVolume

        shard_sets: dict[tuple[str, int], list[int]] = {}
        for name in sorted(os.listdir(self.directory)):
            parsed = parse_ec_shard_file_name(name)
            if parsed is None and name.endswith(".evf"):
                # fully tiered EC volume: zero local .ec?? files, but
                # the .evf + .ecx are enough to serve from the backend
                p = parse_volume_file_name(name[:-4] + ".dat")
                if p is not None:
                    shard_sets.setdefault((p[0], p[1]), [])
                continue
            if parsed is None:
                continue
            collection, vid, shard = parsed
            shard_sets.setdefault((collection, vid), []).append(shard)
        for (collection, vid), shards in shard_sets.items():
            if vid in self.ec_volumes:
                continue
            try:
                self.ec_volumes[vid] = EcVolume.load(
                    self.directory, vid, collection, backend=self.ec_backend
                )
            except (OSError, ValueError):
                continue

    def find_volume(self, vid: int) -> Optional[Volume]:
        return self.volumes.get(vid)

    def has_volume(self, vid: int) -> bool:
        return vid in self.volumes

    def delete_volume(self, vid: int) -> bool:
        v = self.volumes.pop(vid, None)
        if v is None:
            return False
        v.destroy()
        return True

    def unmount_volume(self, vid: int) -> bool:
        """Close and forget a volume, keeping its files on disk
        (disk_location.go UnloadVolume)."""
        v = self.volumes.pop(vid, None)
        if v is None:
            return False
        v.close()
        return True

    def mount_volume(self, vid: int) -> bool:
        """(Re)load one volume from this directory's files
        (disk_location.go LoadVolume)."""
        if vid in self.volumes:
            return True
        for name in sorted(os.listdir(self.directory)):
            parsed = parse_volume_file_name(name)
            if parsed is None or parsed[1] != vid:
                continue
            collection = parsed[0]
            try:
                # no repair here: a runtime remount can race a live
                # -shardWrites worker appending to the same files —
                # only the startup load (above) is provably exclusive
                self.volumes[vid] = Volume(
                    self.directory,
                    vid,
                    collection,
                    create=False,
                    needle_map_kind=self.needle_map_kind,
                )
                return True
            except (OSError, ValueError):
                return False
        return False

    def close(self) -> None:
        for v in self.volumes.values():
            v.close()
        for ev in self.ec_volumes.values():
            close = getattr(ev, "close", None)
            if close:
                close()
