"""`.vif` — per-volume tier metadata (remote file locations).

Behavioral match of reference weed/pb/volume_info.go: the VolumeInfo
protobuf (volume_server.proto:346-358) serialized as jsonpb next to
the volume files. Field names follow jsonpb camelCase so a .vif
written here parses in the reference and vice versa."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from seaweedfs_tpu.util import durable


@dataclass
class RemoteFile:
    backend_type: str = ""
    backend_id: str = ""
    key: str = ""
    offset: int = 0
    file_size: int = 0
    modified_time: int = 0
    extension: str = ""

    @property
    def backend_name(self) -> str:
        return f"{self.backend_type}.{self.backend_id}"

    def to_json(self) -> dict:
        return {
            "backendType": self.backend_type,
            "backendId": self.backend_id,
            "key": self.key,
            "offset": str(self.offset),
            "fileSize": str(self.file_size),
            "modifiedTime": str(self.modified_time),
            "extension": self.extension,
        }

    @classmethod
    def from_json(cls, d: dict) -> "RemoteFile":
        return cls(
            backend_type=d.get("backendType", ""),
            backend_id=d.get("backendId", ""),
            key=d.get("key", ""),
            offset=int(d.get("offset", 0) or 0),
            file_size=int(d.get("fileSize", 0) or 0),
            modified_time=int(d.get("modifiedTime", 0) or 0),
            extension=d.get("extension", ""),
        )


@dataclass
class VolumeInfo:
    files: list[RemoteFile] = field(default_factory=list)
    version: int = 0

    def has_remote_file(self) -> bool:
        return bool(self.files)


def maybe_load_volume_info(file_name: str) -> tuple[VolumeInfo, bool]:
    """(info, found-with-remote-files) — never returns None
    (MaybeLoadVolumeInfo, volume_info.go:18)."""
    vi = VolumeInfo()
    if not os.path.exists(file_name):
        return vi, False
    try:
        with open(file_name) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return vi, False
    vi.version = int(d.get("version", 0) or 0)
    vi.files = [RemoteFile.from_json(x) for x in d.get("files", [])]
    return vi, vi.has_remote_file()


def save_volume_info(file_name: str, vi: VolumeInfo) -> None:
    tmp = file_name + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "files": [rf.to_json() for rf in vi.files],
                "version": str(vi.version),
            },
            f,
            indent=2,
        )
    # durable publish: the .vif decides at load time whether the .dat
    # is local or remote — a lost/torn one after tier_upload deleted
    # the local .dat would leave the volume unloadable
    durable.publish(tmp, file_name)
