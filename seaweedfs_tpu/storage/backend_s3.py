"""S3 remote tier: sealed .dat files living in an S3-compatible bucket.

Behavioral match of reference
weed/storage/backend/s3_backend/s3_backend.go:29-175: CopyFile uploads
the sealed volume data, DownloadFile streams it back, and
S3StorageFile serves ReadAt as ranged GETs so a tiered volume's
needles are readable without the local .dat. Works against any
S3-compatible endpoint — including this repo's own S3 gateway, which
is how the tests exercise it with zero external dependencies."""

from __future__ import annotations

import time
import uuid

from seaweedfs_tpu.s3api.client import S3Client
from seaweedfs_tpu.storage import backend as b


class S3StorageFile(b.BackendStorageFile):
    def __init__(self, storage: "S3BackendStorage", key: str, file_size: int):
        self.storage = storage
        self.key = key
        self.file_size = file_size

    def read_at(self, length: int, offset: int) -> bytes:
        if offset >= self.file_size:
            return b""
        length = min(length, self.file_size - offset)
        return self.storage.client.get_object(
            self.storage.bucket, self.key, offset, length
        )

    def write_at(self, data: bytes, offset: int) -> int:
        raise IOError("s3 tier volumes are sealed (read-only)")

    def truncate(self, size: int) -> None:
        raise IOError("s3 tier volumes are sealed (read-only)")

    def close(self) -> None:
        pass

    def get_stat(self) -> tuple[int, float]:
        return self.file_size, time.time()

    def name(self) -> str:
        return f"s3://{self.storage.bucket}/{self.key}"


class S3BackendStorage(b.BackendStorage):
    storage_type = "s3"

    def __init__(self, instance_id: str, props: dict):
        self.id = instance_id
        self.endpoint = props["endpoint"]
        self.bucket = props["bucket"]
        self.region = props.get("region", "us-east-1")
        self._props = dict(props)
        self.client = S3Client(
            self.endpoint,
            props.get("aws_access_key_id", props.get("access_key", "")),
            props.get("aws_secret_access_key", props.get("secret_key", "")),
            region=self.region,
        )

    def to_properties(self) -> dict:
        return {k: str(v) for k, v in self._props.items() if "secret" not in k}

    def new_storage_file(self, key: str, file_size: int) -> S3StorageFile:
        return S3StorageFile(self, key, file_size)

    def copy_file(self, local_path: str, attributes: dict, progress=None):
        """Streamed upload — a 30 GB sealed .dat never lives in memory
        as one buffer (the reference streams via multipart upload)."""
        import os

        key = f"{uuid.uuid4().hex}{attributes.get('ext', '.dat')}"
        size = os.path.getsize(local_path)
        with open(local_path, "rb") as f:
            self.client.put_object_stream(self.bucket, key, f, size, progress)
        return key, size

    def download_file(self, local_path: str, key: str, progress=None) -> int:
        return self.client.get_object_to_file(
            self.bucket, key, local_path, progress
        )

    def delete_file(self, key: str) -> None:
        self.client.delete_object(self.bucket, key)


b.register_backend_factory("s3", S3BackendStorage)
