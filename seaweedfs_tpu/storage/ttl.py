"""2-byte (count, unit) volume/needle TTL.

Bit-compatible with reference weed/storage/needle/volume_ttl.go:
stored as [count, unit] bytes; unit enum Empty..Year; string forms like
"3m", "4h", "5d", "6w", "7M", "8y" (bare digits imply minutes).
"""

from __future__ import annotations

from dataclasses import dataclass

EMPTY = 0
MINUTE = 1
HOUR = 2
DAY = 3
WEEK = 4
MONTH = 5
YEAR = 6

_UNIT_FROM_CHAR = {"m": MINUTE, "h": HOUR, "d": DAY, "w": WEEK, "M": MONTH, "y": YEAR}
_CHAR_FROM_UNIT = {v: k for k, v in _UNIT_FROM_CHAR.items()}

_UNIT_MINUTES = {
    MINUTE: 1,
    HOUR: 60,
    DAY: 24 * 60,
    WEEK: 7 * 24 * 60,
    MONTH: 31 * 24 * 60,
    YEAR: 365 * 24 * 60,
}


@dataclass(frozen=True)
class TTL:
    count: int = 0
    unit: int = EMPTY

    @staticmethod
    def parse(ttl_string: str) -> "TTL":
        if not ttl_string:
            return TTL()
        unit_char = ttl_string[-1]
        if unit_char.isdigit():
            count_str, unit = ttl_string, MINUTE
        else:
            count_str = ttl_string[:-1]
            if unit_char not in _UNIT_FROM_CHAR:
                raise ValueError(f"unknown TTL unit {unit_char!r}")
            unit = _UNIT_FROM_CHAR[unit_char]
        count = int(count_str)
        if not 0 <= count <= 255:
            raise ValueError(f"TTL count {count} out of byte range")
        return TTL(count, unit)

    @staticmethod
    def from_bytes(b: bytes) -> "TTL":
        if b[0] == 0 and b[1] == 0:
            return TTL()
        return TTL(b[0], b[1])

    @staticmethod
    def from_uint32(v: int) -> "TTL":
        return TTL.from_bytes(bytes([(v >> 8) & 0xFF, v & 0xFF]))

    def to_bytes(self) -> bytes:
        return bytes([self.count & 0xFF, self.unit & 0xFF])

    def to_uint32(self) -> int:
        if self.count == 0:
            return 0
        return ((self.count & 0xFF) << 8) | (self.unit & 0xFF)

    @property
    def minutes(self) -> int:
        if self.count == 0 or self.unit == EMPTY:
            return 0
        return self.count * _UNIT_MINUTES[self.unit]

    def __str__(self) -> str:
        if self.count == 0 or self.unit == EMPTY:
            return ""
        return f"{self.count}{_CHAR_FROM_UNIT[self.unit]}"
