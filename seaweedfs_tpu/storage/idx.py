""".idx index-file entries: 16 bytes = key u64 | offset u32 | size u32.

Matches reference weed/storage/idx/walk.go. Offsets are stored in
8-byte units (storage/types.py); size==TOMBSTONE_FILE_SIZE or a zero
offset marks a deletion entry.

Entries are exposed as (key, offset_units, size) int tuples; numpy
bulk paths (sorting for .ecx, binary search) operate on the raw bytes
as a [N, 16] u8 view to avoid per-entry Python cost on million-entry
indexes.
"""

from __future__ import annotations

import io
from typing import Callable, Iterator

import numpy as np

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.util import bytesutil

ENTRY_SIZE = t.NEEDLE_MAP_ENTRY_SIZE  # 16 (17 under 5-byte offsets)


def pack_entry(key: int, offset_units: int, size: int) -> bytes:
    return (
        t.needle_id_to_bytes(key)
        + t.offset_to_bytes(offset_units)
        + bytesutil.put_u32(size)
    )


def unpack_entry(b: bytes, off: int = 0) -> tuple[int, int, int]:
    key = bytesutil.get_u64(b, off)
    offset_units = t.bytes_to_offset(b[off + 8 : off + 8 + t.OFFSET_SIZE])
    size = bytesutil.get_u32(b, off + 8 + t.OFFSET_SIZE)
    return key, offset_units, size


def iter_entries(data: bytes) -> Iterator[tuple[int, int, int]]:
    for off in range(0, len(data) - ENTRY_SIZE + 1, ENTRY_SIZE):
        yield unpack_entry(data, off)


def walk_index_file(
    f: io.BufferedIOBase,
    fn: Callable[[int, int, int], None],
    rows_to_read: int = 1024,
) -> None:
    """Stream (key, offset_units, size) entries to `fn` (idx/walk.go:14)."""
    f.seek(0)
    while True:
        chunk = f.read(ENTRY_SIZE * rows_to_read)
        if not chunk:
            return
        for off in range(0, len(chunk) - ENTRY_SIZE + 1, ENTRY_SIZE):
            fn(*unpack_entry(chunk, off))
        if len(chunk) < ENTRY_SIZE * rows_to_read:
            return


# --- numpy bulk views -------------------------------------------------------

def entries_as_arrays(data: bytes) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Decode a whole .idx/.ecx byte blob to (keys u64, offsets u64,
    sizes u32) arrays in one vectorized pass. Honors the process
    offset size (4- or 5-byte entries)."""
    osz = t.OFFSET_SIZE
    entry = t.NEEDLE_MAP_ENTRY_SIZE
    n = len(data) // entry
    raw = np.frombuffer(data, dtype=np.uint8, count=n * entry).reshape(n, entry)
    keys = raw[:, :8].copy().view(">u8").reshape(n).astype(np.uint64)
    # big-endian offsets of arbitrary width: widen to 8 bytes, view u64
    off8 = np.zeros((n, 8), dtype=np.uint8)
    off8[:, 8 - osz :] = raw[:, 8 : 8 + osz]
    offsets = off8.view(">u8").reshape(n).astype(np.uint64)
    sizes = (
        raw[:, 8 + osz : 8 + osz + 4].copy().view(">u4").reshape(n).astype(np.uint32)
    )
    return keys, offsets, sizes


def arrays_to_entries(keys: np.ndarray, offsets: np.ndarray, sizes: np.ndarray) -> bytes:
    osz = t.OFFSET_SIZE
    entry = t.NEEDLE_MAP_ENTRY_SIZE
    n = len(keys)
    raw = np.empty((n, entry), dtype=np.uint8)
    raw[:, :8] = keys.astype(">u8").reshape(n, 1).view(np.uint8)
    off8 = offsets.astype(">u8").reshape(n, 1).view(np.uint8).reshape(n, 8)
    raw[:, 8 : 8 + osz] = off8[:, 8 - osz :]
    raw[:, 8 + osz : 8 + osz + 4] = sizes.astype(">u4").reshape(n, 1).view(np.uint8)
    return raw.tobytes()
