"""Per-volume needle index: id → (offset units, size), with metrics.

Behavioral match of reference weed/storage/needle_map.go +
needle_map_memory.go: every Put/Delete is appended to the .idx file
(the map is the .idx replayed), metrics track live/deleted counts and
bytes, deletes keep a tombstone entry. The in-memory representation
here is a plain dict — the reference's CompactMap is a Go-specific
memory optimization (16B/entry arrays); the observable semantics
(last-wins replay, tombstones, metrics, ascending visit) are what the
rest of the system depends on.

A numpy-backed sorted snapshot (SortedNeedleMap) covers the
sorted-file/.ecx binary-search use cases (needle_map_sorted_file.go).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

import numpy as np

from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # units of 8 bytes (types.py)
    size: int

    @property
    def actual_offset(self) -> int:
        return t.units_to_offset(self.offset)


class CompactNeedleMap:
    """In-memory map mirrored to an append-only .idx file."""

    def __init__(self, index_path: str):
        self._index_path = index_path
        self._m: dict[int, tuple[int, int]] = {}  # key -> (offset, size)
        self._index_file = None
        # mapMetric (needle_map_metric.go)
        self.file_count = 0
        self.file_byte_count = 0
        self.deletion_count = 0
        self.deletion_byte_count = 0
        self.max_file_key = 0

    # --- lifecycle ---
    @classmethod
    def load(cls, index_path: str) -> "CompactNeedleMap":
        """Replay an existing .idx (doLoading, needle_map_memory.go:30)."""
        nm = cls(index_path)
        if os.path.exists(index_path):
            with open(index_path, "rb") as f:
                data = f.read()
            for key, offset, size in idx_codec.iter_entries(data):
                nm._replay(key, offset, size)
        nm._index_file = open(index_path, "ab")
        return nm

    def _replay(self, key: int, offset: int, size: int) -> None:
        self.max_file_key = max(self.max_file_key, key)
        if offset != 0 and size != t.TOMBSTONE_FILE_SIZE:
            self.file_count += 1
            self.file_byte_count += size
            old = self._m.get(key)
            self._m[key] = (offset, size)
            if old is not None and old[0] != 0 and old[1] != t.TOMBSTONE_FILE_SIZE:
                self.deletion_count += 1
                self.deletion_byte_count += old[1]
        else:
            old_size = self._delete_in_memory(key)
            self.deletion_count += 1
            self.deletion_byte_count += old_size

    def _delete_in_memory(self, key: int) -> int:
        old = self._m.get(key)
        if old is None or old[1] == t.TOMBSTONE_FILE_SIZE:
            return 0
        self._m[key] = (old[0], t.TOMBSTONE_FILE_SIZE)
        return old[1]

    def _append_index(self, key: int, offset: int, size: int) -> None:
        if self._index_file is None:
            self._index_file = open(self._index_path, "ab")
        self._index_file.write(idx_codec.pack_entry(key, offset, size))
        self._index_file.flush()

    # --- NeedleMapper surface (needle_map.go:22-33) ---
    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        self._m[key] = (offset, size)
        # logPut metric accounting
        self.max_file_key = max(self.max_file_key, key)
        if old is not None and old[1] != t.TOMBSTONE_FILE_SIZE:
            self.deletion_count += 1
            self.deletion_byte_count += old[1]
        self.file_count += 1
        self.file_byte_count += size
        self._append_index(key, offset, size)

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._m.get(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> int:
        """Tombstone `key`; `offset` is the tombstone record's position
        in the .dat (recorded in the .idx entry). Returns freed bytes."""
        freed = self._delete_in_memory(key)
        self.deletion_count += 1
        self.deletion_byte_count += freed
        self._append_index(key, offset, t.TOMBSTONE_FILE_SIZE)
        return freed

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._m):
            offset, size = self._m[key]
            fn(NeedleValue(key, offset, size))

    def items(self) -> Iterator[NeedleValue]:
        for key, (offset, size) in self._m.items():
            yield NeedleValue(key, offset, size)

    def __len__(self) -> int:
        return len(self._m)

    # --- metrics surface ---
    def content_size(self) -> int:
        return self.file_byte_count

    def deleted_size(self) -> int:
        return self.deletion_byte_count

    def index_file_size(self) -> int:
        try:
            return os.path.getsize(self._index_path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._index_file is not None:
            self._index_file.close()
            self._index_file = None

    def destroy(self) -> None:
        self.close()
        if os.path.exists(self._index_path):
            os.remove(self._index_path)


class SortedNeedleMap:
    """Read-only binary-searchable snapshot of a sorted index file
    (.ecx or sorted .idx) held as numpy arrays — the vectorized
    equivalent of needle_map_sorted_file.go / ec_volume.go:199
    SearchNeedleFromSortedIndex."""

    def __init__(self, keys: np.ndarray, offsets: np.ndarray, sizes: np.ndarray):
        self.keys = keys
        self.offsets = offsets
        self.sizes = sizes

    @classmethod
    def load(cls, path: str) -> "SortedNeedleMap":
        with open(path, "rb") as f:
            data = f.read()
        keys, offsets, sizes = idx_codec.entries_as_arrays(data)
        return cls(keys, offsets, sizes)

    def search(self, key: int) -> Optional[NeedleValue]:
        i = int(np.searchsorted(self.keys, np.uint64(key)))
        if i >= len(self.keys) or int(self.keys[i]) != key:
            return None
        return NeedleValue(key, int(self.offsets[i]), int(self.sizes[i]))

    def entry_index(self, key: int) -> int:
        """Index of `key`'s 16-byte entry in the backing file, or -1 —
        used to tombstone entries in place (MarkNeedleDeleted)."""
        i = int(np.searchsorted(self.keys, np.uint64(key)))
        if i >= len(self.keys) or int(self.keys[i]) != key:
            return -1
        return i

    def __len__(self) -> int:
        return len(self.keys)


class DbNeedleMap:
    """Persistent needle map on sqlite (the LevelDB variant's role,
    reference needle_map_leveldb.go:24): the key→(offset,size) table
    lives on disk, so volume load does not hold every entry in RAM and
    does not replay the whole .idx — only the tail written since the
    last checkpoint (watermark = replayed .idx byte count, the role of
    leveldb's recovery from the ldb dir).

    The .idx file stays the append-only source of truth (EC encode,
    compaction, and golden-file compatibility all read it); the db is
    a resumable index over it.
    """

    def __init__(self, index_path: str, db_path: str | None = None):
        import sqlite3

        self._index_path = index_path
        self._db_path = db_path or index_path + ".sdb"
        self._db = sqlite3.connect(self._db_path, check_same_thread=False)
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS needles"
            " (key INTEGER PRIMARY KEY, offset INTEGER, size INTEGER)"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v INTEGER)"
        )
        self._lock = threading.Lock()
        self._index_file = None
        self._ops_since_commit = 0
        self.file_count = 0
        self.file_byte_count = 0
        self.deletion_count = 0
        self.deletion_byte_count = 0
        self.max_file_key = 0
        self._load_metrics()

    # --- lifecycle ---
    # commit cadence: per-write durability is pointless because crash
    # recovery rebuilds from the .idx anyway (the clean flag below);
    # batching commits keeps the db path near memory-map write speed
    _COMMIT_EVERY = 512

    @classmethod
    def load(cls, index_path: str, db_path: str | None = None) -> "DbNeedleMap":
        nm = cls(index_path, db_path)
        watermark = nm._meta_get("idx_bytes")
        clean = nm._meta_get("clean")
        idx_size = os.path.getsize(index_path) if os.path.exists(index_path) else 0
        if idx_size < watermark or not clean:
            # the .idx shrank (vacuum commit) or the previous process
            # died without closing: the db may hold writes the metrics/
            # watermark never checkpointed — rebuild from the .idx, the
            # source of truth (the leveldb variant's recovery role)
            nm._db.execute("DELETE FROM needles")
            nm._reset_metrics()
            watermark = 0
        if idx_size > watermark:
            with open(index_path, "rb") as f:
                f.seek(watermark)
                tail = f.read()
            for key, offset, size in idx_codec.iter_entries(tail):
                nm._replay(key, offset, size)
        nm._meta_set("idx_bytes", idx_size)
        nm._save_metrics()
        nm._meta_set("clean", 0)  # until close() checkpoints
        nm._db.commit()
        nm._index_file = open(index_path, "ab")
        return nm

    # --- meta/metrics persistence ---
    def _meta_get(self, k: str) -> int:
        row = self._db.execute("SELECT v FROM meta WHERE k=?", (k,)).fetchone()
        return int(row[0]) if row else 0

    def _meta_set(self, k: str, v: int) -> None:
        self._db.execute(
            "INSERT INTO meta (k, v) VALUES (?, ?)"
            " ON CONFLICT(k) DO UPDATE SET v=excluded.v",
            (k, v),
        )

    def _load_metrics(self) -> None:
        self.file_count = self._meta_get("file_count")
        self.file_byte_count = self._meta_get("file_byte_count")
        self.deletion_count = self._meta_get("deletion_count")
        self.deletion_byte_count = self._meta_get("deletion_byte_count")
        self.max_file_key = self._meta_get("max_file_key")

    def _save_metrics(self) -> None:
        self._meta_set("file_count", self.file_count)
        self._meta_set("file_byte_count", self.file_byte_count)
        self._meta_set("deletion_count", self.deletion_count)
        self._meta_set("deletion_byte_count", self.deletion_byte_count)
        self._meta_set("max_file_key", self.max_file_key)

    def _reset_metrics(self) -> None:
        self.file_count = 0
        self.file_byte_count = 0
        self.deletion_count = 0
        self.deletion_byte_count = 0
        self.max_file_key = 0

    # --- shared replay/accounting (mirrors CompactNeedleMap) ---
    def _db_get(self, key: int):
        row = self._db.execute(
            "SELECT offset, size FROM needles WHERE key=?", (key,)
        ).fetchone()
        return (int(row[0]), int(row[1])) if row else None

    def _db_set(self, key: int, offset: int, size: int) -> None:
        self._db.execute(
            "INSERT INTO needles (key, offset, size) VALUES (?, ?, ?)"
            " ON CONFLICT(key) DO UPDATE SET offset=excluded.offset,"
            " size=excluded.size",
            (key, offset, size),
        )

    def _replay(self, key: int, offset: int, size: int) -> None:
        # same guard as put/delete: replays arrive from the follower
        # refresh path while handler threads run get() concurrently —
        # the counters and the sqlite handle share one protection
        # (weedlint unguarded-write finding, OPERATIONS.md round 9)
        with self._lock:
            self.max_file_key = max(self.max_file_key, key)
            if offset != 0 and size != t.TOMBSTONE_FILE_SIZE:
                self.file_count += 1
                self.file_byte_count += size
                old = self._db_get(key)
                self._db_set(key, offset, size)
                if old is not None and old[0] != 0 and old[1] != t.TOMBSTONE_FILE_SIZE:
                    self.deletion_count += 1
                    self.deletion_byte_count += old[1]
            else:
                freed = self._delete_in_db(key)
                self.deletion_count += 1
                self.deletion_byte_count += freed

    def _delete_in_db(self, key: int) -> int:
        old = self._db_get(key)
        if old is None or old[1] == t.TOMBSTONE_FILE_SIZE:
            return 0
        self._db_set(key, old[0], t.TOMBSTONE_FILE_SIZE)
        return old[1]

    def _append_index(self, key: int, offset: int, size: int) -> None:
        if self._index_file is None:
            self._index_file = open(self._index_path, "ab")
        self._index_file.write(idx_codec.pack_entry(key, offset, size))
        self._index_file.flush()

    def _maybe_commit(self) -> None:
        self._ops_since_commit += 1
        if self._ops_since_commit >= self._COMMIT_EVERY:
            self._db.commit()
            self._ops_since_commit = 0

    # --- NeedleMapper surface ---
    def put(self, key: int, offset: int, size: int) -> None:
        with self._lock:
            old = self._db_get(key)
            self._db_set(key, offset, size)
            self.max_file_key = max(self.max_file_key, key)
            if old is not None and old[1] != t.TOMBSTONE_FILE_SIZE:
                self.deletion_count += 1
                self.deletion_byte_count += old[1]
            self.file_count += 1
            self.file_byte_count += size
            self._append_index(key, offset, size)
            self._maybe_commit()

    def get(self, key: int) -> Optional[NeedleValue]:
        with self._lock:
            v = self._db_get(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> int:
        with self._lock:
            freed = self._delete_in_db(key)
            self.deletion_count += 1
            self.deletion_byte_count += freed
            self._append_index(key, offset, t.TOMBSTONE_FILE_SIZE)
            self._maybe_commit()
            return freed

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        with self._lock:
            rows = self._db.execute(
                "SELECT key, offset, size FROM needles ORDER BY key"
            ).fetchall()
        for key, offset, size in rows:
            fn(NeedleValue(int(key), int(offset), int(size)))

    def items(self) -> Iterator[NeedleValue]:
        with self._lock:
            rows = self._db.execute(
                "SELECT key, offset, size FROM needles"
            ).fetchall()
        for key, offset, size in rows:
            yield NeedleValue(int(key), int(offset), int(size))

    def __len__(self) -> int:
        with self._lock:
            (n,) = self._db.execute("SELECT COUNT(*) FROM needles").fetchone()
        return int(n)

    # --- metrics surface ---
    def content_size(self) -> int:
        return self.file_byte_count

    def deleted_size(self) -> int:
        return self.deletion_byte_count

    def index_file_size(self) -> int:
        try:
            return os.path.getsize(self._index_path)
        except OSError:
            return 0

    def close(self) -> None:
        # under the map lock: close races a concurrent put/_replay from
        # a handler or follower-refresh thread during volume teardown,
        # and a half-torn _index_file/_db pair here means the checkpoint
        # below records a watermark for writes that never committed
        # (weedlint unguarded-write finding, OPERATIONS.md round 9)
        with self._lock:
            if self._index_file is not None:
                self._index_file.close()
                self._index_file = None
            # checkpoint: metrics + watermark + clean flag in one commit;
            # a crash before this point triggers a full rebuild on load
            try:
                self._save_metrics()
                self._meta_set(
                    "idx_bytes",
                    os.path.getsize(self._index_path)
                    if os.path.exists(self._index_path)
                    else 0,
                )
                self._meta_set("clean", 1)
                self._db.commit()
                self._db.close()
            except Exception:  # noqa: BLE001 - already closed
                pass

    def destroy(self) -> None:
        self.close()
        for p in (self._index_path, self._db_path):
            if os.path.exists(p):
                os.remove(p)
