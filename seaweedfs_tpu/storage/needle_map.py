"""Per-volume needle index: id → (offset units, size), with metrics.

Behavioral match of reference weed/storage/needle_map.go +
needle_map_memory.go: every Put/Delete is appended to the .idx file
(the map is the .idx replayed), metrics track live/deleted counts and
bytes, deletes keep a tombstone entry. The in-memory representation
here is a plain dict — the reference's CompactMap is a Go-specific
memory optimization (16B/entry arrays); the observable semantics
(last-wins replay, tombstones, metrics, ascending visit) are what the
rest of the system depends on.

A numpy-backed sorted snapshot (SortedNeedleMap) covers the
sorted-file/.ecx binary-search use cases (needle_map_sorted_file.go).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, Iterator, Optional

import numpy as np

from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t


@dataclass(frozen=True)
class NeedleValue:
    key: int
    offset: int  # units of 8 bytes (types.py)
    size: int

    @property
    def actual_offset(self) -> int:
        return t.units_to_offset(self.offset)


class CompactNeedleMap:
    """In-memory map mirrored to an append-only .idx file."""

    def __init__(self, index_path: str):
        self._index_path = index_path
        self._m: dict[int, tuple[int, int]] = {}  # key -> (offset, size)
        self._index_file = None
        # mapMetric (needle_map_metric.go)
        self.file_count = 0
        self.file_byte_count = 0
        self.deletion_count = 0
        self.deletion_byte_count = 0
        self.max_file_key = 0

    # --- lifecycle ---
    @classmethod
    def load(cls, index_path: str) -> "CompactNeedleMap":
        """Replay an existing .idx (doLoading, needle_map_memory.go:30)."""
        nm = cls(index_path)
        if os.path.exists(index_path):
            with open(index_path, "rb") as f:
                data = f.read()
            for key, offset, size in idx_codec.iter_entries(data):
                nm._replay(key, offset, size)
        nm._index_file = open(index_path, "ab")
        return nm

    def _replay(self, key: int, offset: int, size: int) -> None:
        self.max_file_key = max(self.max_file_key, key)
        if offset != 0 and size != t.TOMBSTONE_FILE_SIZE:
            self.file_count += 1
            self.file_byte_count += size
            old = self._m.get(key)
            self._m[key] = (offset, size)
            if old is not None and old[0] != 0 and old[1] != t.TOMBSTONE_FILE_SIZE:
                self.deletion_count += 1
                self.deletion_byte_count += old[1]
        else:
            old_size = self._delete_in_memory(key)
            self.deletion_count += 1
            self.deletion_byte_count += old_size

    def _delete_in_memory(self, key: int) -> int:
        old = self._m.get(key)
        if old is None or old[1] == t.TOMBSTONE_FILE_SIZE:
            return 0
        self._m[key] = (old[0], t.TOMBSTONE_FILE_SIZE)
        return old[1]

    def _append_index(self, key: int, offset: int, size: int) -> None:
        if self._index_file is None:
            self._index_file = open(self._index_path, "ab")
        self._index_file.write(idx_codec.pack_entry(key, offset, size))
        self._index_file.flush()

    # --- NeedleMapper surface (needle_map.go:22-33) ---
    def put(self, key: int, offset: int, size: int) -> None:
        old = self._m.get(key)
        self._m[key] = (offset, size)
        # logPut metric accounting
        self.max_file_key = max(self.max_file_key, key)
        if old is not None and old[1] != t.TOMBSTONE_FILE_SIZE:
            self.deletion_count += 1
            self.deletion_byte_count += old[1]
        self.file_count += 1
        self.file_byte_count += size
        self._append_index(key, offset, size)

    def get(self, key: int) -> Optional[NeedleValue]:
        v = self._m.get(key)
        if v is None:
            return None
        return NeedleValue(key, v[0], v[1])

    def delete(self, key: int, offset: int) -> int:
        """Tombstone `key`; `offset` is the tombstone record's position
        in the .dat (recorded in the .idx entry). Returns freed bytes."""
        freed = self._delete_in_memory(key)
        self.deletion_count += 1
        self.deletion_byte_count += freed
        self._append_index(key, offset, t.TOMBSTONE_FILE_SIZE)
        return freed

    def ascending_visit(self, fn: Callable[[NeedleValue], None]) -> None:
        for key in sorted(self._m):
            offset, size = self._m[key]
            fn(NeedleValue(key, offset, size))

    def items(self) -> Iterator[NeedleValue]:
        for key, (offset, size) in self._m.items():
            yield NeedleValue(key, offset, size)

    def __len__(self) -> int:
        return len(self._m)

    # --- metrics surface ---
    def content_size(self) -> int:
        return self.file_byte_count

    def deleted_size(self) -> int:
        return self.deletion_byte_count

    def index_file_size(self) -> int:
        try:
            return os.path.getsize(self._index_path)
        except OSError:
            return 0

    def close(self) -> None:
        if self._index_file is not None:
            self._index_file.close()
            self._index_file = None

    def destroy(self) -> None:
        self.close()
        if os.path.exists(self._index_path):
            os.remove(self._index_path)


class SortedNeedleMap:
    """Read-only binary-searchable snapshot of a sorted index file
    (.ecx or sorted .idx) held as numpy arrays — the vectorized
    equivalent of needle_map_sorted_file.go / ec_volume.go:199
    SearchNeedleFromSortedIndex."""

    def __init__(self, keys: np.ndarray, offsets: np.ndarray, sizes: np.ndarray):
        self.keys = keys
        self.offsets = offsets
        self.sizes = sizes

    @classmethod
    def load(cls, path: str) -> "SortedNeedleMap":
        with open(path, "rb") as f:
            data = f.read()
        keys, offsets, sizes = idx_codec.entries_as_arrays(data)
        return cls(keys, offsets, sizes)

    def search(self, key: int) -> Optional[NeedleValue]:
        i = int(np.searchsorted(self.keys, np.uint64(key)))
        if i >= len(self.keys) or int(self.keys[i]) != key:
            return None
        return NeedleValue(key, int(self.offsets[i]), int(self.sizes[i]))

    def entry_index(self, key: int) -> int:
        """Index of `key`'s 16-byte entry in the backing file, or -1 —
        used to tombstone entries in place (MarkNeedleDeleted)."""
        i = int(np.searchsorted(self.keys, np.uint64(key)))
        if i >= len(self.keys) or int(self.keys[i]) != key:
            return -1
        return i

    def __len__(self) -> int:
        return len(self.keys)
