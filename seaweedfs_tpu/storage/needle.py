"""Needle: one stored blob, and its bit-exact wire format.

Matches reference weed/storage/needle/needle.go:26-46 and
needle_read_write.go:31-120 (write) / 160-280 (read):

  header (16B): cookie u32 | id u64 | size u32          (big-endian)
  body v2/v3 when data present (size counts all of it):
      data_size u32 | data | flags u8
      [name_size u8 | name]        if FlagHasName
      [mime_size u8 | mime]        if FlagHasMime
      [last_modified 5B]           if FlagHasLastModifiedDate
      [ttl 2B]                     if FlagHasTtl
      [pairs_size u16 | pairs]     if FlagHasPairs
  trailer: checksum u32 (masked CRC32-C of data)
      [append_at_ns u64]           v3 only
      padding to 8B alignment — NOTE the reference quirk
      (needle_read_write.go:287-293): padding = 8 - (total % 8),
      i.e. ALWAYS 1..8 bytes, a full 8 when already aligned.

Version1 bodies are raw data + checksum (+padding).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.super_block import VERSION1, VERSION2, VERSION3
from seaweedfs_tpu.storage.ttl import TTL
from seaweedfs_tpu.util import bytesutil
from seaweedfs_tpu.util.crc import crc32c, masked_value

try:  # one-call C record serializer (native/needle_ext.c); None = Python path
    from seaweedfs_tpu.native import needle_ext as _needle_ext
except ImportError:  # pragma: no cover - no compiler on host
    _needle_ext = None

FLAG_GZIP = 0x01
FLAG_HAS_NAME = 0x02
FLAG_HAS_MIME = 0x04
FLAG_HAS_LAST_MODIFIED_DATE = 0x08
FLAG_HAS_TTL = 0x10
FLAG_HAS_PAIRS = 0x20
FLAG_IS_CHUNK_MANIFEST = 0x80

LAST_MODIFIED_BYTES_LENGTH = 5
TTL_BYTES_LENGTH = 2


def padding_length(needle_size: int, version: int) -> int:
    """Reference needle_read_write.go:287 — never returns 0, returns 8
    when the unpadded record is already 8-byte aligned."""
    if version == VERSION3:
        unpadded = (
            t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE + t.TIMESTAMP_SIZE
        )
    else:
        unpadded = t.NEEDLE_HEADER_SIZE + needle_size + t.NEEDLE_CHECKSUM_SIZE
    return t.NEEDLE_PADDING_SIZE - (unpadded % t.NEEDLE_PADDING_SIZE)


def needle_body_length(needle_size: int, version: int) -> int:
    if version == VERSION3:
        return (
            needle_size
            + t.NEEDLE_CHECKSUM_SIZE
            + t.TIMESTAMP_SIZE
            + padding_length(needle_size, version)
        )
    return needle_size + t.NEEDLE_CHECKSUM_SIZE + padding_length(needle_size, version)


def get_actual_size(size: int, version: int) -> int:
    """Total on-disk record length for a needle of stored `size`."""
    return t.NEEDLE_HEADER_SIZE + needle_body_length(size, version)


class CorruptNeedle(ValueError):
    pass


class CookieMismatch(ValueError):
    pass


@dataclass
class Needle:
    cookie: int = 0
    id: int = 0
    size: int = 0  # stored size field (sum of body pieces), set on encode

    data: bytes = b""
    flags: int = 0
    name: bytes = b""
    mime: bytes = b""
    pairs: bytes = b""  # JSON-encoded extra name/value pairs
    last_modified: int = 0  # unix seconds, 5 bytes stored
    ttl: TTL | None = None

    checksum: int = 0  # RAW CRC32-C of data (the reference's n.Checksum;
    # the on-disk trailer stores masked_value(checksum), but Etag and
    # gRPC surfaces expose the raw value — crc.go Etag())
    append_at_ns: int = 0  # v3 only

    # --- flag helpers (needle.go Set*/Has*) ---
    def has_name(self) -> bool:
        return bool(self.flags & FLAG_HAS_NAME)

    def has_mime(self) -> bool:
        return bool(self.flags & FLAG_HAS_MIME)

    def has_last_modified_date(self) -> bool:
        return bool(self.flags & FLAG_HAS_LAST_MODIFIED_DATE)

    def has_ttl(self) -> bool:
        return bool(self.flags & FLAG_HAS_TTL)

    def has_pairs(self) -> bool:
        return bool(self.flags & FLAG_HAS_PAIRS)

    def is_gzipped(self) -> bool:
        return bool(self.flags & FLAG_GZIP)

    def is_chunked_manifest(self) -> bool:
        return bool(self.flags & FLAG_IS_CHUNK_MANIFEST)

    def set_has_name(self) -> None:
        self.flags |= FLAG_HAS_NAME

    def set_has_mime(self) -> None:
        self.flags |= FLAG_HAS_MIME

    def set_has_last_modified_date(self) -> None:
        self.flags |= FLAG_HAS_LAST_MODIFIED_DATE

    def set_has_ttl(self) -> None:
        self.flags |= FLAG_HAS_TTL

    def set_has_pairs(self) -> None:
        self.flags |= FLAG_HAS_PAIRS

    def set_gzipped(self) -> None:
        self.flags |= FLAG_GZIP

    def set_is_chunk_manifest(self) -> None:
        self.flags |= FLAG_IS_CHUNK_MANIFEST

    # --- encode ---
    def _body_size_v2(self) -> int:
        if not self.data:
            return 0
        size = 4 + len(self.data) + 1
        if self.has_name():
            size += 1 + min(len(self.name), 255)
        if self.has_mime():
            size += 1 + len(self.mime)
        if self.has_last_modified_date():
            size += LAST_MODIFIED_BYTES_LENGTH
        if self.has_ttl():
            size += TTL_BYTES_LENGTH
        if self.has_pairs():
            size += 2 + len(self.pairs)
        return size

    def to_bytes(self, version: int = VERSION3) -> bytes:
        """Serialize the full on-disk record (header..padding).

        Mirrors prepareWriteBuffer (needle_read_write.go:31) including its
        edge cases: empty data ⇒ size 0 and an empty body; name longer
        than 255 is truncated via NameSize capping.
        """
        self.checksum = crc32c(self.data)
        stored_checksum = masked_value(self.checksum)
        out = bytearray()
        if version == VERSION1:
            self.size = len(self.data)
            out += bytesutil.put_u32(self.cookie)
            out += bytesutil.put_u64(self.id)
            out += bytesutil.put_u32(self.size)
            out += self.data
            out += bytesutil.put_u32(stored_checksum)
            out += bytes(padding_length(self.size, version))
            return bytes(out)
        if version not in (VERSION2, VERSION3):
            raise ValueError(f"unsupported needle version {version}")

        self.size = self._body_size_v2()
        out += bytesutil.put_u32(self.cookie)
        out += bytesutil.put_u64(self.id)
        out += bytesutil.put_u32(self.size)
        if self.data:
            out += bytesutil.put_u32(len(self.data))
            out += self.data
            out.append(self.flags & 0xFF)
            if self.has_name():
                name = self.name[:255]
                out.append(len(name))
                out += name
            if self.has_mime():
                if len(self.mime) > 255:
                    raise ValueError("mime longer than 255 bytes")
                out.append(len(self.mime))
                out += self.mime
            if self.has_last_modified_date():
                out += bytesutil.put_u64(self.last_modified)[
                    8 - LAST_MODIFIED_BYTES_LENGTH :
                ]
            if self.has_ttl():
                ttl = self.ttl or TTL()
                out += ttl.to_bytes()
            if self.has_pairs():
                if len(self.pairs) >= 65536:
                    raise ValueError("pairs longer than 64KB")
                out += bytesutil.put_u16(len(self.pairs))
                out += self.pairs
        out += bytesutil.put_u32(stored_checksum)
        if version == VERSION3:
            out += bytesutil.put_u64(self.append_at_ns)
        out += bytes(padding_length(self.size, version))
        return bytes(out)

    def encode_record(self, version: int = VERSION3) -> bytes:
        """`to_bytes` fast path: the whole record (header..padding) in
        ONE native call (native/needle_ext.c — the prepareWriteBuffer
        single-pass shape, needle_read_write.go:31). Byte-identical to
        to_bytes; falls back to it when the shim didn't build."""
        if _needle_ext is None:
            return self.to_bytes(version)
        blob, self.size, self.checksum = _needle_ext.encode(
            self.cookie,
            self.id,
            self.data,
            self.flags,
            self.name,
            self.mime,
            self.last_modified,
            (self.ttl or TTL()).to_bytes() if self.has_ttl() else None,
            self.pairs,
            version,
            self.append_at_ns,
        )
        return blob

    # --- decode ---
    @staticmethod
    def parse_header(blob: bytes) -> tuple[int, int, int]:
        """(cookie, id, size) from the 16-byte header."""
        if len(blob) < t.NEEDLE_HEADER_SIZE:
            raise CorruptNeedle(f"needle header truncated: {len(blob)} bytes")
        return (
            bytesutil.get_u32(blob, 0),
            bytesutil.get_u64(blob, t.COOKIE_SIZE),
            bytesutil.get_u32(blob, t.COOKIE_SIZE + t.NEEDLE_ID_SIZE),
        )

    @staticmethod
    def from_bytes(blob: bytes, version: int = VERSION3, size: int | None = None) -> "Needle":
        """Parse a full on-disk record (ReadBytes, needle_read_write.go:163).

        `size` — expected stored size from the index; mismatch raises.
        Verifies the data CRC. Fast path: one native parse+verify call
        (native/needle_ext.c decode); any native rejection re-parses in
        Python so error messages and edge semantics stay identical.
        """
        if _needle_ext is not None:
            try:
                (
                    cookie,
                    nid,
                    nsize,
                    data,
                    flags,
                    name,
                    mime,
                    last_modified,
                    ttl2,
                    pairs,
                    append_at_ns,
                    crc,
                ) = _needle_ext.decode(blob, version, -1 if size is None else size)
            except ValueError:
                pass  # cold path: Python parse below raises the exact error
            else:
                n = Needle()
                n.cookie, n.id, n.size = cookie, nid, nsize
                n.data, n.flags, n.name, n.mime = data, flags, name, mime
                n.last_modified = last_modified
                if ttl2 is not None:
                    n.ttl = TTL.from_bytes(ttl2)
                n.pairs = pairs
                n.append_at_ns = append_at_ns
                if nsize > 0:
                    n.checksum = crc
                return n
        n = Needle()
        n.cookie, n.id, n.size = Needle.parse_header(blob)
        if size is not None and n.size != size:
            raise CorruptNeedle(
                f"entry not found: found id {n.id} size {n.size}, expected {size}"
            )
        h = t.NEEDLE_HEADER_SIZE
        if len(blob) < get_actual_size(n.size, version) - padding_length(n.size, version):
            raise CorruptNeedle(
                f"needle record truncated: {len(blob)} bytes for size {n.size}"
            )
        if version == VERSION1:
            n.data = bytes(blob[h : h + n.size])
        elif version in (VERSION2, VERSION3):
            n._parse_body_v2(blob[h : h + n.size])
        else:
            raise ValueError(f"unsupported needle version {version}")
        if n.size > 0:
            stored = bytesutil.get_u32(blob, h + n.size)
            fresh = crc32c(n.data)
            if stored != masked_value(fresh):
                raise CorruptNeedle("CRC error! Data On Disk Corrupted")
            n.checksum = fresh
        if version == VERSION3:
            ts_off = h + n.size + t.NEEDLE_CHECKSUM_SIZE
            n.append_at_ns = bytesutil.get_u64(blob, ts_off)
        return n

    def _parse_body_v2(self, body: bytes) -> None:
        """readNeedleDataVersion2 (needle_read_write.go:210-280)."""
        idx, end = 0, len(body)
        if idx < end:
            data_size = bytesutil.get_u32(body, idx)
            idx += 4
            if data_size + idx > end:
                raise CorruptNeedle("data_size out of range")
            self.data = bytes(body[idx : idx + data_size])
            idx += data_size
            if idx >= end:
                raise CorruptNeedle("flags byte out of range")
            self.flags = body[idx]
            idx += 1
        if idx < end and self.has_name():
            name_size = body[idx]
            idx += 1
            if name_size + idx > end:
                raise CorruptNeedle("name out of range")
            self.name = bytes(body[idx : idx + name_size])
            idx += name_size
        if idx < end and self.has_mime():
            mime_size = body[idx]
            idx += 1
            if mime_size + idx > end:
                raise CorruptNeedle("mime out of range")
            self.mime = bytes(body[idx : idx + mime_size])
            idx += mime_size
        if idx < end and self.has_last_modified_date():
            if LAST_MODIFIED_BYTES_LENGTH + idx > end:
                raise CorruptNeedle("last_modified out of range")
            self.last_modified = bytesutil.get_uint(
                body[idx : idx + LAST_MODIFIED_BYTES_LENGTH]
            )
            idx += LAST_MODIFIED_BYTES_LENGTH
        if idx < end and self.has_ttl():
            if TTL_BYTES_LENGTH + idx > end:
                raise CorruptNeedle("ttl out of range")
            self.ttl = TTL.from_bytes(body[idx : idx + TTL_BYTES_LENGTH])
            idx += TTL_BYTES_LENGTH
        if idx < end and self.has_pairs():
            if 2 + idx > end:
                raise CorruptNeedle("pairs_size out of range")
            pairs_size = bytesutil.get_u16(body, idx)
            idx += 2
            if pairs_size + idx > end:
                raise CorruptNeedle("pairs out of range")
            self.pairs = bytes(body[idx : idx + pairs_size])
            idx += pairs_size

    def disk_size(self, version: int = VERSION3) -> int:
        return get_actual_size(self.size, version)

    def etag(self) -> str:
        return bytesutil.put_u32(self.checksum).hex()
