"""Core on-disk ABI constants and the Offset codec.

Bit-compatible with reference weed/storage/types/:
  needle_types.go:24-32  — sizes, TombstoneFileSize
  offset_4bytes.go       — 4-byte offset in units of 8-byte padding
                           (⇒ 32 GB max volume)
  offset_5bytes.go       — 5-byte variant (⇒ 8 TB); the reference picks
                           one at *build* time via a build tag
                           (Makefile `build_large`); here it is a
                           process-wide runtime config:
                           set_offset_size(5), or the
                           WEED_VOLUME_OFFSET_SIZE env var at import.
  needle_id_type.go      — 8-byte big-endian needle ids
"""

from __future__ import annotations

COOKIE_SIZE = 4
NEEDLE_ID_SIZE = 8
SIZE_SIZE = 4  # uint32
NEEDLE_HEADER_SIZE = COOKIE_SIZE + NEEDLE_ID_SIZE + SIZE_SIZE  # 16
NEEDLE_CHECKSUM_SIZE = 4
TIMESTAMP_SIZE = 8
NEEDLE_PADDING_SIZE = 8
TOMBSTONE_FILE_SIZE = 0xFFFFFFFF  # size==MaxUint32 marks a deleted entry

OFFSET_SIZE = 4  # default build: 4-byte offsets (see set_offset_size)
NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE  # 16

# 4-byte offset counts NEEDLE_PADDING_SIZE units: 2^32 * 8 = 32 GB
MAX_POSSIBLE_VOLUME_SIZE = (1 << (8 * OFFSET_SIZE)) * NEEDLE_PADDING_SIZE


def set_offset_size(n: int) -> None:
    """Switch the process to 4- or 5-byte stored offsets (the 5-byte
    build supports 8 TB volumes; .idx entries grow to 17 bytes). Must
    be called before any volume/index is opened — mixing sizes in one
    process corrupts indexes, exactly like mixing the reference's
    normal and `build_large` binaries on one dataset."""
    global OFFSET_SIZE, NEEDLE_MAP_ENTRY_SIZE, MAX_POSSIBLE_VOLUME_SIZE
    if n not in (4, 5):
        raise ValueError("offset size must be 4 or 5")
    OFFSET_SIZE = n
    NEEDLE_MAP_ENTRY_SIZE = NEEDLE_ID_SIZE + OFFSET_SIZE + SIZE_SIZE
    MAX_POSSIBLE_VOLUME_SIZE = (1 << (8 * OFFSET_SIZE)) * NEEDLE_PADDING_SIZE
    # idx entry layout follows the type constants
    from seaweedfs_tpu.storage import idx as _idx

    _idx.ENTRY_SIZE = NEEDLE_MAP_ENTRY_SIZE


import os as _os  # noqa: E402

if _os.environ.get("WEED_VOLUME_OFFSET_SIZE") == "5":
    set_offset_size(5)

NEEDLE_ID_EMPTY = 0


def offset_to_units(actual_offset: int) -> int:
    """Byte offset → stored offset units (offset_4bytes.go ToOffset)."""
    return actual_offset // NEEDLE_PADDING_SIZE


def units_to_offset(units: int) -> int:
    """Stored offset units → byte offset (ToAcutalOffset)."""
    return units * NEEDLE_PADDING_SIZE


def offset_to_bytes(units: int, offset_size: int | None = None) -> bytes:
    """Offset units → big-endian bytes (OffsetToBytes)."""
    return units.to_bytes(offset_size or OFFSET_SIZE, "big")


def bytes_to_offset(b: bytes) -> int:
    """Big-endian offset bytes → offset units (BytesToOffset)."""
    return int.from_bytes(b, "big")


def needle_id_to_bytes(needle_id: int) -> bytes:
    return (needle_id & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "big")


def bytes_to_needle_id(b: bytes) -> int:
    return int.from_bytes(b[:8], "big")


import re as _re

_HEX_RE = _re.compile(r"[0-9a-fA-F]+\Z")


def _parse_hex_uint(s: str, bits: int, what: str) -> int:
    """Strict hex parse matching Go's strconv.ParseUint(s, 16, bits):
    no sign, no 0x prefix, no underscores, no whitespace. (Regex, not
    a per-char genexpr: this runs twice per fid parse on the data
    plane's hot path.)"""
    if not _HEX_RE.match(s):
        raise ValueError(f"{what} {s!r} format error")
    v = int(s, 16)
    if v >= 1 << bits:
        raise ValueError(f"{what} {s!r} overflows uint{bits}")
    return v


def parse_needle_id(id_string: str) -> int:
    """Hex needle-id string → int (needle_id_type.go ParseNeedleId)."""
    return _parse_hex_uint(id_string, 64, "needle id")


def parse_cookie(cookie_string: str) -> int:
    return _parse_hex_uint(cookie_string, 32, "cookie")
