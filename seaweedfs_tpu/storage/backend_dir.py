"""Local-directory remote tier: a fake object store on a plain
directory, for tests/chaos/bench runs that need a real BackendStorage
with zero network. Keys are flat file names under the configured dir;
ranged reads are preads — the semantics (opaque keys, upload/download/
delete, ranged read_at) mirror backend_s3 exactly, so anything proven
against `dir.default` holds structurally for `s3.default`."""

from __future__ import annotations

import os
import uuid

from seaweedfs_tpu.storage import backend as b
from seaweedfs_tpu.util import durable

_COPY_CHUNK = 4 << 20


class DirStorageFile(b.BackendStorageFile):
    def __init__(self, path: str, file_size: int):
        self.path = path
        self.file_size = file_size

    def read_at(self, length: int, offset: int) -> bytes:
        with open(self.path, "rb") as f:
            return os.pread(f.fileno(), length, offset)

    def write_at(self, data: bytes, offset: int) -> int:
        raise IOError("dir tier volumes are sealed (read-only)")

    def truncate(self, size: int) -> None:
        raise IOError("dir tier volumes are sealed (read-only)")

    def close(self) -> None:
        pass

    def get_stat(self) -> tuple[int, float]:
        st = os.stat(self.path)
        return st.st_size, st.st_mtime

    def name(self) -> str:
        return self.path


class DirBackendStorage(b.BackendStorage):
    storage_type = "dir"

    def __init__(self, instance_id: str, props: dict):
        self.id = instance_id
        self.directory = props["dir"]
        self._props = dict(props)
        os.makedirs(self.directory, exist_ok=True)

    def to_properties(self) -> dict:
        return {k: str(v) for k, v in self._props.items()}

    def _path(self, key: str) -> str:
        return os.path.join(self.directory, key)

    def new_storage_file(self, key: str, file_size: int) -> DirStorageFile:
        return DirStorageFile(self._path(key), file_size)

    def copy_file(self, local_path: str, attributes: dict, progress=None):
        key = f"{uuid.uuid4().hex}{attributes.get('ext', '.dat')}"
        size = os.path.getsize(local_path)
        tmp = self._path(key) + ".part"
        done = 0
        with open(local_path, "rb") as src, open(tmp, "wb") as dst:
            while True:
                chunk = src.read(_COPY_CHUNK)
                if not chunk:
                    break
                dst.write(chunk)
                done += len(chunk)
                if progress is not None:
                    progress(done, done * 100.0 / max(1, size))
            dst.flush()
            os.fsync(dst.fileno())
        # publish: a crash mid-upload leaves only a .part, never a
        # half-written key a later download would trust
        os.replace(tmp, self._path(key))
        durable.fsync_dir(self.directory)
        return key, size

    def download_file(self, local_path: str, key: str, progress=None) -> int:
        size = os.path.getsize(self._path(key))
        done = 0
        with open(self._path(key), "rb") as src, open(local_path, "wb") as dst:
            while True:
                chunk = src.read(_COPY_CHUNK)
                if not chunk:
                    break
                dst.write(chunk)
                done += len(chunk)
                if progress is not None:
                    progress(done, done * 100.0 / max(1, size))
        return size

    def delete_file(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass


def _factory(instance_id: str, props: dict) -> DirBackendStorage:
    return DirBackendStorage(instance_id, props)


b.register_backend_factory("dir", _factory)
