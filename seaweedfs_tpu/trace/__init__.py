"""Distributed request tracing plane (docs/TRACING.md).

Every serving entry point mints or inherits a request (trace) ID, each
hop records a span into a lock-cheap per-process ring buffer, and the
`X-Weed-Trace` header carries `trace_id:parent_span_id:plane` across
every internal HTTP and gRPC hop — replica fan-out, `x-shard-hop`
worker forwarding, EC remote shard reads, scrub/repair rebuild traffic.
"""

from seaweedfs_tpu.trace.tracer import (
    TRACE_HEADER,
    Span,
    add_stages,
    annotate,
    connection_tracer,
    current,
    current_trace_id,
    debug_payload,
    enabled,
    format_header,
    grpc_metadata,
    header_from_grpc_context,
    header_value,
    inflight_payload,
    inject,
    inject_request,
    loop_tracer,
    parse_header,
    reset,
    sample_every,
    set_enabled,
    set_sample_every,
    set_slow_threshold_ms,
    slow_threshold_ms,
    span,
)

__all__ = [
    "TRACE_HEADER",
    "Span",
    "add_stages",
    "annotate",
    "connection_tracer",
    "current",
    "current_trace_id",
    "debug_payload",
    "enabled",
    "format_header",
    "grpc_metadata",
    "header_from_grpc_context",
    "header_value",
    "inflight_payload",
    "inject",
    "inject_request",
    "loop_tracer",
    "parse_header",
    "reset",
    "sample_every",
    "set_enabled",
    "set_sample_every",
    "set_slow_threshold_ms",
    "slow_threshold_ms",
    "span",
]
