"""Core tracer: spans, context propagation, and the completed-span ring.

Design constraints, in order:

  1. The volume write hot path budgets ~300 us of CPU per request, and
     on the bench host a single request's span lifecycle is dominated
     not by bytecode count but by COLD CACHE LINES — every distinct
     shared object the span path touches (contextvar HAMT nodes,
     metric dicts, lock objects) is evicted between requests and costs
     a miss when touched again. The hot path therefore touches almost
     nothing shared: context rides a per-thread cell (plain list) that
     stays warm on the connection's thread, the completed-span ring
     append is ONE GIL-atomic list store indexed off a C counter (no
     lock), and histogram aggregation is deferred — a background
     drainer (plus drain-on-read for operator endpoints and /metrics
     exposition, via the registry's prerender hook) folds ring entries
     into `weed_span_seconds` off the request path.
  2. `WEED_TRACE=0` (or set_enabled(False)) short-circuits at the one
     `enabled()` check each call site already guards on — a disabled
     tracer adds a module-global read per request and nothing else.
  3. Spans survive same-thread nesting via the cell's previous-span
     chain. Pool threads (EC readers, reconstruction fan-out) do NOT
     inherit the cell — those paths capture the wire context at
     factory time (trace.grpc_metadata()) instead, and cross-thread
     stages attach to the span object directly.

Wire format (`X-Weed-Trace`): `trace_id:parent_span_id:plane`, all
ASCII hex / lowercase tokens. The plane tag (`serve` | `scrub` |
`repair` | `tier`) travels with the trace so a volume server can see that an EC
shard read was rebuild traffic, not a user read — the cross-plane
interference the Facebook warehouse study (PAPERS.md, arXiv:1309.0186)
shows is otherwise invisible.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

from seaweedfs_tpu.stats.metrics import DEFAULT_REGISTRY, SPAN_HISTOGRAM
from seaweedfs_tpu.util import wlog

TRACE_HEADER = "x-weed-trace"  # FastHeaders stores keys lowercased

PLANE_SERVE = "serve"

_ENABLED = os.environ.get("WEED_TRACE", "1") != "0"


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


_RING_SIZE = _pow2(max(64, int(os.environ.get("WEED_TRACE_RING", "2048") or 2048)))
_RING_MASK = _RING_SIZE - 1
_SLOWEST_N = 32
_slow_threshold_ms = float(os.environ.get("WEED_TRACE_SLOW_MS", "0") or 0)
# Head sampling for mini-loop roots WITHOUT an inbound trace header:
# 1 = trace every request (full fidelity, the default); N traces every
# N-th. Requests carrying X-Weed-Trace always trace (internal hops and
# deliberate clients are never sampled away), so a sampled-in trace is
# always complete across its fan-out. Explicit span() calls (scrub,
# repair, EC drivers, bench roots) ignore sampling entirely.
_sample_every = max(1, int(os.environ.get("WEED_TRACE_SAMPLE", "1") or 1))
_sample_counter = itertools.count()

# ID minting: a random base per process XOR a counter — unique across
# restarts and across the cluster's processes without a syscall per
# request. Span ids need the base too: trace.dump merges spans from
# every daemon by span id, and bare counters collide across processes
# (every daemon's first span would be 00000001).
_id_base = int.from_bytes(os.urandom(8), "big")
_span_id_base = int.from_bytes(os.urandom(4), "big")
_trace_counter = itertools.count(1)
_span_counter = itertools.count(1)

# wall = _WALL_BASE + perf_counter(): one clock call per span instead
# of two; diagnostic timestamps tolerate the (NTP-step) drift
_WALL_BASE = time.time() - time.perf_counter()

_node_label = f"pid{os.getpid()}"


def set_node_label(label: str) -> None:
    """Default node tag for spans recorded without an explicit node
    (client-side spans, background planes). Servers pass their own
    host:port per request via span(..., node=...)."""
    global _node_label
    _node_label = label


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Runtime kill switch (bench.py A/B arms toggle this in-process)."""
    global _ENABLED
    _ENABLED = bool(on)


def sample_every() -> int:
    return _sample_every


def set_sample_every(n: int) -> None:
    """`-traceSample N`: head-sample 1-in-N headerless mini-loop roots
    (1 = every request). The overhead knob for hot fleets — see the
    bench `trace` config's sampled arm."""
    global _sample_every
    _sample_every = max(1, int(n))


def slow_threshold_ms() -> float:
    return _slow_threshold_ms


def set_slow_threshold_ms(ms: float) -> None:
    """`-traceSlowMs`: completed local-root spans slower than this are
    written through wlog with their request ID. 0 disables."""
    global _slow_threshold_ms
    _slow_threshold_ms = max(0.0, float(ms))


# --- per-thread context -------------------------------------------------
# One mutable cell per thread holding the innermost open span; open
# parents hang off the span's _prev chain. The cell is registered once
# per thread (for /debug/requests enumeration) and then every span
# entry/exit is two plain list stores on a warm object.

_tls = threading.local()
_cells: dict[int, list] = {}  # thread ident -> cell


def _cell() -> list:
    try:
        return _tls.cell
    except AttributeError:
        c = [None]
        _tls.cell = c
        with _lock:
            if len(_cells) > 1024:
                # prune dead threads' cells (thread-per-connection
                # servers retire threads constantly); amortized over
                # registrations, never on the request path
                alive = {t.ident for t in threading.enumerate()}
                for ident in [i for i in _cells if i not in alive]:
                    del _cells[ident]
            _cells[threading.get_ident()] = c
        return c


class Span:
    """One hop (or stage-bearing operation) of a traced request.

    Also the context manager that records itself: `with span(...)` is
    the only public way to open one, so every started span is
    guaranteed a ring record even when the handler raises.

    IDs are stored raw (ints for locally-minted, strings when
    inherited off the wire) and hex-formatted lazily by the
    `trace_id`/`span_id` properties — the volume leaf hop never reads
    them, so the hot path pays two counter bumps instead of two string
    formats."""

    __slots__ = (
        "_tid", "_sid", "parent_id", "name", "plane", "node",
        "t0", "duration", "status", "nbytes", "stages", "annot",
        "error", "_prev", "_cellref",
    )

    def __init__(
        self,
        name: str,
        tid,
        sid: int,
        parent_id: str,
        plane: str,
        node: str,
        nbytes: int,
        cell: list,
        t0: float = 0.0,
    ):
        self.name = name
        self._tid = tid  # int (local mint, XOR base at format) or str
        self._sid = sid  # int, formatted lazily
        self.parent_id = parent_id
        self.plane = plane
        self.node = node
        self.nbytes = nbytes
        self.t0 = t0 or time.perf_counter()
        self.duration = 0.0
        self.status = 0
        self.stages: dict[str, float] | None = None
        self.annot: dict[str, str] | None = None
        self.error = ""
        self._cellref = cell

    @property
    def trace_id(self) -> str:
        t = self._tid
        if type(t) is int:
            t = self._tid = "%016x" % (_id_base ^ t)
        return t

    @property
    def span_id(self) -> str:
        s = self._sid
        if type(s) is int:
            s = self._sid = "%08x" % (_span_id_base ^ s)
        return s

    # -- context manager -------------------------------------------------
    def __enter__(self) -> "Span":
        c = self._cellref
        self._prev = c[0]
        c[0] = self
        return self

    def __exit__(self, exc_type, exc, _tb) -> bool:
        # the finished-span sink, inlined: one clock read, the cell
        # restore, a C counter bump and a GIL-atomic list store; the
        # root-only extras (slowest table, slow-trace log) are gated on
        # plain float compares so the common case never locks
        self.duration = d = time.perf_counter() - self.t0
        if exc is not None and not self.error:
            self.error = f"{exc_type.__name__}: {exc}"[:200]
        self._cellref[0] = self._prev
        _ring[_ring_next() & _RING_MASK] = self
        if self.parent_id == "":
            if d > _slow_floor:
                _slow_insert(self)
            if _slow_threshold_ms > 0 and d * 1000.0 >= _slow_threshold_ms:
                _slow_log(self)
        if not _drainer_started:
            _start_drainer()
        return False  # never swallow

    # -- enrichment ------------------------------------------------------
    def add_stages(self, stages: dict[str, float]) -> None:
        """Attach stage timings. ADOPTS the dict when none is attached
        yet (callers hand over a per-request dict they never reuse)."""
        if self.stages is None:
            self.stages = stages
        else:
            self.stages.update(stages)

    def annotate(self, key: str, value) -> None:
        if self.annot is None:
            self.annot = {}
        self.annot[key] = str(value)[:200]

    @property
    def start(self) -> float:
        return _WALL_BASE + self.t0

    def to_dict(self) -> dict:
        d = {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "plane": self.plane,
            "node": self.node,
            "start": round(self.start, 6),
            "dur_ms": round(self.duration * 1000.0, 3),
            "status": self.status,
            "bytes": self.nbytes,
        }
        if self.stages:
            d["stages_ms"] = {
                k: round(v * 1000.0, 3) for k, v in self.stages.items()
            }
        if self.annot:
            d["annot"] = self.annot
        if self.error:
            d["error"] = self.error
        return d


# --- completed-span ring ------------------------------------------------
# Preallocated list, power-of-two size. Writers never lock: slot index
# comes off a C counter (GIL-atomic) and the store is one list item
# assignment. _lock guards only the rare/cold paths: slowest-table
# updates, the drain cursor, thread-cell registration, and reset.

_lock = threading.Lock()
_ring: list[Span | None] = [None] * _RING_SIZE
_ring_counter = itertools.count()
_ring_next = _ring_counter.__next__  # bound once; reset() never rebinds
_reset_base = 0  # counter value at the last reset(); recorded = peek - base
_drained = 0  # ring positions (global numbering) already folded into SPAN_HISTOGRAM
# slowest local-root spans, UNSORTED on the hot path (sorted only when
# an operator reads debug_payload); _slow_floor caches min(_slow_durs)
# so the common case — a root span no slower than the current top-32
# floor — is ONE float compare, no lock
_slowest: list[Span] = []
_slow_durs: list[float] = []
_slow_floor = float("-inf")

_DRAIN_INTERVAL_S = 0.25
_drainer_started = False


def _peek() -> int:
    """Current ring-counter value (itertools.count peek — atomic)."""
    return _ring_counter.__reduce__()[1][0]


def _slow_insert(sp: Span) -> None:
    """Admit a root span into the slowest-N table. Reached only when
    its duration beats the cached floor, so the lock is rare."""
    global _slow_floor
    with _lock:
        if len(_slowest) < _SLOWEST_N:
            _slowest.append(sp)
            _slow_durs.append(sp.duration)
            if len(_slowest) == _SLOWEST_N:
                _slow_floor = min(_slow_durs)
        elif sp.duration > _slow_floor:
            i = _slow_durs.index(_slow_floor)
            _slowest[i] = sp
            _slow_durs[i] = sp.duration
            _slow_floor = min(_slow_durs)


def _slow_log(sp: Span) -> None:
    wlog.warning(
        "slow trace %s: %s %.1fms status=%s bytes=%d plane=%s stages=%s",
        sp.trace_id,
        sp.name,
        sp.duration * 1000.0,
        sp.status,
        sp.nbytes,
        sp.plane,
        {k: round(v * 1e3, 2) for k, v in (sp.stages or {}).items()},
    )


def drain() -> None:
    """Fold completed spans recorded since the last drain into the
    span-duration histogram. Runs on the drainer tick, before every
    /metrics exposition (registry prerender hook), and on operator
    reads — never on the request path. Spans overwritten before a
    drain (sustained > ring-size/interval load) are skipped; the exact
    per-request counters don't lose them."""
    global _drained
    put_exemplar = SPAN_HISTOGRAM.put_exemplar
    with _lock:
        cur = _peek()
        lo = max(_drained, cur - _RING_SIZE)
        for i in range(lo, cur):
            sp = _ring[i & _RING_MASK]
            if sp is not None:
                SPAN_HISTOGRAM.observe(sp.duration, sp.name, sp.plane)
                # weedscope exemplars: each bucket remembers the last
                # trace that landed in it — off the request path, here
                # in the drain, where the span is already in hand
                put_exemplar(sp.duration, sp.trace_id, sp.name, sp.plane)
        _drained = cur


def _start_drainer() -> None:
    global _drainer_started
    with _lock:
        if _drainer_started:
            return
        _drainer_started = True
    t = threading.Thread(target=_drain_loop, daemon=True, name="trace-drain")
    t.start()


def _drain_loop() -> None:
    while True:
        time.sleep(_DRAIN_INTERVAL_S)
        drain()


DEFAULT_REGISTRY.add_prerender_hook(drain)


def reset() -> None:
    """Test hook: empty the ring, slowest table, and drain cursor. The
    counter itself is never replaced (its bound `__next__` lives in
    long-lived per-connection closures) — `_reset_base` rebases the
    recorded count instead."""
    global _reset_base, _drained, _slow_floor
    with _lock:
        for i in range(_RING_SIZE):
            _ring[i] = None
        _reset_base = _drained = _peek()
        del _slowest[:]
        del _slow_durs[:]
        _slow_floor = float("-inf")


# --- span construction --------------------------------------------------


def span(
    name: str,
    header: str | None = None,
    plane: str | None = None,
    nbytes: int = 0,
    node: str = "",
    t0: float = 0.0,
) -> "Span | _NullSpan":
    """Open a span: inherits trace id / parent / plane from the ambient
    context span if any, else from a wire `header`, else mints a fresh
    trace. Returns a no-op singleton when tracing is disabled so call
    sites stay a single `with trace.span(...) as sp:` either way.
    `t0` lets a caller that already read perf_counter share the clock
    sample instead of paying a second call."""
    if not _ENABLED:
        return _NULL
    try:
        c = _tls.cell
    except AttributeError:
        c = _cell()
    parent = c[0]
    if parent is not None:
        tid = parent._tid  # share raw; formats to the same hex
        parent_id = parent.span_id
        pl = plane or parent.plane
    else:
        tup = parse_header(header) if header else None
        if tup is not None:
            tid, parent_id, hdr_plane = tup
            pl = plane or hdr_plane or PLANE_SERVE
        else:
            tid = next(_trace_counter)  # XORed with _id_base at format
            parent_id = ""
            pl = plane or PLANE_SERVE
    return Span(
        name,
        tid,
        next(_span_counter),
        parent_id,
        pl,
        node or _node_label,
        nbytes,
        c,
        t0,
    )


def connection_tracer(node: str):
    """Per-connection span open/close pair for the mini request loop:
    every hot object the lifecycle touches — the thread's context
    cell, the Span class, the C counter bumps, the ring list, the
    clock — is captured in the closures, which stay warm on the
    connection's own thread across requests, and the context-manager
    protocol (two method dispatches per request) is bypassed. MUST be
    called on the thread that will serve the requests (the cell is
    that thread's).

    Returns `(open_span, close_span, sample_hit)`. `open_span(name,
    header, nbytes, t0)` returns an ALREADY-ENTERED Span, or _NULL
    when tracing is off (the `enabled()` check stays dynamic so the
    kill switch keeps working mid-connection). The caller must pair a
    truthy result with `close_span(sp, status)` in a finally block,
    and should consult `sample_hit()` for headerless requests before
    opening anything."""
    cell = _cell()
    node = node or _node_label
    span_cls = Span
    next_sid = _span_counter.__next__
    next_tid = _trace_counter.__next__
    parse = parse_header
    null = _NULL
    ring = _ring
    mask = _RING_MASK
    ring_next = _ring_next
    pc = time.perf_counter

    next_sample = _sample_counter.__next__

    def sample_hit() -> bool:
        """Head-sampling gate for a HEADERLESS request: the caller
        checks it BEFORE open_span so a sampled-out request runs the
        identical untraced branch (zero tracer objects touched).
        Full fidelity (N=1, the default) short-circuits to True."""
        return _sample_every == 1 or next_sample() % _sample_every == 0

    def open_span(name: str, header, nbytes: int, t0: float):
        if not _ENABLED:
            return null
        parent = cell[0]
        if parent is not None:
            tid = parent._tid
            parent_id = parent.span_id
            pl = parent.plane
        else:
            tup = parse(header) if header else None
            if tup is not None:
                tid, parent_id, pl = tup
            else:
                tid = next_tid()
                parent_id = ""
                pl = PLANE_SERVE
        sp = span_cls(
            name, tid, next_sid(), parent_id, pl, node, nbytes, cell, t0
        )
        sp._prev = parent
        cell[0] = sp
        return sp

    def close_span(sp, status: int):
        sp.duration = d = pc() - sp.t0
        sp.status = status
        cell[0] = sp._prev
        ring[ring_next() & mask] = sp
        if sp.parent_id == "":
            if d > _slow_floor:
                _slow_insert(sp)
            if _slow_threshold_ms > 0 and d * 1000.0 >= _slow_threshold_ms:
                _slow_log(sp)
        if not _drainer_started:
            _start_drainer()

    return open_span, close_span, sample_hit


def loop_tracer(node: str):
    """Span mint/close pair for the EVENT-DRIVEN serving loop (the C
    epoll core, docs/SERVING.md): like connection_tracer, but
    nesting-free. The epoll loop interleaves many in-flight requests
    on ONE thread — open A, open B, close A through the thread's
    context cell would corrupt the restore stack — so each fast-path
    span rides its own throwaway cell and never becomes ambient
    context. Fast-path GETs are leaf hops that make no further calls,
    so nothing downstream needs the ambient span anyway; cross-hop
    parentage still comes from the request's X-Weed-Trace header.

    Returns `(open_span, close_span, sample_hit)`; open_span(name,
    header, nbytes, t0) -> Span | None (tracing off)."""
    node = node or _node_label
    span_cls = Span
    next_sid = _span_counter.__next__
    next_tid = _trace_counter.__next__
    parse = parse_header
    ring = _ring
    mask = _RING_MASK
    ring_next = _ring_next
    pc = time.perf_counter
    next_sample = _sample_counter.__next__

    def sample_hit() -> bool:
        return _sample_every == 1 or next_sample() % _sample_every == 0

    def open_span(name: str, header, nbytes: int, t0: float):
        if not _ENABLED:
            return None
        tup = parse(header) if header else None
        if tup is not None:
            tid, parent_id, pl = tup
        else:
            tid = next_tid()
            parent_id = ""
            pl = PLANE_SERVE
        cell = [None]
        sp = span_cls(name, tid, next_sid(), parent_id, pl, node, nbytes, cell, t0)
        sp._prev = None
        cell[0] = sp
        return sp

    def close_span(sp, status: int) -> None:
        sp.duration = d = pc() - sp.t0
        sp.status = status
        sp._cellref[0] = None
        ring[ring_next() & mask] = sp
        if sp.parent_id == "":
            if d > _slow_floor:
                _slow_insert(sp)
            if _slow_threshold_ms > 0 and d * 1000.0 >= _slow_threshold_ms:
                _slow_log(sp)
        if not _drainer_started:
            _start_drainer()

    return open_span, close_span, sample_hit


class _NullSpan:
    """Disabled-tracer stand-in: every method a no-op, `if sp:` False."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __bool__(self):
        return False

    def add_stages(self, stages):
        pass

    def annotate(self, key, value):
        pass

    status = 0
    nbytes = 0
    duration = 0.0
    error = ""


_NULL = _NullSpan()


def current() -> Span | None:
    try:
        return _tls.cell[0]
    except AttributeError:
        return None


def current_trace_id() -> str:
    sp = current()
    return sp.trace_id if sp is not None else ""


def add_stages(stages: dict[str, float]) -> None:
    """Attach stage timings to the current span (no-op untraced)."""
    sp = current()
    if sp is not None:
        sp.add_stages(stages)


def annotate(key: str, value) -> None:
    sp = current()
    if sp is not None:
        sp.annotate(key, value)


# wlog consults this per LOG LINE (not per request) so every line
# emitted inside a traced request is prefixed with its request id
wlog.set_request_id_provider(current_trace_id)


# --- wire format --------------------------------------------------------


def format_header(sp: Span) -> str:
    return f"{sp.trace_id}:{sp.span_id}:{sp.plane}"


_HEXDIGITS = frozenset("0123456789abcdefABCDEF")


def _ishex(s: str) -> bool:
    return all(c in _HEXDIGITS for c in s)


def parse_header(value: str) -> tuple[str, str, str] | None:
    """`trace:parent:plane` -> tuple, or None when malformed. Tokens are
    length-capped: the header crosses trust boundaries (a public client
    can send one) and must never become an unbounded stored string."""
    if not value or len(value) > 128:
        return None
    parts = value.split(":")
    if len(parts) != 3:
        return None
    trace_id, parent_id, plane = parts
    if not trace_id or len(trace_id) > 32 or len(parent_id) > 32:
        return None
    # ids must be hex: they end up inside log-format strings (wlog's
    # [trace_id] prefix) and shell output, so a public client must not
    # be able to smuggle '%' or control characters through the header
    if not _ishex(trace_id) or (parent_id and not _ishex(parent_id)):
        return None
    if plane not in ("serve", "scrub", "repair", "tier"):
        plane = PLANE_SERVE
    return trace_id, parent_id, plane


def header_value() -> str | None:
    """The `X-Weed-Trace` value for an outbound hop under the current
    span, or None when untraced/disabled."""
    if not _ENABLED:
        return None
    sp = current()
    return format_header(sp) if sp is not None else None


def inject(headers: dict) -> dict:
    """Add the trace header to an outbound header dict (mutates and
    returns it). The single call every internal HTTP hop makes."""
    v = header_value()
    if v is not None:
        headers[TRACE_HEADER] = v
    return headers


def inject_request(req) -> None:
    """Stamp the current span's context onto an outbound
    urllib.request.Request — the HTTP-object twin of inject()."""
    v = header_value()
    if v is not None:
        req.add_header(TRACE_HEADER, v)


def grpc_metadata() -> tuple | None:
    """Invocation metadata for an outbound gRPC hop (VolumeEcShardRead
    et al.), or None when untraced."""
    v = header_value()
    return ((TRACE_HEADER, v),) if v is not None else None


def header_from_grpc_context(context) -> str | None:
    """Pull the trace header off a servicer context's metadata."""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == TRACE_HEADER:
                return v
    except Exception:  # noqa: BLE001 — tracing must never fail a verb
        return None
    return None


# --- operator surfaces --------------------------------------------------


def debug_payload(n: int = 64) -> dict:
    """`/debug/traces`: tracer state + recent and slowest-N completed
    spans (?n= caps the recent list; n=0 returns only the meta)."""
    drain()
    with _lock:
        cur = _peek()
        total = cur - _reset_base
        count = min(total, _RING_SIZE, max(0, n))
        recent = [
            _ring[(cur - 1 - i) & _RING_MASK] for i in range(count)
        ]
        slowest = sorted(_slowest, key=lambda s: s.duration, reverse=True)
    inflight = _open_spans()
    return {
        "node": _node_label,
        "enabled": _ENABLED,
        "ring_size": _RING_SIZE,
        "recorded": total,
        "dropped": max(0, total - _RING_SIZE),
        "slow_ms": _slow_threshold_ms,
        "inflight": len(inflight),
        "recent": [s.to_dict() for s in recent if s is not None],
        "slowest": [s.to_dict() for s in slowest],
    }


def _open_spans() -> list[Span]:
    """Every currently-open span across threads: walk each registered
    thread cell's previous-span chain. Cells of dead threads are
    dropped along the way."""
    alive = {t.ident for t in threading.enumerate()}
    spans: list[Span] = []
    with _lock:
        for ident in list(_cells):
            if ident not in alive:
                del _cells[ident]
                continue
            sp = _cells[ident][0]
            while sp is not None:
                spans.append(sp)
                sp = sp._prev
    return spans


def inflight_payload() -> dict:
    """`/debug/requests`: spans currently open in this process."""
    now = time.perf_counter()
    return {
        "node": _node_label,
        "inflight": [
            {
                "trace": s.trace_id,
                "span": s.span_id,
                "parent": s.parent_id,
                "name": s.name,
                "plane": s.plane,
                "age_ms": round((now - s.t0) * 1000.0, 3),
                "bytes": s.nbytes,
            }
            for s in _open_spans()
        ],
    }


def _vlog_enabled(level: int = 2) -> bool:
    """Whether verbose tracing logs are on for THIS module — the
    set_vmodule('tracer=N') probe tests/test_trace.py exercises."""
    return bool(wlog.V(level))
