"""Always-on per-daemon flight recorder (weedscope, docs/TRACING.md).

A bounded ring of structured wide-events — one per completed request:
trace id, plane, status, duration, stage timings, peer, bytes, and the
hedge/retry/shed/deadline flags — fed from the same two funnels the
trace plane uses (util/httpd.serve_connection and the C fast path's
complete() callback in util/native_serve). Unlike the span ring, which
head-samples and exists to be drained into histograms, the blackbox is
the post-hoc evidence store: when an SLO burns, the capsule snapshot
of this ring is what shows WHICH requests were slow or failing and how
their stages split, minutes after the fact.

Retention is TAIL-BIASED, two rings:

  * the tail ring keeps EVERY error (status >= 400) and every slow
    request (duration >= WEED_SCOPE_SLOW_MS);
  * the ok ring keeps 1-in-N sampled successes (WEED_SCOPE_OK_EVERY)
    so the baseline is always on hand for comparison without OK
    traffic flushing the interesting tail out of a single ring.

Hot-path economy follows the tracer's cold-line rule: a recorder is a
closure holding preallocated rings and bound C counters; recording an
OK request that loses the 1-in-N draw is one counter bump and a modulo
— no tuple is even built. `WEED_SCOPE=0` turns the whole plane off
(record() returns at one module-global check).

Records are plain tuples (no class, no __dict__):

    (wall, name, trace_id, plane, status, dur_s, nbytes, peer,
     flags, stages)

`flags` is a bitmask (FLAG_HEDGE|FLAG_RETRY|FLAG_SHED|FLAG_DEADLINE);
`stages` is the span's stage dict (shared, never mutated after close)
or None.
"""

from __future__ import annotations

import itertools
import os
import threading
import time

FLAG_HEDGE = 1     # request carried the x-weed-hedge hop header
FLAG_RETRY = 2     # request carried the x-weed-retry hop header
FLAG_SHED = 4      # 503: admission control / lame-duck shed
FLAG_DEADLINE = 8  # 504: X-Weed-Deadline expired

_FLAG_NAMES = (
    (FLAG_HEDGE, "hedge"),
    (FLAG_RETRY, "retry"),
    (FLAG_SHED, "shed"),
    (FLAG_DEADLINE, "deadline"),
)

_ENABLED = os.environ.get("WEED_SCOPE", "1") != "0"


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


_RING_SIZE = _pow2(max(64, int(os.environ.get("WEED_SCOPE_RING", "1024") or 1024)))
_RING_MASK = _RING_SIZE - 1
_OK_EVERY = max(1, int(os.environ.get("WEED_SCOPE_OK_EVERY", "16") or 16))
_SLOW_S = float(os.environ.get("WEED_SCOPE_SLOW_MS", "100") or 100) / 1000.0

# the two rings: writers never lock (slot index off a C counter,
# GIL-atomic list store — the tracer ring's idiom)
_tail: list[tuple | None] = [None] * _RING_SIZE
_tail_counter = itertools.count()
_tail_next = _tail_counter.__next__
_ok: list[tuple | None] = [None] * _RING_SIZE
_ok_counter = itertools.count()
_ok_next = _ok_counter.__next__
_sample_counter = itertools.count()

_lock = threading.Lock()  # snapshot/reset only — never the record path
_reset_tail = 0
_reset_ok = 0

# wall = base + perf_counter(), the tracer's one-clock-call trick
_WALL_BASE = time.time() - time.perf_counter()


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Runtime kill switch (WEED_SCOPE=0 sets the boot default; bench
    A/B arms and tests flip it in-process)."""
    global _ENABLED
    _ENABLED = bool(on)


def slow_threshold_s() -> float:
    return _SLOW_S


def recorder(name_prefix: str, node: str):
    """Build the per-server record closure the dispatch funnels call
    once per completed request:

        record(method, trace_id, plane, status, dur_s, nbytes, peer,
               flags, stages)

    Everything the per-request path touches is hoisted into closure
    locals (the docs/TRACING.md cold-line rule); `name_prefix` becomes
    the event name prefix (`volume.GET`)."""
    tail = _tail
    mask = _RING_MASK
    tail_next = _tail_next
    ok = _ok
    ok_next = _ok_next
    next_sample = _sample_counter.__next__
    ok_every = _OK_EVERY
    slow_s = _SLOW_S
    wall_base = _WALL_BASE
    pc = time.perf_counter
    prefix = (name_prefix or "http") + "."

    def record(
        method: str,
        trace_id: str,
        plane: str,
        status: int,
        dur_s: float,
        nbytes: int,
        peer: str,
        flags: int,
        stages,
    ) -> None:
        if not _ENABLED:
            return
        if status < 400 and dur_s < slow_s and flags == 0:
            # the common case: an unremarkable OK. Decide 1-in-N BEFORE
            # building anything — a lost draw costs one bump + modulo.
            if ok_every != 1 and next_sample() % ok_every:
                return
            ring, nxt = ok, ok_next
        else:
            ring, nxt = tail, tail_next
        ring[nxt() & mask] = (
            wall_base + pc(),
            prefix + method,
            trace_id,
            plane,
            status,
            dur_s,
            nbytes,
            peer,
            flags,
            stages,
        )

    return record


def request_flags(headers, status: int) -> int:
    """Flag bitmask for a completed request: hop headers mark hedged
    and retried attempts (the sending sides stamp x-weed-hedge /
    x-weed-retry), the status marks shed (503) and expired-deadline
    (504) outcomes."""
    flags = 0
    if headers.get("x-weed-hedge") is not None:
        flags |= FLAG_HEDGE
    if headers.get("x-weed-retry") is not None:
        flags |= FLAG_RETRY
    if status == 503:
        flags |= FLAG_SHED
    elif status == 504:
        flags |= FLAG_DEADLINE
    return flags


def _dump(rec: tuple) -> dict:
    wall, name, trace_id, plane, status, dur_s, nbytes, peer, flags, stages = rec
    d = {
        "t": round(wall, 3),
        "name": name,
        "trace": trace_id,
        "plane": plane,
        "status": status,
        "dur_ms": round(dur_s * 1000.0, 3),
        "bytes": nbytes,
        "peer": peer,
    }
    if flags:
        d["flags"] = [n for bit, n in _FLAG_NAMES if flags & bit]
    if stages:
        d["stages_ms"] = {k: round(v * 1000.0, 3) for k, v in stages.items()}
    return d


def _peek(counter: itertools.count) -> int:
    return counter.__reduce__()[1][0]


def _ring_slice(ring: list, counter: itertools.count, n: int) -> list[tuple]:
    cur = _peek(counter)
    count = min(cur, _RING_SIZE, max(0, n))
    out = []
    for i in range(count):
        rec = ring[(cur - 1 - i) & _RING_MASK]
        if rec is not None:
            out.append(rec)
    return out


def snapshot(n: int = 256) -> dict:
    """`/debug/blackbox` and the capsule's flight-recorder section:
    newest-first tail (errors + slow) and sampled-OK records, plus the
    recorder's own accounting so "0 interesting events" is
    distinguishable from "recorder off"."""
    with _lock:
        tail_total = _peek(_tail_counter) - _reset_tail
        ok_total = _peek(_ok_counter) - _reset_ok
        tail = _ring_slice(_tail, _tail_counter, n)
        oks = _ring_slice(_ok, _ok_counter, n)
    return {
        "enabled": _ENABLED,
        "ring_size": _RING_SIZE,
        "ok_every": _OK_EVERY,
        "slow_ms": _SLOW_S * 1000.0,
        "tail_recorded": tail_total,
        "ok_recorded": ok_total,
        "tail": [_dump(r) for r in tail],
        "ok": [_dump(r) for r in oks],
    }


def reset() -> None:
    """Test hook: empty both rings (the counters are never replaced —
    their bound __next__ lives in per-server recorder closures)."""
    global _reset_tail, _reset_ok
    with _lock:
        for i in range(_RING_SIZE):
            _tail[i] = None
            _ok[i] = None
        _reset_tail = _peek(_tail_counter)
        _reset_ok = _peek(_ok_counter)
