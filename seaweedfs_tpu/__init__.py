"""seaweedfs_tpu — a TPU-native distributed object/file store.

A from-scratch re-design of the SeaweedFS capability set (Haystack-style
blob store: master + volume servers + erasure-coded warm tier + filer)
whose performance-critical tier — the RS(10,4) GF(2^8) erasure codec —
runs as JAX/XLA programs on TPU, with bitsliced XOR-matmul kernels that
ride the MXU, and whose multi-volume batch paths shard over a
`jax.sharding.Mesh`.

Layering (mirrors SURVEY.md §1):
    storage/   L1 storage engine: needle format, volumes, needle maps
    ec/        the EC codec + striping + EC volumes (the north star)
    topology/  L3 control plane: node tree, layouts, placement
    server/    L2/L3 HTTP+RPC servers (master, volume)
    filer/     L5 namespace layer
    parallel/  mesh/sharding helpers for batched TPU paths
    util/      cross-cutting codecs, crc, config

On-disk formats are bit-compatible with the reference implementation
(see SURVEY.md; citations in each module point at
/root/reference/weed/... file:line for the behavior being matched).
"""

__version__ = "0.1.0"
