"""Dynamic crash-state enumerator: record a live workload's effect
trace, enumerate every disk state a crash could legally leave behind,
and re-run real recovery against each one (docs/ANALYSIS.md v3).

The ALICE idea (the crash-state exploration literature the ISSUE/
PAPERS.md cite): the kernel only promises what fsync promises. Between
barriers, data writes may be lost or torn and directory operations may
land without the data they publish. So instead of arguing "the .idx
entry is appended after the pwritev" in a comment, we

  1. RECORD the ordered effect trace of a real workload — a shim over
     os.pwrite/pwritev/write/fsync/replace/rename/ftruncate/unlink
     plus a buffered-`open` proxy, installed/uninstalled the way
     tests/faults.py and the lock witness install themselves;
  2. ENUMERATE legal post-crash states under this model:
       * per-file data writes persist as a PREFIX of their issue
         order, with the final applied write optionally TORN at any
         iov boundary or byte cut (the ordered-writeback model of an
         append-only file; see non-goals below);
       * directory operations (create/rename/unlink) are totally
         ordered among themselves; a crash keeps a prefix of them —
         independently of data durability, which is exactly the
         rename-visible-before-data hazard;
       * an fsync of a file pins every earlier write to that file;
         an fsync of a directory pins every earlier namespace op;
     bounded by WEED_CRASH_BUDGET with deterministic seeded sampling
     (WEED_CRASH_SEED) and an explicit `truncated` flag — never a
     silent cap;
  3. MATERIALIZE each candidate into a scratch dir (WEED_CRASH_SCRATCH
     or a tempdir) and run REAL recovery — `Volume(create=False,
     repair=True)` + idx replay, scrub-state load — asserting the
     workload's invariants: no acked needle lost, no torn record
     surfaced as valid (CRC gate), .idx never references bytes past
     the .dat, vacuum recovers to wholly-old or wholly-new.

Non-goals (stated, per the no-silent-caps rule): no sector-granularity
tearing (tears are byte cuts of one logical write, plus iov
boundaries); within ONE file writes persist in issue order (cross-file
and data-vs-namespace reordering is fully modeled — that is where
every bug this plane has caught lives); no modeling of filesystem
metadata corruption beyond lost/landed namespace ops.
"""

from __future__ import annotations

import builtins
import hashlib
import os
import random
import shutil
import tempfile
from dataclasses import dataclass, field

from seaweedfs_tpu.util import wlog

# ---------------------------------------------------------------------------
# knobs (documented in OPERATIONS.md "Environment knobs")


def budget_default() -> int:
    try:
        return int(os.environ.get("WEED_CRASH_BUDGET", "256"))
    except ValueError:
        return 256


def seed_default() -> int:
    try:
        return int(os.environ.get("WEED_CRASH_SEED", "0"))
    except ValueError:
        return 0


def scratch_base() -> str | None:
    return os.environ.get("WEED_CRASH_SCRATCH") or None


# ---------------------------------------------------------------------------
# the recorded effect trace


@dataclass
class Event:
    kind: str  # write | trunc | fsync | link | rename | unlink | dirsync | ack
    ino: int = -1  # write/trunc/fsync target
    offset: int = 0
    chunks: tuple = ()  # write payload, one entry per iov
    size: int = 0  # trunc
    path: str = ""  # link/unlink target, rename SRC
    dst: str = ""  # rename destination
    payload: object = None  # ack marker

    def nbytes(self) -> int:
        return sum(len(c) for c in self.chunks)


@dataclass
class Trace:
    root: str
    initial: dict[int, bytes] = field(default_factory=dict)  # ino -> bytes
    ns0: dict[str, int] = field(default_factory=dict)  # rel path -> ino
    events: list[Event] = field(default_factory=list)


class Recorder:
    """Installable effect-trace shim. Paths outside `root` pass through
    unrecorded; everything under it lands in the trace with inode
    identity preserved across renames (the two-generation vacuum swap
    depends on it)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.trace = Trace(root=self.root)
        self._ns: dict[str, int] = {}  # live rel-path -> ino mirror
        self._next_ino = 0
        self._fd: dict[int, int] = {}  # os-level fd -> ino
        self._dirfd: set[int] = set()  # fds opened on directories
        self._installed = False
        self._orig: dict[str, object] = {}
        self._snapshot()

    # -- helpers ---------------------------------------------------------
    def _rel(self, path) -> str | None:
        try:
            p = os.path.abspath(os.fspath(path))
        except TypeError:
            return None
        if p == self.root or p.startswith(self.root + os.sep):
            return os.path.relpath(p, self.root)
        return None

    def _snapshot(self) -> None:
        for dirpath, _dirs, files in os.walk(self.root):
            for name in files:
                p = os.path.join(dirpath, name)
                rel = os.path.relpath(p, self.root)
                with open(p, "rb") as f:
                    data = f.read()
                ino = self._next_ino
                self._next_ino += 1
                self.trace.initial[ino] = data
                self.trace.ns0[rel] = ino
                self._ns[rel] = ino

    def _emit(self, **kw) -> None:
        self.trace.events.append(Event(**kw))

    def mark(self, payload) -> None:
        """Workload marker (e.g. 'these needle ids are now acked'):
        rides the trace so invariants can be crash-point-relative."""
        self._emit(kind="ack", payload=payload)

    def _creat(self, rel: str, truncate: bool) -> int:
        ino = self._ns.get(rel)
        if ino is None:
            ino = self._next_ino
            self._next_ino += 1
            self._ns[rel] = ino
            self._emit(kind="link", path=rel, ino=ino)
            self._emit(kind="trunc", ino=ino, size=0)
        elif truncate:
            self._emit(kind="trunc", ino=ino, size=0)
        return ino

    # -- install/uninstall ----------------------------------------------
    def install(self) -> None:
        assert not self._installed
        self._installed = True
        rec = self
        self._orig = {
            "open": builtins.open,
            "os_open": os.open,
            "os_close": os.close,
            "pwrite": os.pwrite,
            "pwritev": os.pwritev,
            "write": os.write,
            "fsync": os.fsync,
            "fdatasync": os.fdatasync,
            "replace": os.replace,
            "rename": os.rename,
            "truncate": os.truncate,
            "ftruncate": os.ftruncate,
            "remove": os.remove,
            "unlink": os.unlink,
            "posix_fallocate": os.posix_fallocate,
        }
        o = self._orig

        def _open(path, mode="r", *a, **kw):
            f = o["open"](path, mode, *a, **kw)
            rel = rec._rel(path) if isinstance(path, (str, bytes, os.PathLike)) else None
            if rel is None or getattr(f, "readable", None) is None:
                return f
            writable = any(m in mode for m in ("w", "a", "+", "x"))
            if not writable:
                # read opens are invisible to the crash model; fd-based
                # fsyncs arrive via os.open (durable.fsync_path), which
                # registers its own mapping
                return f
            ino = rec._creat(rel, truncate="w" in mode)
            rec._fd[f.fileno()] = ino
            return _RecordingFile(f, rec, ino)

        def _os_open(path, flags, *a, **kw):
            fd = o["os_open"](path, flags, *a, **kw)
            rel = rec._rel(path)
            if rel is not None:
                try:
                    is_dir = os.path.isdir(path)
                except OSError:
                    is_dir = False
                if is_dir:
                    rec._dirfd.add(fd)
                else:
                    if flags & (os.O_WRONLY | os.O_RDWR | os.O_CREAT):
                        rec._creat(rel, truncate=bool(flags & os.O_TRUNC))
                    ino = rec._ns.get(rel)
                    if ino is not None:
                        rec._fd[fd] = ino
            return fd

        def _os_close(fd):
            rec._fd.pop(fd, None)
            rec._dirfd.discard(fd)
            return o["os_close"](fd)

        def _pwrite(fd, data, offset):
            n = o["pwrite"](fd, data, offset)
            ino = rec._fd.get(fd)
            if ino is not None:
                rec._emit(kind="write", ino=ino, offset=offset,
                          chunks=(bytes(data[:n]),))
            return n

        def _pwritev(fd, buffers, offset, *a):
            bufs = [bytes(b) for b in buffers]
            n = o["pwritev"](fd, bufs, offset, *a)
            ino = rec._fd.get(fd)
            if ino is not None:
                rec._emit(kind="write", ino=ino, offset=offset,
                          chunks=tuple(bufs))
            return n

        def _write(fd, data):
            ino = rec._fd.get(fd)
            pos = os.lseek(fd, 0, os.SEEK_CUR) if ino is not None else 0
            n = o["write"](fd, data)
            if ino is not None:
                rec._emit(kind="write", ino=ino, offset=pos,
                          chunks=(bytes(data[:n]),))
            return n

        def _fsync(fd):
            r = o["fsync"](fd)
            if fd in rec._dirfd:
                rec._emit(kind="dirsync")
            else:
                ino = rec._fd.get(fd)
                if ino is not None:
                    rec._emit(kind="fsync", ino=ino)
            return r

        def _replace(src, dst, **kw):
            r = o["replace"](src, dst, **kw)
            rs, rd = rec._rel(src), rec._rel(dst)
            if rs is not None and rd is not None and rs in rec._ns:
                rec._ns[rd] = rec._ns.pop(rs)
                rec._emit(kind="rename", path=rs, dst=rd)
            return r

        def _truncate(path, length):
            r = o["truncate"](path, length)
            if isinstance(path, int):
                ino = rec._fd.get(path)
            else:
                rel = rec._rel(path)
                ino = rec._ns.get(rel) if rel is not None else None
            if ino is not None:
                rec._emit(kind="trunc", ino=ino, size=length)
            return r

        def _ftruncate(fd, length):
            r = o["ftruncate"](fd, length)
            ino = rec._fd.get(fd)
            if ino is not None:
                rec._emit(kind="trunc", ino=ino, size=length)
            return r

        def _posix_fallocate(fd, offset, length):
            r = o["posix_fallocate"](fd, offset, length)
            ino = rec._fd.get(fd)
            if ino is not None:
                # modeled as a size-extension whose durability is
                # independent (a trunc event the enumerator may apply
                # or drop) — the EC stream drivers preallocate with
                # exactly this call before their pwritev streams. The
                # recorded size is the REAL post-call st_size, not
                # offset+length: fallocate never shrinks, so emitting
                # the smaller value for an already-larger file would
                # let the sweep materialize shrunken states no
                # hardware can produce
                rec._emit(kind="trunc", ino=ino,
                          size=os.fstat(fd).st_size)
            return r

        def _remove(path, **kw):
            r = o["remove"](path, **kw)
            rel = rec._rel(path)
            if rel is not None and rel in rec._ns:
                rec._ns.pop(rel)
                rec._emit(kind="unlink", path=rel)
            return r

        builtins.open = _open
        os.open = _os_open
        os.close = _os_close
        os.pwrite = _pwrite
        os.pwritev = _pwritev
        os.write = _write
        os.fsync = _fsync
        os.fdatasync = _fsync
        os.replace = _replace
        os.rename = _replace
        os.truncate = _truncate
        os.ftruncate = _ftruncate
        os.remove = _remove
        os.unlink = _remove
        os.posix_fallocate = _posix_fallocate

    def uninstall(self) -> None:
        if not self._installed:
            return
        o = self._orig
        builtins.open = o["open"]
        os.open = o["os_open"]
        os.close = o["os_close"]
        os.pwrite = o["pwrite"]
        os.pwritev = o["pwritev"]
        os.write = o["write"]
        os.fsync = o["fsync"]
        os.fdatasync = o["fdatasync"]
        os.replace = o["replace"]
        os.rename = o["rename"]
        os.truncate = o["truncate"]
        os.ftruncate = o["ftruncate"]
        os.remove = o["remove"]
        os.unlink = o["unlink"]
        os.posix_fallocate = o["posix_fallocate"]
        self._installed = False

    def __enter__(self) -> "Recorder":
        self.install()
        return self

    def __exit__(self, *exc) -> None:
        self.uninstall()


class _RecordingFile:
    """Buffered-file proxy: records write/truncate effects at the
    OS-visible layer (offset = position at write time) and delegates
    everything else. App-buffer vs page-cache is deliberately NOT
    modeled separately: both are lost without fsync, which is the only
    distinction the crash model needs."""

    def __init__(self, f, rec: Recorder, ino: int):
        self._f = f
        self._rec = rec
        self._ino = ino
        # text handles: tell() returns an opaque cookie, so byte
        # positions are tracked here (text writes in this tree are
        # sequential json/str dumps into fresh tmp files)
        self._text = "b" not in getattr(f, "mode", "b")
        self._pos = os.fstat(f.fileno()).st_size if self._text else 0

    def write(self, data):
        if self._text:
            n = self._f.write(data)
            payload = data[:n].encode(
                getattr(self._f, "encoding", None) or "utf-8"
            )
            self._rec._emit(kind="write", ino=self._ino, offset=self._pos,
                            chunks=(payload,))
            self._pos += len(payload)
            return n
        pos = self._f.tell()
        n = self._f.write(data)
        self._rec._emit(kind="write", ino=self._ino, offset=pos,
                        chunks=(bytes(data[:n]),))
        return n

    def writelines(self, lines):
        for line in lines:
            self.write(line)

    def truncate(self, size=None):
        size = self._f.tell() if size is None else size
        r = self._f.truncate(size)
        self._rec._emit(kind="trunc", ino=self._ino, size=size)
        return r

    def close(self):
        try:
            fd = self._f.fileno()
        except ValueError:
            fd = -1  # already closed
        self._rec._fd.pop(fd, None)
        return self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __getattr__(self, name):
        return getattr(self._f, name)

    def __iter__(self):
        return iter(self._f)


# ---------------------------------------------------------------------------
# legal-crash-state enumeration


@dataclass
class CrashState:
    label: str
    crash_index: int
    files: dict[str, bytes]  # rel path -> content

    def digest(self) -> str:
        h = hashlib.sha1()
        for path in sorted(self.files):
            h.update(path.encode())
            h.update(b"\0")
            h.update(hashlib.sha1(self.files[path]).digest())
        return h.hexdigest()


def _apply_write(buf: bytearray, ev: Event, upto: int | None = None) -> None:
    data = b"".join(ev.chunks)
    if upto is not None:
        data = data[:upto]
    end = ev.offset + len(data)
    if len(buf) < end:
        buf.extend(bytes(end - len(buf)))
    buf[ev.offset:end] = data


def _materialize(trace: Trace, crash_index: int, cuts: dict[int, int],
                 ns_cut: int, torn: tuple[int, int] | None,
                 label: str) -> CrashState:
    """Build the on-disk state: per-ino apply the first cuts[ino] of
    its data ops (writes + truncs, in issue order), `torn` = (event
    index, byte prefix) partially applies one more write; namespace =
    ns0 + the first ns_cut namespace ops."""
    per_ino: dict[int, list[tuple[int, Event]]] = {}
    ns_ops: list[Event] = []
    for idx, ev in enumerate(trace.events[:crash_index]):
        if ev.kind in ("write", "trunc"):
            per_ino.setdefault(ev.ino, []).append((idx, ev))
        elif ev.kind in ("link", "rename", "unlink"):
            ns_ops.append(ev)
    content: dict[int, bytearray] = {
        ino: bytearray(data) for ino, data in trace.initial.items()
    }
    for ino, ops in per_ino.items():
        buf = content.setdefault(ino, bytearray())
        n = cuts.get(ino, len(ops))
        for _idx, ev in ops[:n]:
            if ev.kind == "write":
                _apply_write(buf, ev)
            else:
                if ev.size < len(buf):
                    del buf[ev.size:]
                else:
                    buf.extend(bytes(ev.size - len(buf)))
        if torn is not None and n < len(ops):
            t_idx, t_bytes = torn
            if ops[n][0] == t_idx and ops[n][1].kind == "write":
                _apply_write(buf, ops[n][1], upto=t_bytes)
    ns: dict[str, int] = dict(trace.ns0)
    for ev in ns_ops[:ns_cut]:
        if ev.kind == "link":
            ns[ev.path] = ev.ino
        elif ev.kind == "rename":
            if ev.path in ns:
                ns[ev.dst] = ns.pop(ev.path)
        elif ev.kind == "unlink":
            ns.pop(ev.path, None)
    files = {
        path: bytes(content.get(ino, bytearray())) for path, ino in ns.items()
    }
    return CrashState(label=label, crash_index=crash_index, files=files)


def _mandatory(trace: Trace, crash_index: int
               ) -> tuple[dict[int, int], int, dict[int, int], int]:
    """(per-ino mandatory cut, mandatory ns cut, per-ino total ops,
    total ns ops) at a crash index: fsync pins all earlier writes to
    that file; dirsync pins all earlier namespace ops."""
    counts: dict[int, int] = {}
    mand: dict[int, int] = {}
    ns_total = 0
    ns_mand = 0
    for ev in trace.events[:crash_index]:
        if ev.kind in ("write", "trunc"):
            counts[ev.ino] = counts.get(ev.ino, 0) + 1
        elif ev.kind in ("link", "rename", "unlink"):
            ns_total += 1
        elif ev.kind == "fsync":
            mand[ev.ino] = counts.get(ev.ino, 0)
        elif ev.kind == "dirsync":
            ns_mand = ns_total
    return mand, ns_mand, counts, ns_total


def enumerate_states(trace: Trace, budget: int | None = None,
                     seed: int | None = None
                     ) -> tuple[list[CrashState], bool, int]:
    """(deduped states, truncated?, candidate count before budget)."""
    budget = budget_default() if budget is None else budget
    seed = seed_default() if seed is None else seed
    events = trace.events
    # candidates are cheap PARAMETER tuples (crash_index, cuts, ns_cut,
    # torn, label); _materialize — which replays the trace and copies
    # every file's bytes — runs only on the states the budget keeps
    specs: list[tuple] = []

    # 1. in-order prefixes: crash after event i with everything issued
    #    so far on disk (writeback caught up, then power cut)
    for i in range(len(events) + 1):
        specs.append((i, {}, 1 << 30, None, f"prefix@{i}"))

    # 2. reorder states at each barrier-relevant point: only durable
    #    data survived, with (a) all namespace ops landed — the
    #    rename-visible-before-data shape — and (b) only durable
    #    namespace ops landed
    for i in range(1, len(events) + 1):
        mand, ns_mand, counts, ns_total = _mandatory(trace, i)
        if all(mand.get(k, 0) == v for k, v in counts.items()) and \
                ns_mand == ns_total:
            continue  # nothing pending: identical to the prefix state
        cuts = {ino: mand.get(ino, 0) for ino in counts}
        specs.append((i, cuts, ns_total, None, f"durable-data+all-ns@{i}"))
        specs.append((i, cuts, ns_mand, None, f"durable-only@{i}"))

    # 3. torn final write: iov boundaries + byte cuts of each write
    for i, ev in enumerate(events):
        if ev.kind != "write":
            continue
        total = ev.nbytes()
        if total <= 1:
            continue
        cutpoints: list[int] = []
        acc = 0
        for c in ev.chunks[:-1]:
            acc += len(c)
            cutpoints.append(acc)  # every iov boundary
        cutpoints += [1, total // 2, total - 1]
        seen_cut: set[int] = set()
        per_ino_ops = sum(
            1 for e in events[:i]
            if e.kind in ("write", "trunc") and e.ino == ev.ino
        )
        for cut in cutpoints:
            if not 0 < cut < total or cut in seen_cut:
                continue
            seen_cut.add(cut)
            specs.append((
                i + 1, {ev.ino: per_ino_ops}, 1 << 30, (i, cut),
                f"torn@{i}+{cut}B",
            ))

    n_candidates = len(specs)
    truncated = False
    rng = random.Random(seed)
    if n_candidates > budget:
        truncated = True
        # half the budget is a deterministic even spread INCLUDING both
        # endpoints (a floor-stride spread can never pick the last
        # ~n/budget candidates — which are exactly the torn states of
        # the trace's final writes, generated last); the rest is a
        # seeded sample of the remainder so repeated runs with
        # different WEED_CRASH_SEEDs cover different slices
        det = max(2, budget // 2)
        stride = (n_candidates - 1) / (det - 1)
        idxs = {int(round(k * stride)) for k in range(det)}
        idxs.add(n_candidates - 1)
        rest = [i for i in range(n_candidates) if i not in idxs]
        rng.shuffle(rest)
        idxs.update(rest[: max(0, budget - len(idxs))])
        specs = [specs[i] for i in sorted(idxs)]
    else:
        # spend the remaining budget on seeded random mixed states
        extra = budget - n_candidates
        for _ in range(extra):
            if not events:
                break
            i = rng.randint(1, len(events))
            mand, ns_mand, counts, ns_total = _mandatory(trace, i)
            cuts = {
                ino: rng.randint(mand.get(ino, 0), total)
                for ino, total in counts.items()
            }
            ns_cut = rng.randint(ns_mand, ns_total)
            specs.append((i, cuts, ns_cut, None, f"random@{i}"))
    candidates = [_materialize(trace, *spec) for spec in specs]

    # dedup on (materialized content, acked-set): two states with the
    # same bytes but different ack coverage are DIFFERENT test cases —
    # the later one carries stronger invariants (keying on content
    # alone silently dropped the "batch fully applied AND acked" case)
    ack_prefix = [0]
    for ev in events:
        ack_prefix.append(ack_prefix[-1] + (ev.kind == "ack"))
    deduped: list[CrashState] = []
    seen: set[tuple[str, int]] = set()
    for st in candidates:
        key = (st.digest(), ack_prefix[min(st.crash_index, len(events))])
        if key not in seen:
            seen.add(key)
            deduped.append(st)
    return deduped, truncated, n_candidates


# ---------------------------------------------------------------------------
# the sweep harness


@dataclass
class CrashReport:
    workload: str
    states_tested: int = 0
    candidates: int = 0
    truncated: bool = False
    violations: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "workload": self.workload,
            "states_tested": self.states_tested,
            "candidates": self.candidates,
            "truncated": self.truncated,
            "violations": self.violations,
        }


def acked_at(trace: Trace, crash_index: int) -> list:
    """Every mark() payload whose ack event precedes the crash point —
    client-visible promises the recovered state must keep."""
    return [
        ev.payload for ev in trace.events[:crash_index]
        if ev.kind == "ack"
    ]


def sweep(trace: Trace, recover, workload: str = "workload",
          budget: int | None = None, seed: int | None = None,
          scratch: str | None = None) -> CrashReport:
    """Materialize every enumerated state and run `recover(dirpath,
    state, acked)` against it; any exception it raises is a recorded
    invariant violation. `acked` is the list of mark() payloads already
    acknowledged at the state's crash point."""
    report = CrashReport(workload=workload)
    states, report.truncated, report.candidates = enumerate_states(
        trace, budget=budget, seed=seed
    )
    scratch = scratch or scratch_base()
    base = tempfile.mkdtemp(prefix=f"weedcrash-{workload}-", dir=scratch)
    try:
        for st in states:
            state_dir = os.path.join(base, f"s{report.states_tested}")
            os.makedirs(state_dir)
            for rel, data in st.files.items():
                p = os.path.join(state_dir, rel)
                os.makedirs(os.path.dirname(p), exist_ok=True)
                with open(p, "wb") as f:
                    f.write(data)
            try:
                recover(state_dir, st, acked_at(trace, st.crash_index))
            except Exception as e:  # noqa: BLE001 — every failure is a finding
                report.violations.append(
                    f"[{st.label}] {type(e).__name__}: {e}"
                )
            shutil.rmtree(state_dir, ignore_errors=True)
            report.states_tested += 1
    finally:
        shutil.rmtree(base, ignore_errors=True)
    if report.truncated:
        # no-silent-caps: a bounded sweep must say it was bounded
        wlog.warning(
            "weedcrash[%s]: state budget hit — tested %d of %d "
            "candidate states (WEED_CRASH_BUDGET raises the bound)",
            workload, report.states_tested, report.candidates,
        )
    return report


# ---------------------------------------------------------------------------
# recovery invariants shared by the volume workloads


def verify_volume(state_dir: str, vid: int, acked: dict[int, bytes],
                  deleted: set[int] = frozenset(),
                  revisions: tuple[int, ...] | None = None):
    """Open the volume the way server startup does and assert the
    recovery invariants. Returns the recovered Volume's stats for
    workload-specific extra checks."""
    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.needle import get_actual_size
    from seaweedfs_tpu.storage.volume import NeedleNotFound, Volume

    v = Volume(state_dir, vid, create=False, repair=True)
    try:
        if revisions is not None:
            rev = v.super_block.compaction_revision
            assert rev in revisions, (
                f"hybrid generation: compaction revision {rev} not in "
                f"{revisions}"
            )
        dat_size = v.data_file_size()
        for nv in v.nm.items():
            if nv.offset == 0 or nv.size == t.TOMBSTONE_FILE_SIZE:
                continue
            end = nv.actual_offset + get_actual_size(nv.size, v.version)
            assert end <= dat_size, (
                f"idx references bytes past .dat: needle {nv.key} ends "
                f"at {end}, .dat is {dat_size}"
            )
        for nid, data in acked.items():
            n = v.read_needle(nid)  # CRC-gated read
            assert n.data == data, (
                f"acked needle {nid}: recovered {len(n.data)}B != "
                f"written {len(data)}B"
            )
        for nid in deleted:
            try:
                v.read_needle(nid)
            except NeedleNotFound:
                continue
            raise AssertionError(f"deleted needle {nid} resurrected")
        return v.stats_snapshot()
    finally:
        v.close()


# ---------------------------------------------------------------------------
# workload traces (the ones the acceptance gate sweeps)


def _mk_needle(nid: int, payload: bytes):
    from seaweedfs_tpu.storage.needle import Needle

    return Needle(cookie=0x5EED, id=nid, data=payload)


def run_group_commit(budget: int | None = None,
                     seed: int | None = None) -> CrashReport:
    """Group-commit POST burst: base needles durably acked, then one
    write_needles batch (ONE pwritev + ONE fsync) acked at the end.
    Invariants: acked-at-crash needles survive every legal state, torn
    batch tails never surface as valid records."""
    from seaweedfs_tpu.storage.volume import Volume

    with tempfile.TemporaryDirectory() as d:
        v = Volume(d, 1)
        base = {i: b"base-%03d\xff" % i * 40 for i in range(1, 4)}
        for nid, data in base.items():
            v.write_needle(_mk_needle(nid, data))
        v.commit()
        v.close()
        # the volume is REOPENED inside the recording window so its
        # .dat fd and .idx append handle are the recording proxies —
        # handles opened before install() would bypass the trace
        rec = Recorder(d)
        rec.mark(dict(base))
        batch = {i: b"batch-%03d\x00\xfe" % i * 60 for i in range(10, 18)}
        with rec:
            v = Volume(d, 1, create=False)
            results = v.write_needles(
                [(_mk_needle(nid, data), None) for nid, data in batch.items()],
                durable=True,
            )
            for r in results:
                if isinstance(r, BaseException):
                    raise r
            rec.mark(dict(batch))
            v.close()

        def recover(state_dir, _st, acked_payloads):
            acked: dict[int, bytes] = {}
            for p in acked_payloads:
                acked.update(p)
            verify_volume(state_dir, 1, acked)

        return sweep(rec.trace, recover, workload="group-commit",
                     budget=budget, seed=seed)


def run_vacuum(budget: int | None = None,
               seed: int | None = None) -> CrashReport:
    """Vacuum crash matrix: compact() → post-snapshot write →
    commit_compact(), crashed at every enumerated point. Invariants:
    recovery reaches wholly-old or wholly-new (never the new .dat
    under the old .idx), every durably-acked needle survives both
    generations, deletes stay deleted."""
    from seaweedfs_tpu.storage.volume import Volume

    with tempfile.TemporaryDirectory() as d:
        v = Volume(d, 1)
        live = {i: b"vac-%03d\xaa" % i * 50 for i in range(1, 7)}
        for nid, data in live.items():
            v.write_needle(_mk_needle(nid, data))
        v.delete_needle(_mk_needle(2, b""))
        del live[2]
        old_rev = v.super_block.compaction_revision
        v.commit()
        v.close()
        rec = Recorder(d)
        rec.mark(dict(live))
        with rec:
            # reopened under the recorder: see run_group_commit
            v = Volume(d, 1, create=False)
            v.compact()
            extra = {20: b"post-snapshot\xbb" * 30}
            v.write_needle(_mk_needle(20, extra[20]))
            v.commit()
            rec.mark(dict(extra))
            v.commit_compact()
            v.close()

        def recover(state_dir, _st, acked_payloads):
            acked: dict[int, bytes] = {}
            for p in acked_payloads:
                acked.update(p)
            verify_volume(
                state_dir, 1, acked, deleted={2},
                revisions=(old_rev, old_rev + 1),
            )

        return sweep(rec.trace, recover, workload="vacuum",
                     budget=budget, seed=seed)


def run_quarantine(budget: int | None = None,
                   seed: int | None = None) -> CrashReport:
    """Scrub quarantine: the `.bad` rename of a corrupt EC shard plus
    the scrub_state.json cursor publish. Invariants: the shard's bytes
    exist under exactly one of its two names and are unmodified (the
    rename moves, never rewrites — rebuild needs the forensic copy
    intact), and the state file is always a complete JSON document —
    old or new, never torn."""
    import json

    from seaweedfs_tpu.ec import ec_files
    from seaweedfs_tpu.scrub.state import ScrubState

    with tempfile.TemporaryDirectory() as d:
        shard_rel = "7" + ec_files.to_ext(3)
        shard_path = os.path.join(d, shard_rel)
        shard_bytes = bytes(range(256)) * 64
        with open(shard_path, "wb") as f:
            f.write(shard_bytes)
        state = ScrubState(path=os.path.join(d, "scrub_state.json"))
        h = state.get(7, True)
        h.cursor = 11
        state.save()
        rec = Recorder(d)
        with rec:
            # the quarantine rename exactly as EcVolume performs it
            # (shard object graph elided: the effect trace is the
            # rename + dir fsync, which is what the invariant audits)
            os.replace(shard_path, shard_path + ".bad")
            from seaweedfs_tpu.util import durable

            durable.fsync_dir(d)
            h.cursor = 999
            h.corruptions_found += 1
            state.save()

        def recover(state_dir, _st, _acked):
            good = os.path.join(state_dir, shard_rel)
            bad = good + ".bad"
            names = [p for p in (good, bad) if os.path.exists(p)]
            assert len(names) == 1, (
                f"shard exists under {len(names)} names (want exactly 1)"
            )
            with open(names[0], "rb") as f:
                assert f.read() == shard_bytes, "shard bytes changed"
            sp = os.path.join(state_dir, "scrub_state.json")
            if os.path.exists(sp):
                with open(sp) as f:
                    doc = json.load(f)  # torn JSON raises = violation
                cursors = {
                    row.get("cursor")
                    for row in doc.get("volumes", [])
                }
                assert cursors <= {11, 999}, f"hybrid cursor {cursors}"

        return sweep(rec.trace, recover, workload="quarantine",
                     budget=budget, seed=seed)


def run_handoff_hint(budget: int | None = None, seed: int | None = None,
                     durable: bool = True) -> CrashReport:
    """Hinted-handoff spool (weedguard, docs/HEALTH.md): the primary
    durably publishes a replica request as a hint BEFORE acking the
    client (server/handoff.HintStore.write_hint → util/durable), and a
    replay after crash must deliver the exact bytes. Invariants per
    crash state: once the hint write is acked, EXACTLY one complete
    hint exists and parses back byte-identical (acked-with-hint is a
    durability promise — losing or tearing it loses an acked write);
    before the ack, any *.hint under the final name must still be
    complete (rename only ever publishes fsynced bytes). A delivered
    hint (post-unlink + dirsync mark) must stay gone — a resurrected
    hint is the double-apply shape.

    `durable=False` replays the BUG ordering (plain write + rename, no
    fsyncs) as the positive control: the enumerator must surface
    rename-before-data states where the published hint is torn."""
    import struct as _struct

    body = bytes(range(256)) * 40 + b"\x00tail"
    target = "127.0.0.1:18080"
    path = "/3,0203fbfb?type=replicate"
    headers = {"Content-Type": "application/octet-stream"}

    with tempfile.TemporaryDirectory() as d:
        rec = Recorder(d)
        with rec:
            if durable:
                from seaweedfs_tpu.server.handoff import HintStore

                hs = HintStore(os.path.join(d, "spool"))
                assert hs.write_hint(target, "POST", path, body, headers)
            else:
                # the planted bug: same wire format, no fsync before
                # the rename, no dirsync after
                import json as _json

                tdir = os.path.join(d, "spool", "127.0.0.1_18080")
                os.makedirs(tdir, exist_ok=True)
                head = _json.dumps(
                    {"target": target, "method": "POST", "path": path,
                     "headers": headers}
                ).encode()
                tmp = os.path.join(tdir, "0000000000001-000001.hint.tmp")
                with open(tmp, "wb") as f:
                    f.write(_struct.pack(">I", len(head)))
                    f.write(head)
                    f.write(body)
                os.replace(tmp, tmp[: -len(".tmp")])
            rec.mark({"hint": True})

        def recover(state_dir, _st, acked_payloads):
            from seaweedfs_tpu.server.handoff import HintStore

            hs = HintStore(os.path.join(state_dir, "spool"))
            hints = []
            for _t, tdir in hs.targets():
                for e in sorted(os.scandir(tdir), key=lambda e: e.name):
                    if e.name.endswith(".hint"):
                        hints.append(e.path)
            if acked_payloads:
                assert len(hints) == 1, (
                    f"acked hint missing/duplicated: {len(hints)} found"
                )
            for hp in hints:
                parsed = hs.read_hint(hp)
                assert parsed is not None, f"torn hint published: {hp}"
                head, got = parsed
                assert got == body, (
                    f"hint body corrupt: {len(got)}B != {len(body)}B"
                )
                assert head["target"] == target and head["path"] == path

        return sweep(rec.trace, recover, workload="handoff-hint",
                     budget=budget, seed=seed)


def run_handoff_delivery(budget: int | None = None,
                         seed: int | None = None) -> CrashReport:
    """The other half of the hint lifecycle: after the agent delivers a
    hint it unlinks the file and fsyncs the spool dir — a crash then
    must never resurrect the hint (a revived hint replays a write the
    replica already applied: the double-apply shape; harmless for
    byte-identical needles but the contract is audited anyway)."""
    body = b"delivered-hint" * 64
    with tempfile.TemporaryDirectory() as d:
        from seaweedfs_tpu.server.handoff import HintStore

        hs = HintStore(os.path.join(d, "spool"))
        assert hs.write_hint(
            "127.0.0.1:18081", "POST", "/4,01aa?type=replicate", body, {}
        )
        (tgt, tdir), = hs.targets()
        (name,) = [
            e.name for e in os.scandir(tdir) if e.name.endswith(".hint")
        ]
        rec = Recorder(d)
        with rec:
            hs2 = HintStore(os.path.join(d, "spool"))
            hs2.remove(os.path.join(tdir, name))
            rec.mark({"delivered": True})

        def recover(state_dir, _st, acked_payloads):
            hp = os.path.join(
                state_dir, "spool", "127.0.0.1_18081", name
            )
            if acked_payloads:
                assert not os.path.exists(hp), (
                    "delivered hint resurrected after crash"
                )

        return sweep(rec.trace, recover, workload="handoff-delivery",
                     budget=budget, seed=seed)


def run_broken_publish(budget: int | None = None,
                       seed: int | None = None) -> CrashReport:
    """Positive control (the planted bug bench --check must DETECT on
    every run): a tmp+rename publish with NO fsync of the bytes. The
    enumerator must produce at least one legal state where the rename
    landed but the data did not — an empty/torn file under the final
    name."""
    import json

    with tempfile.TemporaryDirectory() as d:
        final = os.path.join(d, "state.json")
        with open(final, "w") as f:
            json.dump({"gen": 1}, f)
        rec = Recorder(d)
        with rec:
            tmp = final + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"gen": 2, "pad": "x" * 64}, f)
            os.replace(tmp, final)  # the bug: no fsync before, no dirsync after

        def recover(state_dir, _st, _acked):
            with open(os.path.join(state_dir, "state.json")) as f:
                doc = json.load(f)
            assert doc.get("gen") in (1, 2), f"hybrid doc {doc}"

        return sweep(rec.trace, recover, workload="broken-publish",
                     budget=budget, seed=seed)


def run_ec_encode(budget: int | None = None, seed: int | None = None,
                  durable: bool = True) -> CrashReport:
    """EC shard writer-pool flush ordering (the PR-11 follow-on): sweep
    stream_write_ec_files — the pooled preallocate+pwritev driver — plus
    the .ecx publish that acks the encode. Invariant: whenever the .ecx
    exists complete under its final name, every shard file byte-equals
    a control encode (the generate verb's contract: a visible index
    never fronts page-cache-only shard bytes).

    durable=False replays the PRE-FIX ordering (no shard fsyncs, .ecx
    written in place) — the regression control that must DETECT the
    complete-index-over-torn-shards states."""
    import shutil as _shutil

    from seaweedfs_tpu.ec import ec_files, ec_stream
    from seaweedfs_tpu.ec.codec import new_encoder
    from seaweedfs_tpu.storage.volume import Volume

    # tiny block geometry keeps shard files (and every materialized
    # state) a few KB; .ecx content only depends on the .idx
    blocks = {"large_block_size": 8192, "small_block_size": 4096}
    with tempfile.TemporaryDirectory() as d:
        v = Volume(d, 1)
        for nid in range(1, 4):
            v.write_needle(_mk_needle(nid, b"ec-%03d\xee" % nid * 30))
        v.commit()
        v.close()
        base = os.path.join(d, "1")
        rs = new_encoder(backend="cpu")
        parity_fn, fetch_fn = ec_stream.local_encode_fns(rs)

        def encode(target_base: str, durable_arm: bool) -> None:
            ec_stream.stream_write_ec_files(
                target_base, tile_bytes=4096, parity_fn=parity_fn,
                fetch_fn=fetch_fn, writer_threads=2, reader_threads=1,
                durable=durable_arm, **blocks,
            )
            ec_files.write_sorted_file_from_idx(
                target_base, durable=durable_arm
            )

        # control: the byte-exact expected outputs, encoded outside the
        # recorder from a copy of the same .dat/.idx
        ctl = os.path.join(d, "ctl")
        os.makedirs(ctl)
        for ext in (".dat", ".idx"):
            _shutil.copy(base + ext, os.path.join(ctl, "1" + ext))
        encode(os.path.join(ctl, "1"), durable_arm=True)
        expect = {}
        for i in range(ec_files.TOTAL_SHARDS):
            with open(os.path.join(ctl, "1" + ec_files.to_ext(i)), "rb") as f:
                expect[ec_files.to_ext(i)[1:]] = f.read()
        with open(os.path.join(ctl, "1.ecx"), "rb") as f:
            expect["ecx"] = f.read()
        _shutil.rmtree(ctl)

        rec = Recorder(d)
        with rec:
            encode(base, durable_arm=durable)
            rec.mark("encoded")

        def recover(state_dir, _st, _acked):
            ecx = os.path.join(state_dir, "1.ecx")
            if not os.path.exists(ecx):
                return  # encode never acked: nothing is promised
            with open(ecx, "rb") as f:
                got = f.read()
            assert got == expect["ecx"], (
                f".ecx visible but torn: {len(got)}B of "
                f"{len(expect['ecx'])}B"
            )
            for i in range(ec_files.TOTAL_SHARDS):
                ext = ec_files.to_ext(i)
                p = os.path.join(state_dir, "1" + ext)
                assert os.path.exists(p), f".ecx complete but {ext} missing"
                with open(p, "rb") as f:
                    shard = f.read()
                assert shard == expect[ext[1:]], (
                    f".ecx complete but {ext} bytes wrong "
                    f"({len(shard)}B, want {len(expect[ext[1:]])}B)"
                )

        return sweep(rec.trace, recover, workload="ec-encode",
                     budget=budget, seed=seed)


def run_ecc_publish(budget: int | None = None, seed: int | None = None,
                    durable: bool = True) -> CrashReport:
    """`.ecc` scrub-sidecar publish ordering (ec/ecc_sidecar.py): the
    sidecar ATTESTS shard bytes, so it must never reach its final name
    before those bytes are durable. Sweep: write 14 shard files, fsync
    them, publish the sidecar through util/durable.publish. Invariant:
    whenever a parseable sidecar exists under its final name, every
    shard it attests exists with exactly the attested size and
    CRC-32C — a crash can leave NO sidecar (scrub takes the parity
    path, fine) or a torn one (load fails, parity path, fine), but
    never a confident sidecar over lost shard bytes.

    durable=False replays the planted ordering bug — shard fsyncs
    skipped, sidecar still published durably — which the sweep must
    DETECT: the durable-only reorder state has the fsynced sidecar
    complete and visible over empty shard files."""
    from seaweedfs_tpu.ec import ec_files, ecc_sidecar
    from seaweedfs_tpu.util import durable as _durable
    from seaweedfs_tpu.util.crc import crc32c

    with tempfile.TemporaryDirectory() as d:
        base = os.path.join(d, "1")
        rec = Recorder(d)
        with rec:
            crcs = []
            for sid in range(ec_files.TOTAL_SHARDS):
                data = bytes([0x40 + sid]) * (512 + 64 * sid)
                with open(base + ec_files.to_ext(sid), "wb") as f:
                    f.write(data)
                crcs.append(crc32c(data))
            if durable:
                # the ordering under test: shard bytes durable BEFORE
                # the sidecar that vouches for them becomes visible
                for sid in range(ec_files.TOTAL_SHARDS):
                    _durable.fsync_path(base + ec_files.to_ext(sid))
            ecc_sidecar.write_sidecar(
                base, crcs, total_shards=ec_files.TOTAL_SHARDS
            )
            rec.mark("published")

        def recover(state_dir, _st, _acked):
            b = os.path.join(state_dir, "1")
            doc = ecc_sidecar.load_sidecar(b)
            if doc is None:
                return  # absent/torn sidecar: the parity path covers it
            for sid_s, ent in doc["shards"].items():
                p = b + ec_files.to_ext(int(sid_s))
                assert os.path.exists(p), (
                    f"sidecar attests shard {sid_s} that does not exist"
                )
                with open(p, "rb") as f:
                    got = f.read()
                assert len(got) == ent["size"], (
                    f"sidecar attests shard {sid_s} at {ent['size']}B "
                    f"but {len(got)}B are on disk"
                )
                assert crc32c(got) == ent["crc"], (
                    f"sidecar CRC mismatch on shard {sid_s}: the "
                    f"sidecar outlived the bytes it attests"
                )

        return sweep(rec.trace, recover, workload="ecc-publish",
                     budget=budget, seed=seed)


def run_shard_handback(budget: int | None = None,
                       seed: int | None = None) -> CrashReport:
    """-shardWrites ownership handback (the PR-11 follow-on): a worker
    OWNS a vid's writes (SharedReadVolume appends through the same
    Volume write path as the lead), releases ownership, and the lead
    appends more and commits. Sweeps the combined append stream.
    Invariants: every needle acked at the final durability point
    survives recovery (the lead's commit fsyncs the .dat; repair-mode
    open re-indexes fsynced-but-unindexed records), the .idx never
    references past the .dat, torn tails never surface as valid."""
    from seaweedfs_tpu.server.volume_workers import SharedReadVolume
    from seaweedfs_tpu.storage.volume import Volume

    with tempfile.TemporaryDirectory() as d:
        v = Volume(d, 1)
        base = {i: b"lead-%03d\xaa" % i * 40 for i in range(1, 4)}
        for nid, data in base.items():
            v.write_needle(_mk_needle(nid, data))
        v.commit()
        v.close()
        rec = Recorder(d)
        rec.mark(dict(base))
        with rec:
            # worker-owned phase: appends ride the shared wrapper the
            # -shardWrites read workers use for owned vids
            w = SharedReadVolume(d, 1)
            worker_writes = {i: b"wrk-%03d\x00\xfe" % i * 50
                             for i in range(10, 14)}
            for nid, data in worker_writes.items():
                w.write_needle(_mk_needle(nid, data))
            # handback: worker stops writing forever; the lead reopens,
            # catches up from the on-disk .idx, appends, and COMMITS —
            # the durability point the final ack rides
            lead = Volume(d, 1, create=False)
            lead_writes = {i: b"ld2-%03d\xbb" % i * 45
                           for i in range(20, 23)}
            for nid, data in lead_writes.items():
                lead.write_needle(_mk_needle(nid, data))
            lead.commit()
            rec.mark({**worker_writes, **lead_writes})
            lead.close()
            w.close()

        def recover(state_dir, _st, acked_payloads):
            acked: dict[int, bytes] = {}
            for p in acked_payloads:
                acked.update(p)
            verify_volume(state_dir, 1, acked)

        return sweep(rec.trace, recover, workload="shard-handback",
                     budget=budget, seed=seed)


ALL_WORKLOADS = {
    "group-commit": run_group_commit,
    "vacuum": run_vacuum,
    "quarantine": run_quarantine,
    "ec-encode": run_ec_encode,
    "ecc-publish": run_ecc_publish,
    "shard-handback": run_shard_handback,
    "handoff-hint": run_handoff_hint,
    "handoff-delivery": run_handoff_delivery,
}


def run_all(budget: int | None = None, seed: int | None = None
            ) -> list[CrashReport]:
    return [fn(budget=budget, seed=seed) for fn in ALL_WORKLOADS.values()]
