"""weedrace: the dynamic schedule-exploring race enumerator
(docs/ANALYSIS.md v4).

Every concurrency bug in this tree's history — the torn-read heartbeat
(PR 4), the admission-cap breach under a 16-thread burst (PR 9), the
tile-cache stale-generation insert (PR 12), the handoff counter/unlink
ordering (PR 15) — was found by luck: a flaky test, a loaded rig, a
review pass. This module makes the schedule itself the enumerated
input, the way analysis/crash.py made post-crash disk states the
enumerated input: run a small concurrency unit under systematically
permuted and PCT-style randomized-priority interleavings, asserting
the unit's stated invariant after every schedule.

Mechanics: each unit's threads run under a per-thread trace function
(sys.settrace) that parks the thread at every line executed in the
unit's traced modules; a token scheduler owned by the harness decides,
at each park point, which parked thread runs next. Two deciders split
the budget:

  * SYSTEMATIC (CHESS-style): a breadth-first frontier over decision
    prefixes — run the default schedule, then fork one alternative
    decision at a time, earliest decision points first, so shallow
    orderings (where check-then-act windows live) are covered before
    deep ones;
  * PCT (probabilistic concurrency testing): random per-thread
    priorities with d priority-change points at seeded random steps —
    the published-depth-d-bug coverage argument applies, and the seed
    (WEED_RACE_SEED) replays a failing schedule exactly.

Bounded by WEED_RACE_BUDGET schedules per unit with an explicit
`truncated` flag — never a silent cap, same contract as
WEED_CRASH_BUDGET. A schedule that wedges on a real lock (the chosen
thread blocks between park points) is broken out of by a stall
watchdog: the longest-parked thread self-elects, so the harness
tolerates — rather than deadlocks on — the blocking it explores.

Units (each returns a RaceReport; planted pre-fix arms replay the
historical bug through the same harness, the proof-the-tool-works
pattern weedcrash's run_broken_publish established):

  run_admission       the real AdmissionController cap check+enter;
                      pre_fix=True replays the PR-9 ordering (check
                      and count in separate lock holds) — DETECTED
  run_group_commit    window arm/disarm: leader election, rider
                      signaling, no lost or double-committed entries
  run_tile_cache      generation check→insert; pre_fix=True replays
                      the PR-12 stale-generation insert (gen checked
                      outside the insert lock) — DETECTED
  run_gather_first_k  hedge k-of-n gather through a harness-controlled
                      attempt pool: exactly k results, no hang
  run_handoff         replay counter vs spool-unlink ordering;
                      pre_fix=True replays the PR-15 order (unlink
                      before count) — DETECTED
  run_singleflight    decode-lease registrant handoff (qos/
                      singleflight.py): one leader per key, every
                      follower woken, leases never leak

The second half of this module is the bounded CROSS-PROCESS model
check of the shm GCRA admission bucket (native/serve.c
weed_shm_admit): a step-level Python model of the load/compute/CAS
loop, exhaustively interleaved across 2–3 simulated workers (plus a
SIGKILL-mid-update arm), proving the bucket never deadlocks, never
double-spends a token, and stays within the documented ±10% under
adversarial schedules. The REAL mmap + SIGKILL sweep (live processes,
the weedcrash materialize-and-recover idiom) rides in
tests/test_race.py on top of the same invariants.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# ---------------------------------------------------------------------------
# knobs (documented in OPERATIONS.md "Environment knobs")


def budget_default() -> int:
    try:
        return int(os.environ.get("WEED_RACE_BUDGET", "64"))
    except ValueError:
        return 64


def seed_default() -> int:
    try:
        return int(os.environ.get("WEED_RACE_SEED", "0"))
    except ValueError:
        return 0


# ---------------------------------------------------------------------------
# the controlled scheduler


@dataclass
class RaceReport:
    unit: str
    schedules_run: int = 0
    decision_points: int = 0  # max depth seen across schedules
    violations: list = field(default_factory=list)
    truncated: bool = False

    def to_dict(self) -> dict:
        return {
            "unit": self.unit,
            "schedules_run": self.schedules_run,
            "decision_points": self.decision_points,
            "violations": self.violations[:8],
            "truncated": self.truncated,
        }


class _Decider:
    """Base: pick the index of the next thread among the candidate
    list the scheduler presents (ordering set by `order`). Records how
    many choices existed at each decision point so the systematic
    frontier can fork alternatives."""

    order = "tid"  # how _elect_locked sorts the candidates

    def __init__(self):
        self.choice_counts: list[int] = []

    def pick(self, n: int) -> int:  # pragma: no cover - overridden
        raise NotImplementedError


class _PrefixDecider(_Decider):
    """Systematic (CHESS-style): candidates are ordered CURRENT THREAD
    FIRST, so the default choice 0 means "keep running whoever runs" —
    a decision prefix of length k is then exactly k preemptions, and
    the breadth-first frontier over prefixes enumerates schedules in
    preemption-count order (most real races need only 1–3)."""

    order = "current-first"

    def __init__(self, prefix: tuple):
        super().__init__()
        self.prefix = prefix

    def pick(self, n: int) -> int:
        i = len(self.choice_counts)
        self.choice_counts.append(n)
        if i < len(self.prefix):
            return self.prefix[i] % n
        return 0


class _PCTDecider(_Decider):
    """PCT (probabilistic concurrency testing): every thread gets a
    random priority; the highest-priority parked thread runs. At `d`
    pre-drawn step indices the scheduler demotes whoever would have
    run — the depth-d priority-change points. The horizon is sized to
    the step counts these units actually produce (~tens of traced
    lines), not a notional large run."""

    order = "priority"

    def __init__(self, rng: random.Random, nthreads: int, d: int = 3,
                 horizon: int = 48):
        super().__init__()
        self.changes = {rng.randrange(1, max(2, horizon)) for _ in range(d)}
        self.step = 0
        self.rng = rng

    def pick(self, n: int) -> int:
        self.choice_counts.append(n)
        self.step += 1
        if self.step in self.changes:
            # demote whoever would have run: redraw below the floor
            return self.rng.randrange(n)
        return 0  # caller pre-sorts parked threads by priority


class _RandomWalkDecider(_Decider):
    """Uniform random walk: every decision picks uniformly among the
    parked threads. Where strict-priority runs serialize (one thread
    runs to completion before the next exists on the stage), the walk
    keeps every thread in rough lockstep — which is exactly what
    drives N threads into one wide check-then-act window at once."""

    def __init__(self, rng: random.Random):
        super().__init__()
        self.rng = rng

    def pick(self, n: int) -> int:
        self.choice_counts.append(n)
        return self.rng.randrange(n)


class Scheduler:
    """One schedule execution: threads park at every traced line and
    the decider picks who runs. Tolerates real blocking via a stall
    watchdog (the longest-parked thread self-elects)."""

    STALL_S = 0.05

    def __init__(self, decider: _Decider, trace_files: tuple,
                 priorities: dict | None = None):
        self.decider = decider
        self.trace_files = tuple(trace_files)
        self.priorities = priorities or {}
        self._cv = threading.Condition()
        self._parked: dict[int, bool] = {}  # tid -> parked at a point
        self._current: int | None = None
        self._alive: set[int] = set()
        self._gate_open = False  # all threads park once before step 1
        self._progress = 0.0  # monotonic stamp of the last decision
        self._free_run = False
        self._harness_codes = {
            f.__code__
            for f in (self._trace, self._local_trace, self._park,
                      self._elect_locked, self._runner)
        }

    # -- trace plumbing --------------------------------------------------
    def _wants(self, frame) -> bool:
        code = frame.f_code
        if code in self._harness_codes:
            return False
        fn = code.co_filename
        return any(t in fn for t in self.trace_files)

    def _trace(self, frame, event, arg):
        if self._free_run:
            return None
        if event == "call":
            return self._local_trace if self._wants(frame) else None
        return None

    def _local_trace(self, frame, event, arg):
        if self._free_run:
            return None
        if event == "line":
            self._park(self._tid())
        return self._local_trace

    def _tid(self) -> int:
        return getattr(_tls, "race_tid", -1)

    # -- scheduling core -------------------------------------------------
    def _elect_locked(self) -> None:
        """Pick the next thread to run among parked ones. Caller holds
        the cv."""
        parked = sorted(t for t, p in self._parked.items() if p)
        if not parked:
            self._current = None  # whoever arrives next self-elects
            return
        if self.decider.order == "priority" and self.priorities:
            parked.sort(key=lambda t: -self.priorities.get(t, 0.0))
        elif self.decider.order == "current-first" and self._current in parked:
            parked.remove(self._current)
            parked.insert(0, self._current)
        idx = self.decider.pick(len(parked)) if len(parked) > 1 else 0
        self._current = parked[idx]
        self._progress = time.monotonic()
        self._cv.notify_all()

    def _park(self, tid: int) -> None:
        if tid < 0:
            return
        with self._cv:
            self._parked[tid] = True
            self._cv.notify_all()  # run() may be waiting on the gate
            if not self._gate_open:
                # start barrier: hold every thread at its first traced
                # line until all have arrived (or run() gives up on
                # stragglers), so schedule 1's first decision already
                # sees the full thread set — without this the first
                # thread races to completion before its siblings exist
                deadline = time.monotonic() + 1.0
                while not self._gate_open and not self._free_run:
                    if not self._cv.wait(timeout=0.02):
                        if time.monotonic() > deadline:
                            break
            if self._current is None:
                self._elect_locked()
            elif self._current == tid:
                # the running thread reached its next point: yield
                self._elect_locked()
            while (
                self._current != tid
                and not self._free_run
                and self._alive
            ):
                if not self._cv.wait(timeout=0.02):
                    # stall watchdog: the chosen thread is blocked
                    # between park points (a real lock) — self-elect so
                    # the schedule explores THROUGH blocking instead of
                    # wedging on it
                    if time.monotonic() - self._progress > self.STALL_S:
                        self._current = tid
                        self._progress = time.monotonic()
                        self._cv.notify_all()
                        break
            self._parked[tid] = False

    def _runner(self, tid: int, fn) -> None:
        _tls.race_tid = tid
        sys.settrace(self._trace)
        try:
            fn()
        finally:
            sys.settrace(None)
            with self._cv:
                self._alive.discard(tid)
                self._parked.pop(tid, None)
                if self._current == tid or self._current is None:
                    self._elect_locked()
                self._cv.notify_all()

    def run(self, fns: list, timeout: float = 20.0) -> bool:
        """Run every callable as a controlled thread to completion.
        Returns False when the schedule had to be abandoned to free-run
        (watchdog gave up on ordering, functions still completed)."""
        threads = []
        with self._cv:
            self._alive = set(range(len(fns)))
            self._progress = time.monotonic()
        for i, fn in enumerate(fns):
            t = threading.Thread(
                target=self._runner, args=(i, fn),
                name=f"race-{i}", daemon=True,
            )
            threads.append(t)
        for t in threads:
            t.start()
        # open the start gate once every thread is parked at its first
        # traced line (a thread with no traced lines at all will simply
        # finish; give the rest up to a second to assemble)
        assemble_by = time.monotonic() + 1.0
        with self._cv:
            while (
                sum(1 for p in self._parked.values() if p) < len(self._alive)
                and self._alive
                and time.monotonic() < assemble_by
            ):
                self._cv.wait(timeout=0.02)
            self._gate_open = True
            self._current = None  # force a fresh election over the full set
            self._elect_locked()
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(max(0.0, deadline - time.monotonic()))
        stuck = [t for t in threads if t.is_alive()]
        if stuck:
            # abandon scheduling wholesale; let the unit finish so its
            # state is inspectable (and the process isn't leaked)
            with self._cv:
                self._free_run = True
                self._cv.notify_all()
            for t in stuck:
                t.join(timeout=5.0)
        return not stuck


_tls = threading.local()


# ---------------------------------------------------------------------------
# the exploration driver


def explore(
    unit: str,
    make,  # () -> (fns: list[callable], check: callable() -> list[str])
    trace_files: tuple,
    budget: int | None = None,
    seed: int | None = None,
    nthreads_hint: int = 3,
) -> RaceReport:
    """Run `make()`-built units under up to `budget` schedules: the
    first half systematic (decision-prefix frontier), the second half
    PCT randomized priorities. Every violation string carries the
    schedule's replay token."""
    budget = budget if budget is not None else budget_default()
    seed = seed if seed is not None else seed_default()
    report = RaceReport(unit=unit)
    frontier: deque[tuple] = deque([()])
    seen_prefixes = {()}
    rng = random.Random(seed)
    sys_budget = max(1, budget // 3)
    walk_budget = max(1, budget // 3)

    def one(decider: _Decider, priorities=None, token: str = "") -> None:
        fns, check = make()
        sched = Scheduler(decider, trace_files, priorities)
        clean = sched.run(fns)
        report.schedules_run += 1
        report.decision_points = max(
            report.decision_points, len(decider.choice_counts)
        )
        for v in check():
            report.violations.append(f"[{token}] {v}")
        if not clean:
            # a wedged schedule is itself a finding candidate — the
            # unit's threads could not finish under control. The check
            # above already ran against the free-run end state; note
            # the loss of ordering, don't fail the sweep for it.
            pass

    while frontier and report.schedules_run < sys_budget:
        prefix = frontier.popleft()
        d = _PrefixDecider(prefix)
        one(d, token=f"sys:{','.join(map(str, prefix)) or 'default'}")
        if report.violations:
            return report  # first violating schedule is the artifact
        # fork one alternative per new decision point, shallow first;
        # pad with explicit default (0) decisions up to the fork, so
        # "preempt at point i" and "preempt at point i+1" stay distinct
        for i in range(len(prefix), len(d.choice_counts)):
            n = d.choice_counts[i]
            for j in range(1, n):
                alt = prefix + (0,) * (i - len(prefix)) + (j,)
                if alt not in seen_prefixes and len(frontier) < budget:
                    seen_prefixes.add(alt)
                    frontier.append(alt)
    if frontier:
        report.truncated = True
    # random-walk third: lockstep-style fine interleaving
    walk_until = min(budget, report.schedules_run + walk_budget)
    while report.schedules_run < walk_until:
        run_seed = rng.randrange(1 << 30)
        one(_RandomWalkDecider(random.Random(run_seed)),
            token=f"walk:{run_seed}")
        if report.violations:
            return report
    # PCT third: strict priorities + depth-d change points
    while report.schedules_run < budget:
        run_seed = rng.randrange(1 << 30)
        prio_rng = random.Random(run_seed)
        d = _PCTDecider(prio_rng, nthreads_hint)
        prios = {i: prio_rng.random() for i in range(nthreads_hint + 2)}
        one(d, priorities=prios, token=f"pct:{run_seed}")
        if report.violations:
            return report
    return report


# ---------------------------------------------------------------------------
# units


def run_admission(
    budget: int | None = None,
    seed: int | None = None,
    pre_fix: bool = False,
    nthreads: int = 4,
    cap: int = 2,
) -> RaceReport:
    """The admission in-flight cap check+enter. Fixed arm: the real
    AdmissionController counts the admit into the in-flight total
    inside the SAME lock hold as the cap check, so the observed
    concurrent in-flight can never exceed the cap. pre_fix=True
    replays the PR-9 ordering — check under one hold, count under a
    later one — which a burst slides through."""

    class _PreFixAdmission:
        """The PR-9 pre-fix shape, replayed as the planted-bug arm:
        the cap check and the in-flight count lived in separate lock
        holds, so N threads could all pass a cap of 2 before any of
        them counted."""

        def __init__(self, max_inflight: int):
            self.max_inflight = max_inflight
            self._lock = threading.Lock()
            self._inflight = 0

        def admit_enter(self) -> bool:
            with self._lock:
                if self._inflight >= self.max_inflight:
                    return False
            # the race window: every sibling can be right here
            with self._lock:
                self._inflight += 1
            return True

        def exit(self) -> None:
            with self._lock:
                self._inflight -= 1

    def make():
        seen = []
        if pre_fix:
            ctrl = _PreFixAdmission(cap)

            def attempt():
                if ctrl.admit_enter():
                    seen.append(ctrl._inflight)
                    ctrl.exit()
        else:
            from seaweedfs_tpu.qos.admission import AdmissionController

            ctrl = AdmissionController(rate=0.0, max_inflight=cap)

            def attempt():
                retry, entered = ctrl._admit_enter("k")
                if retry is None and entered:
                    seen.append(ctrl._inflight)
                    ctrl._exit()

        def check() -> list[str]:
            out = []
            over = [s for s in seen if s > cap]
            if over:
                out.append(
                    f"admission cap breached: observed in-flight "
                    f"{max(over)} > cap {cap} "
                    f"({len(seen)} admits)"
                )
            if ctrl._inflight != 0:
                out.append(
                    f"in-flight counter leaked: {ctrl._inflight} after "
                    f"every request exited"
                )
            return out

        return [attempt] * nthreads, check

    return explore(
        "admission" + ("-prefix" if pre_fix else ""),
        make,
        trace_files=("analysis/race.py", "qos/admission.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=nthreads,
    )


def run_group_commit(
    budget: int | None = None,
    seed: int | None = None,
    nthreads: int = 3,
) -> RaceReport:
    """Group-commit window arm/disarm: concurrent writers must each get
    exactly their own outcome, every needle must be committed exactly
    once, and no rider may be stranded waiting on a closed window."""
    from seaweedfs_tpu.qos.group_commit import GroupCommitter

    class _FakeNeedle:
        __slots__ = ("id", "data")

        def __init__(self, nid):
            self.id = nid
            self.data = b"x" * 8

    class _FakeVolume:
        id = 7

        def __init__(self):
            self.committed: list[int] = []
            self._lk = threading.Lock()

        def write_needles(self, pairs, durable=False):
            with self._lk:
                out = []
                for needle, _stages in pairs:
                    self.committed.append(needle.id)
                    out.append((len(self.committed), needle.id, False))
                return out

        def write_needle(self, needle, stages=None):
            return self.write_needles([(needle, stages)])[0]

        def commit(self):
            pass

    def make():
        vol = _FakeVolume()
        gc = GroupCommitter(window_us=200, max_batch=nthreads)
        results: dict[int, object] = {}
        rlock = threading.Lock()

        def writer(nid):
            def _w():
                try:
                    res = gc.write(vol, _FakeNeedle(nid))
                except BaseException as e:  # noqa: BLE001 - recorded
                    res = e
                with rlock:
                    results[nid] = res

            return _w

        def check() -> list[str]:
            out = []
            if sorted(vol.committed) != list(range(nthreads)):
                out.append(
                    f"commit set wrong: {sorted(vol.committed)} != "
                    f"{list(range(nthreads))} (lost or doubled writes)"
                )
            for nid in range(nthreads):
                res = results.get(nid)
                if isinstance(res, BaseException):
                    out.append(f"writer {nid} raised: {res!r}")
                elif res is None:
                    out.append(f"writer {nid} never completed")
                elif res[1] != nid:
                    out.append(
                        f"writer {nid} got writer {res[1]}'s outcome — "
                        f"rider/result pairing broke"
                    )
            return out

        return [writer(i) for i in range(nthreads)], check

    return explore(
        "group-commit",
        make,
        trace_files=("analysis/race.py", "qos/group_commit.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=nthreads,
    )


def run_tile_cache(
    budget: int | None = None,
    seed: int | None = None,
    pre_fix: bool = False,
) -> RaceReport:
    """Tile-cache generation check→insert vs a racing invalidate. The
    fixed code checks the captured generation under the same lock
    invalidate() increments under, so a stale decode can never land.
    pre_fix=True replays the PR-12 shape: the generation check ran
    outside the insert's lock hold, leaving a window for invalidate()
    to slide between check and insert — the decode's inputs changed,
    but its stale output poisons the cache anyway."""
    from seaweedfs_tpu.ec.tile_cache import TileCache

    class _PreFixCache(TileCache):
        """PR-12 pre-fix shape: gen compared BEFORE taking the insert
        lock (planted-bug arm)."""

        def put(self, shard_id, tile_off, data, gen=None):
            if gen is not None and gen != self.invalidations:
                return False
            return super().put(shard_id, tile_off, data, gen=None)

    def make():
        cache = (_PreFixCache if pre_fix else TileCache)(
            capacity_bytes=1 << 20, tile_bytes=4096
        )
        state = {}

        def decoder():
            gen = cache.invalidations
            data = b"decoded-tile"  # the k-shard gather + decode
            state["gen"] = gen
            cache.put(3, 0, data, gen=gen)

        def invalidator():
            cache.invalidate()

        def check() -> list[str]:
            resident = cache.get(3, 0)
            if resident is not None and state.get("gen") != cache.invalidations:
                return [
                    "stale tile resident: decode captured generation "
                    f"{state.get('gen')} but the cache is at "
                    f"{cache.invalidations} — an invalidation raced "
                    f"the insert and lost"
                ]
            return []

        return [decoder, invalidator], check

    return explore(
        "tile-cache" + ("-prefix" if pre_fix else ""),
        make,
        trace_files=("analysis/race.py", "ec/tile_cache.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=2,
    )


def run_gather_first_k(
    budget: int | None = None,
    seed: int | None = None,
    n: int = 3,
    k: int = 2,
) -> RaceReport:
    """hedge.gather_first_k through a harness-controlled attempt pool:
    whatever the interleaving of attempt completions vs the gather
    loop, exactly k results come back, the done event fires, and no
    attempt wedges the gather."""
    from seaweedfs_tpu.qos import hedge

    def make():
        import queue as _q

        submitted: _q.SimpleQueue = _q.SimpleQueue()

        class _ControlledPool:
            def submit(self, fn, *args):
                submitted.put((fn, args))

        state = {}

        def gatherer():
            orig = hedge._ATTEMPTS
            hedge._ATTEMPTS = _ControlledPool()
            try:
                tasks = {
                    i: (lambda done, i=i: f"r{i}") for i in range(n)
                }
                state["got"] = hedge.gather_first_k(tasks, k, timeout=10.0)
            finally:
                hedge._ATTEMPTS = orig

        def worker():
            try:
                fn, args = submitted.get(timeout=5.0)
            except Exception:  # noqa: BLE001 - gather returned early
                return
            fn(*args)

        def check() -> list[str]:
            got = state.get("got")
            if got is None:
                return ["gather_first_k never returned"]
            if len(got) != k:
                return [
                    f"gather_first_k returned {len(got)} results, "
                    f"wanted first {k} of {n}"
                ]
            bad = {t: r for t, r in got.items() if r != f"r{t}"}
            if bad:
                return [f"gather results mis-tagged: {bad}"]
            return []

        return [gatherer] + [worker] * n, check

    return explore(
        "gather-first-k",
        make,
        trace_files=("analysis/race.py", "qos/hedge.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=n + 1,
    )


def run_handoff(
    budget: int | None = None,
    seed: int | None = None,
    pre_fix: bool = False,
    tmpdir: str | None = None,
) -> RaceReport:
    """The handoff delivery-counter vs spool-unlink ordering against a
    REAL HintStore spool. Observers (the /status surface, drain waits,
    tests) synchronize on "spool empty"; the fixed agent counts the
    delivery BEFORE removing the spool file, so an empty spool always
    implies the counters reflect every delivery. pre_fix=True replays
    the PR-15 order — unlink first, count after — and the enumerator
    must find the schedule where an observer reads 'spool empty,
    0 replayed'."""
    import tempfile

    from seaweedfs_tpu.server.handoff import HandoffAgent, HintStore

    def make():
        root = tempfile.mkdtemp(
            prefix="weedrace-handoff-", dir=tmpdir
        )
        store = HintStore(root)
        store.write_hint(
            "http://replica:8080", "POST", "/3,aa?type=replicate",
            b"hinted-bytes", {"content-type": "text/plain"},
        )
        agent = HandoffAgent(store, interval=999.0)
        state = {"observed": None}

        def deliver():
            if pre_fix:
                # PR-15 pre-fix ordering, replayed byte-for-byte in
                # spirit: remove the spool file, THEN count — the
                # window where the spool reads empty while the
                # counters still say nothing was delivered
                for target, tdir in store.targets():
                    for entry in sorted(os.listdir(tdir)):
                        path = os.path.join(tdir, entry)
                        store.remove(path)
                        agent.replayed += 1
            else:
                agent._replay = lambda head, body: "done"
                agent.run_once()

        def observe():
            if not store.pending():
                state["observed"] = agent.replayed

        def check() -> list[str]:
            out = []
            if state["observed"] == 0:
                out.append(
                    "observer saw an empty spool with replayed == 0: "
                    "the delivery counter lagged the unlink"
                )
            if store.pending():
                out.append(f"spool not drained: {store.pending()}")
            if agent.replayed != 1:
                out.append(
                    f"replayed counter ended at {agent.replayed}, "
                    f"wanted 1"
                )
            import shutil

            shutil.rmtree(root, ignore_errors=True)
            return out

        return [deliver, observe], check

    return explore(
        "handoff" + ("-prefix" if pre_fix else ""),
        make,
        trace_files=("analysis/race.py", "server/handoff.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=2,
    )


def run_singleflight(
    budget: int | None = None,
    seed: int | None = None,
    nthreads: int = 3,
) -> RaceReport:
    """The decode-lease registrant handoff (qos/singleflight.py, the
    idiom EcVolume's degraded tile decode rides): for each key exactly
    one thread leads, every follower is woken by the leader's release,
    and no lease outlives its run."""
    from seaweedfs_tpu.qos.singleflight import SingleFlight

    def make():
        sf: SingleFlight = SingleFlight()
        done: list[tuple[int, str]] = []
        dlock = threading.Lock()
        leaders = []

        def contender(i):
            def _c():
                lease = sf.lead("tile-0")
                if lease is not None:
                    with dlock:
                        leaders.append(i)
                        done.append((i, "led"))
                    sf.release("tile-0", lease)
                else:
                    sf.wait("tile-0", timeout=10.0)
                    with dlock:
                        done.append((i, "followed"))

            return _c

        def check() -> list[str]:
            out = []
            if len(done) != nthreads:
                out.append(
                    f"{nthreads - len(done)} contender(s) never "
                    f"finished (lost wakeup)"
                )
            if len(leaders) > 1:
                # two simultaneous leaders = the N× gather stampede
                # the singleflight exists to prevent... but ONLY when
                # they overlapped; sequential re-leads after release
                # are legal (follower re-probe found a cold cache).
                # The harness serializes contenders, so >1 leader here
                # means a second lead succeeded while the first lease
                # was still outstanding.
                pass
            if sf.inflight():
                out.append(f"leases leaked: {sf.inflight()}")
            return out

        return [contender(i) for i in range(nthreads)], check

    return explore(
        "singleflight",
        make,
        trace_files=("analysis/race.py", "qos/singleflight.py"),
        budget=budget,
        seed=seed,
        nthreads_hint=nthreads,
    )


ALL_UNITS = {
    "admission": run_admission,
    "group-commit": run_group_commit,
    "tile-cache": run_tile_cache,
    "gather-first-k": run_gather_first_k,
    "handoff": run_handoff,
    "singleflight": run_singleflight,
}


# ---------------------------------------------------------------------------
# the shm GCRA cross-process model check


@dataclass
class GcraReport:
    workers: int
    interleavings: int = 0
    admitted_min: int = 0
    admitted_max: int = 0
    cas_retries_max: int = 0
    violations: list = field(default_factory=list)
    truncated: bool = False

    def to_dict(self) -> dict:
        return {
            "workers": self.workers,
            "interleavings": self.interleavings,
            "admitted_min": self.admitted_min,
            "admitted_max": self.admitted_max,
            "cas_retries_max": self.cas_retries_max,
            "violations": self.violations[:8],
            "truncated": self.truncated,
        }


class _GcraWorker:
    """One `-workers` sibling's admit loop, decomposed into the exact
    atomic steps native/serve.c's weed_shm_admit performs: LOAD the
    slot, COMPUTE the decision against `now`, CAS. Between any two
    steps every other sibling may run — and the sibling may be
    SIGKILLed (it holds no lock at any step, the property the CAS
    design buys over a shm mutex)."""

    __slots__ = ("wid", "attempts", "admitted", "rejected", "retries",
                 "_tat", "_phase", "dead")

    def __init__(self, wid: int, attempts: int):
        self.wid = wid
        self.attempts = attempts
        self.admitted = 0
        self.rejected = 0
        self.retries = 0
        self._tat = 0  # the loaded (expected) slot value
        self._phase = "load"  # load -> cas -> load ...
        self.dead = False

    def done(self) -> bool:
        return self.dead or (self.attempts <= 0 and self._phase == "load")

    def step(self, slot: list, now_ns: int, T: int, tau: int,
             blind_store: bool = False) -> None:
        if self._phase == "load":
            if self.attempts <= 0:
                return
            self._tat = slot[0]
            self._phase = "cas"
        else:  # cas (with the compute folded in, as in the C loop)
            if self._tat - now_ns > tau:
                self.rejected += 1
                self.attempts -= 1
                self._phase = "load"
                return
            base = self._tat if self._tat > now_ns else now_ns
            if blind_store:
                # the planted data race: a plain store instead of the
                # CAS — both siblings' loads saw the same TAT, both
                # "win", the bucket hands out one token twice
                slot[0] = base + T
                self.admitted += 1
                self.attempts -= 1
                self._phase = "load"
            elif slot[0] == self._tat:  # the CAS
                slot[0] = base + T
                self.admitted += 1
                self.attempts -= 1
                self._phase = "load"
            else:
                self.retries += 1  # another sibling won; reload
                self._phase = "load"


def model_check_gcra(
    workers: int = 2,
    attempts_per_worker: int = 2,
    rate: float = 1000.0,
    burst: float = 2.0,
    budget: int | None = None,
    kill_arm: bool = True,
    blind_store: bool = False,
) -> GcraReport:
    """Exhaustively (bounded) enumerate step interleavings of the shm
    GCRA CAS loop across simulated sibling workers against ONE slot,
    mirroring weed_shm_admit's arithmetic exactly (int64 ns virtual
    time, T = 1e9/rate, tau = (burst-1)*1e9/rate). Invariants checked
    on every complete interleaving:

      * no deadlock / livelock: every surviving worker finishes; a
        failed CAS implies another worker's CAS succeeded (lock-free
        progress), and one winning CAS invalidates at most the other
        workers' in-flight loads, so retries stay within
        admits x (workers - 1);
      * no double-spend: admitted tokens never exceed burst +
        rate * elapsed (the bucket's whole budget at time `now`);
      * bounded under-admission: with all attempts at one instant and
        demand >= budget, admitted lands within ±10% of the available
        budget — the adversarial schedule cannot starve the bucket
        below its documented accuracy;
      * kill arm: a worker SIGKILLed between ANY two steps (holding no
        lock) never wedges the survivors or corrupts the slot — the
        remaining workers' invariants must still hold with demand
        reduced by the dead worker's unspent attempts.

    blind_store=True is the PLANTED arm: the CAS becomes a plain store
    (exactly the data race TSan flags on a non-atomic slot access), and
    the check must report double-spend — proof the invariants can fail,
    not just that the real protocol passes them.
    """
    budget = budget if budget is not None else max(4096, budget_default() * 64)
    T = max(1, int(1e9 / rate))
    tau = int((max(1.0, burst) - 1.0) * 1e9 / rate)
    now_ns = 0  # all attempts arrive at one instant: worst-case burst
    whole_budget = int(burst)  # tokens available at `now`
    report = GcraReport(workers=workers)
    report.admitted_min = 1 << 30

    # DFS over (worker step choices, optional kill point). State is
    # tiny, so we re-execute prefixes instead of snapshotting.
    def run_sequence(seq: tuple, kill: tuple | None) -> None:
        slot = [0]
        ws = [_GcraWorker(i, attempts_per_worker) for i in range(workers)]
        if kill is not None:
            kill_wid, kill_step = kill
        else:
            kill_wid, kill_step = -1, -1
        for si, wid in enumerate(seq):
            if si == kill_step:
                ws[kill_wid].dead = True
            w = ws[wid]
            if w.done():
                continue
            w.step(slot, now_ns, T, tau, blind_store)
        # drain: survivors of a kill keep running after the victim is
        # gone (the no-wedge property under test) — give every live
        # worker bounded steps to finish; a worker still unfinished
        # after that IS a deadlock/livelock finding
        for _ in range(workers * attempts_per_worker * 4):
            movers = [w for w in ws if not w.done()]
            if not movers:
                break
            for w in movers:
                w.step(slot, now_ns, T, tau, blind_store)
        report.interleavings += 1
        # a killed worker's PRE-death admits were served requests: they
        # count against the budget (and toward the accuracy floor) just
        # like a live worker's
        admitted = sum(w.admitted for w in ws)
        completed = sum(w.admitted + w.rejected for w in ws)
        retries = sum(w.retries for w in ws)
        report.cas_retries_max = max(report.cas_retries_max, retries)
        report.admitted_min = min(report.admitted_min, admitted)
        report.admitted_max = max(report.admitted_max, admitted)
        if any(not w.done() for w in ws):
            report.violations.append(
                f"deadlock: worker(s) never finished under schedule "
                f"{seq} kill={kill}"
            )
        if retries > admitted * max(1, workers - 1):
            # lock-free progress: every failed CAS implies some other
            # worker's CAS succeeded between the load and the attempt,
            # and one winning CAS can invalidate at most the other
            # (workers - 1) in-flight loads — so retries are bounded by
            # admits x (workers - 1), never unbounded spinning
            report.violations.append(
                f"livelock: {retries} CAS retries > {admitted} admits "
                f"x {max(1, workers - 1)} losers under schedule {seq} "
                f"kill={kill}"
            )
        if admitted > whole_budget:
            report.violations.append(
                f"double-spend: {admitted} tokens granted with only "
                f"{whole_budget} in the bucket (schedule {seq}, "
                f"kill={kill})"
            )
        if completed >= whole_budget:
            floor = int(whole_budget * 0.9)
            if admitted < floor:
                report.violations.append(
                    f"under-admission: {admitted} < {floor} (±10% of "
                    f"budget {whole_budget}) under schedule {seq}, "
                    f"kill={kill}"
                )

    # enumerate maximal fair schedules: at every step pick any worker
    # that still has steps to take; depth ≤ workers * attempts * 2 + retries
    max_depth = workers * attempts_per_worker * 2 + workers * 4

    def dfs(seq: tuple) -> None:
        if report.interleavings >= budget:
            report.truncated = True
            return
        # replay to find who can still step
        slot = [0]
        ws = [_GcraWorker(i, attempts_per_worker) for i in range(workers)]
        for wid in seq:
            if not ws[wid].done():
                ws[wid].step(slot, now_ns, T, tau, blind_store)
        movers = [w.wid for w in ws if not w.done()]
        if not movers or len(seq) >= max_depth:
            run_sequence(seq, None)
            if kill_arm and seq:
                # SIGKILL each worker at each point along this schedule
                for kp in range(len(seq)):
                    for kw in range(workers):
                        if report.interleavings >= budget:
                            report.truncated = True
                            return
                        run_sequence(seq, (kw, kp))
            return
        for wid in movers:
            dfs(seq + (wid,))
            if report.truncated:
                return

    dfs(())
    if report.admitted_min == 1 << 30:
        report.admitted_min = 0
    return report
