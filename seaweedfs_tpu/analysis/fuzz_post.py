"""Structured fuzzer: the C POST parser vs the Python fallback.

native/post.c re-implements multipart framing, part-header parsing,
filename extraction, gzippability sniffing, and needle assembly — all
of it byte-contracted to the pure-Python path (util/multipart.py +
server/write_path.py): for any request the C path either DECLINES
(and Python serves it) or produces the exact same .dat bytes, .idx
bytes, and 201 reply. This driver generates adversarial requests —
hostile boundaries, escaped/unterminated filenames, transfer
encodings, embedded delimiter bytes, torn framing, NULs, non-ASCII —
and checks that contract end-to-end through two real Volumes.

Crash persistence: each candidate is written to the corpus directory
BEFORE the C call and removed after a clean verdict, so a segfaulting
input survives the dead process for triage (run the driver under
WEED_NATIVE_SAN=asan + the LD_PRELOAD recipe from
_build.asan_preload_env() to catch the heap corruption behind it).
Diverging inputs persist as regression entries; tests/corpus/ holds
the standing set and tests/test_fuzz_corpus.py sweeps identity over
every entry on every tier-1 run.

    python -m seaweedfs_tpu.analysis.fuzz_post --n 500 --seed 7
    python -m seaweedfs_tpu.analysis.fuzz_post --seed-corpus  # refresh tests/corpus/
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import random
import tempfile
import types
from dataclasses import dataclass, field

from seaweedfs_tpu.analysis import REPO_ROOT

DEFAULT_CORPUS = os.path.join(REPO_ROOT, "tests", "corpus")

_BOUNDARY_CHARS = (
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    "'()+_,-./:=? "
)

_NAMES = [
    "a.bin", "x.txt", "photo.jpg", "img.jpeg", "data", "deep/p/a.th",
    "sp ace.bin", "q\"uote.bin", "unié.bin", ".hidden", "a..b.gz",
    "ends.", "x" * 80 + ".bin", "back\\slash.bin", "semi;colon.bin",
]

_MIMES = [
    "application/octet-stream", "text/plain", "Image/svg", "image/png",
    "application/json", "application/weird+xml", "TEXT/PLAIN",
    "application/x-script", "", "a" * 300,
]


def _payload(rng: random.Random, boundary: str) -> bytes:
    kind = rng.randrange(7)
    if kind == 0:
        return rng.randbytes(rng.randrange(0, 700))
    if kind == 1:  # compressible text (C must decline > 128 bytes)
        return b"all work and no play " * rng.randrange(1, 40)
    if kind == 2:  # embedded delimiter bytes mid-payload
        filler = rng.randbytes(rng.randrange(3, 60))
        return (
            filler + b"\r\n--" + boundary.encode("latin-1", "replace")
            + rng.choice([b"", b"--", b" junk", b"\ttail", b"\r\n"])
            + filler
        )
    if kind == 3:  # gzip magic without gzip truth
        return b"\x1f\x8b\x08\x00" + rng.randbytes(rng.randrange(0, 300))
    if kind == 4:  # NUL-laced
        return bytes(rng.randrange(0, 3) for _ in range(rng.randrange(1, 400)))
    if kind == 5:  # exactly around the 128-byte compression threshold
        return bytes([rng.randrange(200, 256)]) * rng.choice(
            [127, 128, 129, 130]
        )
    return b""


def _part_head(rng: random.Random, filename: str | None, mime: str | None
               ) -> bytes:
    lines: list[bytes] = []
    disp = "form-data"
    if rng.random() < 0.3:
        disp += f'; name="{rng.choice(["file", "f", "field?*", ""])}"'
    if filename is not None:
        quote_style = rng.randrange(4)
        if quote_style == 0:
            disp += f'; filename="{filename}"'
        elif quote_style == 1:
            disp += f"; filename={filename.replace(' ', '_')}"
        elif quote_style == 2:  # escaped quote inside quoted string
            disp += f'; filename="pre\\"post.bin"'
        else:  # unterminated quote
            disp += f'; filename="{filename}'
    key = rng.choice(
        ["Content-Disposition", "content-disposition", "CONTENT-DISPOSITION",
         "Content-Disposition "]
    )
    lines.append(f"{key}: {disp}".encode("latin-1", "replace"))
    if mime is not None:
        lines.append(f"Content-Type: {mime}".encode("latin-1", "replace"))
    if rng.random() < 0.25:
        lines.append(
            b"Content-Transfer-Encoding: "
            + rng.choice([b"binary", b"8bit", b"base64", b"quoted-printable",
                          b"7bit", b"x-unknown"])
        )
    if rng.random() < 0.2:
        lines.append(b"Content-Encoding: " + rng.choice([b"gzip", b"GZIP",
                                                         b"identity"]))
    if rng.random() < 0.15:  # hostile header shapes
        lines.append(rng.choice([
            b"no-colon-line",
            b": empty-key",
            b"X-Weird\t: tabbed",
            b"X-Long: " + b"v" * 2000,
        ]))
    return b"\r\n".join(lines)


def gen_case(rng: random.Random) -> dict:
    """One adversarial request: {'q', 'headers', 'url_filename', 'body'}."""
    case_kind = rng.randrange(10)
    q: dict[str, str] = {"ts": str(1_700_000_000 + rng.randrange(1000))}
    headers: dict[str, str] = {}
    url_filename = rng.choice(["", "u.bin", "u.jpg", "ur l.txt"])
    if rng.random() < 0.2:
        q["filename"] = rng.choice(_NAMES)
    if rng.random() < 0.1:
        q["cm"] = "true"
    if rng.random() < 0.15:
        headers[f"seaweed-{rng.choice(['k', 'key2', 'UPPER'])}"] = (
            rng.choice(["v", "v" * 50, "späce"])
        )

    if case_kind == 0:  # raw body, not multipart
        body = _payload(rng, "x")
        if rng.random() < 0.4:
            headers["content-type"] = rng.choice(_MIMES)
        if rng.random() < 0.2:
            headers["content-encoding"] = "gzip"
        return {"q": q, "headers": headers, "url_filename": url_filename,
                "body": body}

    boundary = "".join(
        rng.choice(_BOUNDARY_CHARS) for _ in range(rng.randrange(1, 40))
    ).strip() or "b"
    quoted = rng.random() < 0.4
    ct_boundary = f'"{boundary}"' if quoted else boundary
    sep = rng.choice(["; ", ";", " ; ", ";\t"])
    headers["content-type"] = (
        f"multipart/form-data{sep}boundary={ct_boundary}"
    )
    if rng.random() < 0.1:  # boundary parameter spacing abuse
        headers["content-type"] = (
            f"multipart/form-data; boundary = {ct_boundary}"
        )

    delim = b"--" + boundary.encode("latin-1", "replace")
    chunks: list[bytes] = []
    if rng.random() < 0.2:
        chunks.append(b"preamble junk " + rng.randbytes(10) + b"\r\n")
    n_parts = rng.randrange(0, 4)
    for i in range(n_parts):
        has_name = rng.random() < 0.6
        filename = rng.choice(_NAMES) if has_name else None
        mime = rng.choice(_MIMES) if rng.random() < 0.7 else None
        head = _part_head(rng, filename, mime)
        payload = _payload(rng, boundary)
        glue = rng.choice([b"\r\n\r\n", b"\r\n\r\n", b"\r\n\r\n", b"\n\n",
                           b"\r\n"])
        chunks.append(delim + rng.choice([b"", b" ", b"\t \t"]) + b"\r\n")
        chunks.append(head + glue + payload + b"\r\n")
    closing = rng.choice(
        [delim + b"--\r\n", delim + b"--", delim + b"-- \t\r\nepilogue",
         delim + b"\r\n", b""]
    )
    chunks.append(closing)
    body = b"".join(chunks)
    if rng.random() < 0.1:  # torn framing
        body = body[: rng.randrange(0, max(1, len(body)))]
    return {"q": q, "headers": headers, "url_filename": url_filename,
            "body": body}


# ---------------------------------------------------------------------------
# the identity oracle


def _pin(v) -> None:
    """Deterministic append stamps, matching tests/test_native_post.py:
    a pure function of volume state so a declined C attempt does not
    advance the clock."""
    v._now_ns = types.MethodType(
        lambda self: self.last_append_at_ns + 1, v
    )


def run_case(case: dict, workdir: str) -> tuple[str, str | None]:
    """(verdict, divergence): verdict is 'handled' (C served it),
    'declined' (Python fallback served it), or 'rejected' (both sides
    refused the request). Writes nothing outside `workdir`."""
    from seaweedfs_tpu.server import write_path
    from seaweedfs_tpu.storage.file_id import FileId
    from seaweedfs_tpu.storage.volume import Volume
    from seaweedfs_tpu.util.httpd import FastHeaders
    from seaweedfs_tpu.util.multipart import MalformedUpload

    headers = FastHeaders()
    for k, val in case["headers"].items():
        headers[k.lower()] = val
    q = dict(case["q"])
    body = case["body"]
    url_filename = case["url_filename"]
    os.mkdir(os.path.join(workdir, "a"))
    os.mkdir(os.path.join(workdir, "b"))
    va = Volume(os.path.join(workdir, "a"), 1)
    vb = Volume(os.path.join(workdir, "b"), 1)
    _pin(va)
    _pin(vb)
    fid = FileId(1, 0x1234, 0xCAFE)
    try:
        fast = write_path.try_native_post(
            va, fid, q, body, headers, url_filename,
            fix_jpg_orientation=True,
        )
        c_handled = fast is not None

        def py_write(v):
            n, fname, err = write_path.build_upload_needle(
                fid, q, body, headers, url_filename,
                fix_jpg_orientation=True,
            )
            if err is not None:
                return None, err
            try:
                _off, size, _unchanged = v.write_needle(n)
            except (OSError, ValueError) as e:
                return None, f"write_needle: {e}"
            reply = b'{"name": %s, "size": %d, "eTag": "%s"}' % (
                json.dumps(fname).encode(), size, n.etag().encode()
            )
            return reply, None

        try:
            py_reply, py_err = py_write(vb)
        except MalformedUpload as e:
            py_reply, py_err = None, f"malformed: {e}"
        if py_err is not None:
            if c_handled:
                return "handled", (
                    f"C accepted a request Python rejects ({py_err})"
                )
            return "rejected", None  # both sides reject: fine
        if not c_handled:
            # declined: the fallback must serve volume A identically
            fast, fb_err = py_write(va)
            if fb_err is not None:
                return "declined", (
                    f"fallback failed after decline ({fb_err}) though "
                    f"the oracle volume accepted"
                )
        files = {}
        for tag, v in (("a", va), ("b", vb)):
            with open(v.base_name + ".dat", "rb") as f:
                dat = f.read()
            with open(v.base_name + ".idx", "rb") as f:
                idx = f.read()
            files[tag] = (dat, idx)
        verdict = "handled" if c_handled else "declined"
        if files["a"][0] != files["b"][0]:
            return verdict, ".dat bytes diverged"
        if files["a"][1] != files["b"][1]:
            return verdict, ".idx bytes diverged"
        if fast != py_reply:
            return verdict, (
                f"reply diverged: {fast!r:.120} vs {py_reply!r:.120}"
            )
        return verdict, None
    finally:
        va.close()
        vb.close()


# ---------------------------------------------------------------------------
# corpus plumbing


def case_to_json(case: dict) -> str:
    return json.dumps(
        {
            "q": case["q"],
            "headers": case["headers"],
            "url_filename": case["url_filename"],
            "body_b64": base64.b64encode(case["body"]).decode(),
        },
        indent=1,
        sort_keys=True,
    )


def case_from_json(text: str) -> dict:
    raw = json.loads(text)
    return {
        "q": raw["q"],
        "headers": raw["headers"],
        "url_filename": raw.get("url_filename", ""),
        "body": base64.b64decode(raw["body_b64"]),
    }


def _case_name(case: dict, prefix: str) -> str:
    digest = hashlib.sha256(
        case_to_json(case).encode()
    ).hexdigest()[:12]
    return f"{prefix}_{digest}.json"


@dataclass
class FuzzReport:
    iterations: int = 0
    handled: int = 0  # cases the C path served
    declined: int = 0
    rejected: int = 0  # both sides refused (malformed)
    divergences: list[str] = field(default_factory=list)
    corpus_written: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "iterations": self.iterations,
            "c_handled": self.handled,
            "c_declined": self.declined,
            "both_rejected": self.rejected,
            "divergences": self.divergences,
            "corpus_written": self.corpus_written,
        }


def run(
    iterations: int = 200,
    seed: int = 0,
    corpus_dir: str | None = None,
    persist_divergent: bool = True,
) -> FuzzReport:
    rng = random.Random(seed)
    report = FuzzReport()
    corpus_dir = corpus_dir or DEFAULT_CORPUS
    os.makedirs(corpus_dir, exist_ok=True)
    pending = os.path.join(corpus_dir, f"pending_{seed}.json")
    try:
        for i in range(iterations):
            case = gen_case(rng)
            # persist BEFORE the C call: a segfault leaves the input behind
            with open(pending, "w", encoding="utf-8") as f:
                f.write(case_to_json(case))
            report.iterations += 1
            with tempfile.TemporaryDirectory(prefix="weedfuzz") as workdir:
                verdict, divergence = run_case(case, workdir)
            if verdict == "handled":
                report.handled += 1
            elif verdict == "rejected":
                report.rejected += 1
            else:
                report.declined += 1
            if divergence is not None:
                report.divergences.append(
                    f"iter {i} (seed {seed}): {divergence}"
                )
                if persist_divergent:
                    name = _case_name(case, "div")
                    # weedlint: ignore[crash-rename-no-dirsync,crash-rename-unsynced-src] — forensic corpus artifact; persistence is best-effort and the fuzzer reruns
                    os.replace(pending, os.path.join(corpus_dir, name))
                    report.corpus_written.append(name)
    finally:
        # a hard C crash never reaches here, so the repro survives; any
        # Python-side exit (exception, Ctrl-C) must not leave pending_*
        # behind in the version-controlled corpus dir
        try:
            os.remove(pending)
        except OSError:
            pass
    return report


def seed_corpus(
    corpus_dir: str | None = None, seed: int = 20260803, target: int = 24
) -> list[str]:
    """Refresh tests/corpus/ with a spread of adversarial inputs: the
    generator runs until `target` distinct framing categories × payload
    kinds are covered. Deterministic for a given seed, so re-seeding
    produces a stable corpus (plus any div_*/pending_* regressions
    already present, which are never touched)."""
    rng = random.Random(seed)
    corpus_dir = corpus_dir or DEFAULT_CORPUS
    os.makedirs(corpus_dir, exist_ok=True)
    written: list[str] = []
    seen_kinds: set[tuple] = set()
    guard = 0
    while len(written) < target and guard < 10000:
        guard += 1
        case = gen_case(rng)
        ct = case["headers"].get("content-type", "")
        kind = (
            ct.split(";")[0],
            "filename=" in ct or b"filename" in case["body"],
            b"Content-Transfer-Encoding" in case["body"],
            len(case["body"]) % 3,
            bool(case["q"].get("cm")),
        )
        if kind in seen_kinds:
            continue
        seen_kinds.add(kind)
        name = _case_name(case, "seed")
        with open(
            os.path.join(corpus_dir, name), "w", encoding="utf-8"
        ) as f:
            f.write(case_to_json(case))
        written.append(name)
    return written


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m seaweedfs_tpu.analysis.fuzz_post"
    )
    ap.add_argument("--n", type=int, default=200, help="fuzz iterations to run")
    ap.add_argument(
        "--seed", type=int, default=0,
        help="PRNG seed (same seed = same input stream)",
    )
    ap.add_argument(
        "--corpus", default=DEFAULT_CORPUS,
        help="corpus directory for crash/divergence persistence",
    )
    ap.add_argument(
        "--seed-corpus",
        action="store_true",
        help="write the deterministic seed corpus and exit",
    )
    args = ap.parse_args(argv)
    if args.seed_corpus:
        names = seed_corpus(args.corpus)
        print(f"seeded {len(names)} corpus entries in {args.corpus}")
        return 0
    report = run(iterations=args.n, seed=args.seed, corpus_dir=args.corpus)
    print(json.dumps(report.to_dict(), indent=2))
    return 1 if report.divergences else 0


if __name__ == "__main__":
    raise SystemExit(main())
