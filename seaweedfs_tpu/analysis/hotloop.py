"""Hot-loop lint: blocking calls on the data-plane dispatch paths.

Every serving daemon rides the mini request loop (util/httpd
serve_connection → FastHandler.do_*): one handler thread per
connection, keep-alive, whole-response writes. A blocking call inside
that dispatch tree is a stalled connection at best and, under the
SO_REUSEPORT worker model, a stalled accept slot — the Go reference
never hits this class because goroutines are preemptible and every
net call carries a deadline.

Entry points are found structurally: `do_*` methods of every class
deriving (transitively, within the package) from FastHandler /
FastRequestMixin / BaseHTTPRequestHandler, plus serve_connection
itself. Reachability then follows the same resolved call graph the
lock-order pass builds (self-methods, module functions, unique method
names, local callbacks). Rules:

  hot-loop-sleep           time.sleep() — a dispatch thread parked on
                           wall-clock time
  hot-loop-subprocess      subprocess.* — fork+exec latency and an
                           unbounded child wait
  hot-loop-no-timeout      urlopen()/create_connection() without a
                           timeout= (a dead peer pins the thread
                           forever; sockets must carry deadlines)
  hot-loop-unbounded-read  rfile.read() with no byte count: an
                           EOF-delimited read of a keep-alive socket
                           blocks until the CLIENT closes
  hot-loop-gil-span        gzip.compress/decompress of request bodies
                           inline in dispatch — a multi-MiB compress
                           holds the GIL for milliseconds and stalls
                           every other handler thread (the C tier
                           exists precisely because of this class;
                           suppressions must say why Python is still
                           the right place)
"""

from __future__ import annotations

import ast

from seaweedfs_tpu.analysis import Finding, dotted_name as _dotted
from seaweedfs_tpu.analysis.lockorder import PackageIndex, build_index

_HANDLER_BASES = {
    "FastHandler",
    "FastRequestMixin",
    "BaseHTTPRequestHandler",
    "StreamRequestHandler",
}

_SUBPROCESS_FNS = {
    "run", "Popen", "call", "check_call", "check_output",
}

# Structural exemptions: qualname prefixes excluded from the hot-loop
# scan even though they are reachable from every dispatch path, WITH
# the mandatory reason (mirroring the suppression policy — an entry
# without a reason string would defeat the point). Keep this list
# short; it exists for infrastructure the dispatch tree deliberately
# carries on every request.
_EXEMPT_QUALS: dict[str, str] = {
    # The tracing plane's ring-buffer append (trace/tracer._record) and
    # span bookkeeping run inside EVERY do_* dispatch by design
    # (util/httpd.serve_connection wraps dispatch in a span). Its
    # critical section is two preallocated-list/dict operations behind
    # one process-wide lock — bounded, no IO, no waits — so tracing
    # itself must never read as a blocking call; flagging it would
    # train people to suppress the checker on real findings.
    "seaweedfs_tpu.trace.tracer.": (
        "lock-cheap ring append + span bookkeeping; bounded two-op "
        "critical section, no IO (docs/TRACING.md)"
    ),
    # The telemetry plane's profiler capture (/debug/profile?seconds=S)
    # parks ONLY the requesting operator connection's thread for the
    # operator-chosen, httpd-capped window — that sleep IS the feature
    # (snapshot → wait → diff), and the sampler/collector loops it
    # shares the module with run on their own background threads, never
    # inside dispatch (docs/TELEMETRY.md).
    "seaweedfs_tpu.telemetry.profiler.": (
        "operator-requested bounded capture window; parks only the "
        "requesting connection's thread, by design (docs/TELEMETRY.md)"
    ),
    # The collector's scrape fan-out is a leader-side background loop;
    # it is only reachable from dispatch through the read-only
    # /cluster/* payload builders, which never block — exempting the
    # module keeps a future lint-graph widening from flagging the
    # scrape loop's own deadline-bounded waits as handler stalls.
    "seaweedfs_tpu.telemetry.collector.": (
        "leader-side background scrape loop; /cluster/* handlers only "
        "read ring snapshots under short locks (docs/TELEMETRY.md)"
    ),
    # The EC streaming pipeline's staging-ring and queue waits
    # (_q_get/_q_put/_StagingRing.acquire, docs/CODEC.md) are bounded
    # 200 ms-tick polls that exist precisely so an aborted pipeline can
    # never park a pool thread forever; they run on the pipeline's OWN
    # reader/writer pool threads inside the maintenance verbs
    # (generate/rebuild), never inside a serving dispatch, and the
    # blocking IS the backpressure design — flagging the waits would
    # train people to suppress the checker on real handler stalls.
    "seaweedfs_tpu.ec.ec_stream.": (
        "staging-ring/queue backpressure waits on pipeline pool "
        "threads, stop-aware 200 ms ticks by design; maintenance "
        "verbs only, not serving dispatch (docs/CODEC.md)"
    ),
}


def _handler_classes(index: PackageIndex) -> set[str]:
    """Class names deriving (transitively in-package) from a handler base."""
    out: set[str] = set()
    changed = True
    while changed:
        changed = False
        for cls in index.classes.values():
            if cls.name in out:
                continue
            if any(b in _HANDLER_BASES or b in out for b in cls.bases):
                out.add(cls.name)
                changed = True
    return out


def _entry_points(index: PackageIndex) -> set[str]:
    entries: set[str] = set()
    handler_names = _handler_classes(index)
    for cls in index.classes.values():
        if cls.name not in handler_names:
            continue
        for mname, qual in cls.methods.items():
            if mname.startswith("do_") or mname in (
                "handle", "handle_one_request"
            ):
                entries.add(qual)
    for qual in index.funcs:
        if qual.endswith(".serve_connection"):
            entries.add(qual)
    return entries


def _reachable(index: PackageIndex, entries: set[str]) -> dict[str, str]:
    """qualname -> entry point it is reachable from (first found)."""
    seen: dict[str, str] = {}
    stack = [(e, e) for e in sorted(entries)]
    while stack:
        qual, origin = stack.pop()
        if qual in seen:
            continue
        seen[qual] = origin
        rec = index.funcs.get(qual)
        if rec is None:
            continue
        for _held, ref, _line, cb_args in rec.calls:
            if ref is not None and ref not in seen:
                stack.append((ref, origin))
            for _k, cb in cb_args:
                if cb not in seen:
                    stack.append((cb, origin))
    return seen


def _has_kw(call: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in call.keywords)


def _scan_function(qual: str, origin: str, fn: ast.FunctionDef,
                   path: str) -> list[Finding]:
    findings: list[Finding] = []
    via = f" (reached from {origin.rsplit('.', 2)[-1]})" if origin != qual \
        else ""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        tail = dotted.rsplit(".", 1)[-1]
        # normalize the `import gzip as _gzip` aliasing idiom
        head = dotted.split(".", 1)[0].lstrip("_")
        if tail == "sleep" and head == "time":
            findings.append(Finding(
                "hot-loop-sleep", path, node.lineno,
                f"time.sleep() in dispatch path {qual}{via}: parks the "
                f"connection's handler thread on wall-clock time",
            ))
        elif head == "subprocess" and tail in _SUBPROCESS_FNS:
            findings.append(Finding(
                "hot-loop-subprocess", path, node.lineno,
                f"subprocess.{tail}() in dispatch path {qual}{via}: "
                f"fork+exec and child wait block the request loop",
            ))
        elif (
            tail == "urlopen"
            and len(node.args) < 3  # timeout is urlopen's 3rd positional
            and not _has_kw(node, "timeout")
        ):
            findings.append(Finding(
                "hot-loop-no-timeout", path, node.lineno,
                f"urlopen() without timeout= in dispatch path "
                f"{qual}{via}: a dead peer pins this handler thread "
                f"forever",
            ))
        elif (
            tail == "create_connection"
            and head == "socket"
            and len(node.args) < 2
            and not _has_kw(node, "timeout")
        ):
            findings.append(Finding(
                "hot-loop-no-timeout", path, node.lineno,
                f"socket.create_connection() without a timeout in "
                f"dispatch path {qual}{via}",
            ))
        elif (
            tail == "settimeout"
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value is None
        ):
            findings.append(Finding(
                "hot-loop-no-timeout", path, node.lineno,
                f"settimeout(None) in dispatch path {qual}{via}: "
                f"removes the socket deadline",
            ))
        elif (
            tail == "read"
            and not node.args
            and not node.keywords
            and isinstance(node.func, ast.Attribute)
            and _dotted(node.func.value).endswith("rfile")
        ):
            findings.append(Finding(
                "hot-loop-unbounded-read", path, node.lineno,
                f"rfile.read() with no byte count in dispatch path "
                f"{qual}{via}: an EOF-delimited read of a keep-alive "
                f"socket blocks until the client closes",
            ))
        elif head == "gzip" and tail in ("compress", "decompress"):
            findings.append(Finding(
                "hot-loop-gil-span", path, node.lineno,
                f"gzip.{tail}() inline in dispatch path {qual}{via}: "
                f"holds the GIL for the whole (de)compression of the "
                f"body",
            ))
    return findings


def check(root: str | None = None, index: PackageIndex | None = None
          ) -> tuple[list[Finding], PackageIndex]:
    index = index or build_index(root)
    entries = _entry_points(index)
    reach = _reachable(index, entries)
    findings: list[Finding] = []
    for qual, origin in sorted(reach.items()):
        fn = index.fn_nodes.get(qual)
        rec = index.funcs.get(qual)
        if fn is None or rec is None:
            continue
        if any(qual.startswith(pfx) for pfx in _EXEMPT_QUALS):
            continue
        findings.extend(_scan_function(qual, origin, fn, rec.path))
    # dedupe: one site can be reachable from many entries
    seen: set[tuple[str, int, str]] = set()
    out: list[Finding] = []
    for f in findings:
        key = (f.path, f.line, f.rule)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out, index
