"""weedlint v4 `race` rules: shared-state escape lint (docs/ANALYSIS.md).

The unguarded-write rule (lockorder.py) catches writes that skip a
lock OTHER sites hold. This pass catches the subtler shape behind the
tree's actual concurrency history (PR 9, PR 12, PR 15): CHECK-THEN-ACT
— a decision read of `self.attr` and a dependent write of the same
attribute that do not share one continuous lock hold. Both halves may
even take the same lock (the PR-9 pre-fix admission did: cap check
under one `with self._lock:`, the increment under a second), which is
why the analysis tracks HOLD SPANS, not held lock names: two separate
`with` blocks on one lock are two spans, and a check in span 1 with
its act in span 2 has lost atomicity exactly as if no lock were held.

Only objects that ESCAPE to a second thread can race, so findings are
gated on an escape fixpoint (precision over recall, the same contract
as every lockorder rule — a finding here should be a true positive):

  * thread targets/args: `threading.Thread(target=obj.m)` /
    `args=(obj, ...)` / `threading.Timer(..., obj.m)` escape obj's
    class; a nested-def target escapes the enclosing class (the
    closure carries `self`);
  * pool submits: `pool.submit(obj.m, ...)`, `submit_attempt(fn)`;
  * module globals: a module-level `NAME = ClassName(...)` singleton
    is reachable from every server/handler thread (the FastHandler
    do_* dispatch tree runs on per-connection threads and touches
    exactly these);
  * containment, to fixpoint: an escaped class's constructor-assigned
    attribute classes escape with it (the Thread target's `self.store`
    is as shared as `self`).

Within an escaped class's non-constructor methods (ctor-exempt and
classmethod contexts reuse lockorder's fixpoint), the rule flags:

  * an `if`/`while` whose test reads `self.attr`, with a write to the
    same attr in the branch body — when the test's hold-spans and the
    write's hold-spans are disjoint;
  * the guard-clause form: `if <reads self.attr>: return/raise` with a
    later write to the attr in the same function, spans disjoint (the
    PR-9 shape).

Noise gate: a finding requires a lock SIGNAL — the class declares at
least one lock attribute, or one side of the pair actually holds one.
An escaped class with no locks anywhere is either lock-free by design
or externally serialized; flagging every bare check in it would bury
the true positives (stated non-goal, ANALYSIS.md v4). Suppressions use
the standard grammar — `weedlint: ignore[race-check-then-act]` in a
comment, em-dash reason mandatory; the dynamic side of weedrace
(race.py) is the recall instrument, exactly as the witness backs
lock-order.
"""

from __future__ import annotations

import ast

from seaweedfs_tpu.analysis import Finding
from seaweedfs_tpu.analysis.lockorder import (
    PackageIndex,
    _CTOR_METHODS,
    _MUTATORS,
    _call_contexts,
    _param_annotations,
    build_index,
)

RULE = "race-check-then-act"

# callables that hand their function argument to another thread
_SUBMIT_NAMES = {"submit", "submit_attempt", "apply_async", "map_async"}
_THREAD_FACTORIES = {"Thread", "Timer"}


# ---------------------------------------------------------------------------
# escape analysis


def _resolve_owner(
    index: PackageIndex, node: ast.expr, rec, annotations: dict
) -> str | None:
    """The class name whose instance `node` denotes, when knowable:
    `self` → the enclosing class; an annotated param/local → its class;
    `self.m` / `obj.m` (a bound method) → the receiver's class."""
    if isinstance(node, ast.Name):
        if node.id == "self" and rec.cls is not None:
            return rec.cls
        cls_name = annotations.get(node.id)
        if cls_name and index.class_by_name(cls_name) is not None:
            return cls_name
        return None
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        # bound method: the RECEIVER escapes
        return _resolve_owner(index, node.value, rec, annotations)
    return None


def _escape_sites(index: PackageIndex) -> dict[str, str]:
    """class name -> human-readable reason it escapes to another
    thread. Conservative: only resolvable receivers count."""
    escapes: dict[str, str] = {}

    def mark(cls_name: str | None, reason: str) -> None:
        if cls_name is not None and cls_name not in escapes:
            escapes[cls_name] = reason

    for qual, fn_node in index.fn_nodes.items():
        rec = index.funcs[qual]
        annotations = _param_annotations(fn_node)
        local_defs = {
            n.name
            for n in ast.walk(fn_node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn_node
        }
        for call in ast.walk(fn_node):
            if not isinstance(call, ast.Call):
                continue
            fn = call.func
            cname = (
                fn.attr if isinstance(fn, ast.Attribute)
                else fn.id if isinstance(fn, ast.Name) else ""
            )
            if cname in _THREAD_FACTORIES:
                where = f"{rec.path}:{call.lineno}"
                for kw in call.keywords:
                    if kw.arg == "target":
                        tgt = kw.value
                        if (
                            isinstance(tgt, ast.Name)
                            and tgt.id in local_defs
                        ):
                            # closure target: `self` rides in the cell
                            mark(rec.cls, f"closure thread target {where}")
                        else:
                            mark(
                                _resolve_owner(index, tgt, rec, annotations),
                                f"thread target {where}",
                            )
                    elif kw.arg == "args" and isinstance(
                        kw.value, (ast.Tuple, ast.List)
                    ):
                        for el in kw.value.elts:
                            mark(
                                _resolve_owner(index, el, rec, annotations),
                                f"thread arg {where}",
                            )
                # Timer's positional callback: Timer(5.0, self.m)
                if cname == "Timer" and len(call.args) >= 2:
                    mark(
                        _resolve_owner(
                            index, call.args[1], rec, annotations
                        ),
                        f"timer callback {where}",
                    )
            elif cname in _SUBMIT_NAMES and call.args:
                where = f"{rec.path}:{call.lineno}"
                head = call.args[0]
                if isinstance(head, ast.Name) and head.id in local_defs:
                    mark(rec.cls, f"closure pool submit {where}")
                else:
                    mark(
                        _resolve_owner(index, head, rec, annotations),
                        f"pool submit {where}",
                    )
                for el in call.args[1:]:
                    mark(
                        _resolve_owner(index, el, rec, annotations),
                        f"pool submit arg {where}",
                    )

    # module-level singletons: NAME = ClassName(...) at module scope is
    # reachable from every thread that imports the module — the
    # FastHandler do_* dispatch tree touches exactly these
    for rel_path, source in index.sources.items():
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError:
            continue
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            val = node.value
            if not (isinstance(val, ast.Call) and isinstance(
                val.func, ast.Name
            )):
                continue
            if index.class_by_name(val.func.id) is not None:
                mark(
                    val.func.id,
                    f"module-global instance {rel_path}:{node.lineno}",
                )

    # containment fixpoint: an escaped class's ctor-assigned attribute
    # classes are exactly as shared as the instance that carries them
    for _ in range(10):
        grew = False
        for cls_qual, cls in index.classes.items():
            if cls.name not in escapes:
                continue
            ctor_qual = cls.methods.get("__init__")
            ctor_node = index.fn_nodes.get(ctor_qual) if ctor_qual else None
            if ctor_node is None:
                continue
            for sub in ast.walk(ctor_node):
                if not (
                    isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Attribute)
                    and isinstance(sub.targets[0].value, ast.Name)
                    and sub.targets[0].value.id == "self"
                    and isinstance(sub.value, ast.Call)
                ):
                    continue
                ctor_fn = sub.value.func
                held_name = (
                    ctor_fn.id if isinstance(ctor_fn, ast.Name)
                    else ctor_fn.attr if isinstance(ctor_fn, ast.Attribute)
                    else None
                )
                if (
                    held_name
                    and held_name not in escapes
                    and index.class_by_name(held_name) is not None
                ):
                    escapes[held_name] = (
                        f"held by escaped {cls.name} "
                        f"({escapes[cls.name]})"
                    )
                    grew = True
        if not grew:
            break
    return escapes


# ---------------------------------------------------------------------------
# check-then-act walk (hold-span aware)


class _SpanWalker:
    """Tracks (lock, span) holds through one function body. Every
    `with self.lock:` block gets a fresh span id: a check and an act
    share atomicity ONLY when they share a span, not merely a lock."""

    def __init__(self, index: PackageIndex, rec, cls):
        self.index = index
        self.rec = rec
        self.cls = cls
        self.held: list[tuple[str, int]] = []  # (lock id, span serial)
        self._span = 0
        # (attr, test_line, test_spans, write_line, write_spans)
        self.pairs: list[tuple] = []
        # guard-clause tests awaiting a later write:
        # attr -> [(test_line, test_spans)]
        self._armed: dict[str, list[tuple[int, frozenset]]] = {}

    # -- resolution ----------------------------------------------------
    def _is_own_lock(self, expr: ast.expr) -> bool:
        return (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and self.cls is not None
            and expr.attr in self.cls.lock_attrs
        )

    def _self_reads(self, expr: ast.expr) -> set[str]:
        """self.attr names READ inside an expression (any context —
        a subscript probe or method call on the attr is a read)."""
        out: set[str] = set()
        for sub in ast.walk(expr):
            if (
                isinstance(sub, ast.Attribute)
                and isinstance(sub.value, ast.Name)
                and sub.value.id == "self"
                and (self.cls is None or sub.attr not in self.cls.lock_attrs)
            ):
                out.add(sub.attr)
        return out

    def _self_writes(self, stmt: ast.stmt) -> list[tuple[str, int]]:
        """(attr, line) for writes to self.attr within one statement:
        assignment targets, augmented assigns, subscript stores, and
        mutator method calls (.append/.pop/...)."""
        out: list[tuple[str, int]] = []

        def target(tgt: ast.expr) -> None:
            if isinstance(tgt, ast.Attribute) and isinstance(
                tgt.value, ast.Name
            ) and tgt.value.id == "self":
                out.append((tgt.attr, tgt.lineno))
            elif isinstance(tgt, ast.Subscript):
                inner = tgt.value
                if isinstance(inner, ast.Attribute) and isinstance(
                    inner.value, ast.Name
                ) and inner.value.id == "self":
                    out.append((inner.attr, tgt.lineno))
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                for el in tgt.elts:
                    target(el)

        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    target(tgt)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                target(sub.target)
            elif isinstance(sub, ast.Delete):
                for tgt in sub.targets:
                    target(tgt)
            elif isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ):
                recv = sub.func.value
                if (
                    sub.func.attr in _MUTATORS
                    and isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                ):
                    out.append((recv.attr, sub.lineno))
        return out

    def _spans(self) -> frozenset:
        return frozenset(self.held)

    # -- statement walk ------------------------------------------------
    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _note_writes(self, stmt: ast.stmt) -> None:
        spans = self._spans()
        for attr, line in self._self_writes(stmt):
            checks = self._armed.get(attr, [])
            if not checks:
                continue
            # A write is safe when ANY check of the attr shares a hold
            # span with it — the governing decision was (re)validated
            # inside the act's own hold. This is what makes the fixed
            # double-checked shape (`with lock: if cond: return; act`)
            # pass while the torn shape pairs with its nearest check.
            if any(test_spans & spans for _, test_spans in checks):
                continue
            test_line, test_spans = checks[-1]
            self.pairs.append(
                (attr, test_line, test_spans, line, spans)
            )

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            pushed = 0
            for item in stmt.items:
                if self._is_own_lock(item.context_expr):
                    self._span += 1
                    self.held.append(
                        (
                            f"{self.cls.name}.{item.context_expr.attr}",
                            self._span,
                        )
                    )
                    pushed += 1
            self.walk(stmt.body)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.If, ast.While)):
            reads = self._self_reads(stmt.test)
            test_spans = self._spans()
            test_line = stmt.lineno
            # Arm every tested attr; any later write with disjoint
            # spans completes a pair. This covers both shapes at once:
            # the direct form (write inside the branch, walked next)
            # and the guard-clause form (write after the early return).
            for attr in reads:
                self._armed.setdefault(attr, []).append(
                    (test_line, test_spans)
                )
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for handler in stmt.handlers:
                self.walk(handler.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._note_writes_shallow(stmt)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        self._note_writes(stmt)

    def _note_writes_shallow(self, stmt: ast.stmt) -> None:
        """For compound statements whose bodies are walked separately:
        only writes in the header (iter/targets) belong to this level."""
        spans = self._spans()
        header_writes: list[tuple[str, int]] = []
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            probe = ast.Assign(targets=[stmt.target], value=stmt.iter)
            ast.copy_location(probe, stmt)
            ast.fix_missing_locations(probe)
            header_writes = self._self_writes(probe)
        for attr, line in header_writes:
            checks = self._armed.get(attr, [])
            if not checks or any(
                test_spans & spans for _, test_spans in checks
            ):
                continue
            test_line, test_spans = checks[-1]
            self.pairs.append(
                (attr, test_line, test_spans, line, spans)
            )


# ---------------------------------------------------------------------------
# entry point


def check(
    root: str | None = None, index: PackageIndex | None = None
) -> tuple[list[Finding], PackageIndex]:
    index = index or build_index(root)
    escapes = _escape_sites(index)
    ctor_exempt, guarded = _call_contexts(index)
    findings: list[Finding] = []
    seen: set[tuple] = set()
    for qual, fn_node in index.fn_nodes.items():
        rec = index.funcs[qual]
        if rec.cls is None or rec.cls not in escapes:
            continue
        name = qual.rsplit(".", 1)[-1]
        if (
            name in _CTOR_METHODS
            or rec.is_classmethod
            or qual in ctor_exempt
            # every call site holds the class's own lock (the
            # `_refill_locked` idiom): the whole method body runs
            # inside the CALLER's continuous hold, so an internal
            # check-then-act cannot be torn
            or qual in guarded
        ):
            continue
        cls = index.func_cls.get(qual)
        if cls is None:
            continue
        walker = _SpanWalker(index, rec, cls)
        walker.walk(fn_node.body)
        for attr, test_line, test_spans, write_line, write_spans in (
            walker.pairs
        ):
            # noise gate: require a lock signal — the class owns locks,
            # or one side of the pair actually held one
            if not (cls.lock_attrs or test_spans or write_spans):
                continue
            key = (rec.path, write_line, attr)
            if key in seen:
                continue
            seen.add(key)
            t_locks = (
                "/".join(sorted({l for l, _ in test_spans})) or "no lock"
            )
            w_locks = (
                "/".join(sorted({l for l, _ in write_spans})) or "no lock"
            )
            same_lock_hint = ""
            if {l for l, _ in test_spans} & {l for l, _ in write_spans}:
                same_lock_hint = (
                    " (same lock, SEPARATE holds — atomicity broken "
                    "between them)"
                )
            findings.append(
                Finding(
                    RULE,
                    rec.path,
                    write_line,
                    f"{qual} acts on {rec.cls}.{attr} (line {write_line},"
                    f" holding {w_locks}) from a check at line "
                    f"{test_line} (holding {t_locks}) without one "
                    f"continuous hold{same_lock_hint}; instances of "
                    f"{rec.cls} escape to other threads via "
                    f"{escapes[rec.cls]}",
                )
            )
    findings.sort(key=lambda f: (f.path, f.line))
    return findings, index
