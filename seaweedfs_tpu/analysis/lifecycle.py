"""Resource-lifecycle checker: fd/socket/thread acquire-release pairing.

The upcoming epoll C serving core (ROADMAP) will hold thousands of
fds per process; a Python-side path that leaks one fd per error
return turns into fd exhaustion at connection scale, and a
started-never-joined worker thread is a shutdown hang waiting for a
signal. CPython's refcounting hides most of these in tests (the
collector closes what you forgot) — which is exactly why they ship.

Rules:

  lifecycle-fd-leak      a locally acquired fd/file/socket can leave
                         the function unclosed on some path: an early
                         `return`/`raise` between acquisition and
                         close, or plain fall-through. `with` blocks
                         and try/finally-closed resources are clean.
  lifecycle-thread-leak  a non-daemon threading.Thread start()ed but
                         never join()ed, stored, or returned — a
                         process that can never exit cleanly

Analysis (precision over recall, like every weedlint pass):

  * acquisitions: open()/os.open()/os.dup()/socket.socket()/
    socket.create_connection()/sock.accept() and calls to in-package
    ALLOCATOR functions (a function whose return value is a fresh
    resource — computed to fixpoint over the call graph, so
    `fd = self._open_shard()` carries the obligation to the caller);
  * releases: .close()/os.close()/.join(), `with` context entry,
    contextlib.closing;
  * escapes (ownership transfer — the obligation moves, the local
    check ends): returning/yielding the resource, storing it on self
    or into any container, aliasing it, and passing it to a call —
    EXCEPT known borrowing builtins (os.read/os.pread/os.fstat/
    select.select... never take ownership) and in-package callees the
    interprocedural pass proves only borrow their parameter. A callee
    that closes or stores its parameter is a RELEASER/owner; passing
    to it is a transfer. The explicit annotation
        # weedlint: owns[param] — reason
    on (or above) a `def` line forces ownership-transfer for that
    parameter when the analysis cannot see it (C bindings, pools that
    adopt fds);
  * control flow: `with` bodies, try/finally (resources closed in the
    finally are protected through the try), branches walked with
    closed-in-any-arm leniency. Loops walk once.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from seaweedfs_tpu.analysis import Finding, dotted_name as _dotted
from seaweedfs_tpu.analysis.lockorder import PackageIndex, build_index

# dotted-name tails that mint a resource; kind is cosmetic (messages)
_ACQUIRE_BUILTIN = {
    "open": "file",  # both open() and os.open()
    "dup": "fd",
    "socket": "socket",
    "create_connection": "socket",
    "accept": "socket",
    "fdopen": "file",
    "TemporaryFile": "file",
    "NamedTemporaryFile": "file",
}

# borrowing builtins: passing an fd/file here never transfers
# ownership — the caller still owns the close
_BORROW_TAILS = {
    "read", "write", "pread", "pwrite", "pwritev", "preadv", "fstat",
    "lseek", "ftruncate", "fsync", "fdatasync", "sendfile", "select",
    "poll", "register", "len", "isinstance", "print", "repr", "str",
    "fileno", "tell", "seek", "flush", "append_le", "pack", "unpack",
    "min", "max", "abs", "int", "float", "bool", "hash", "id",
}

_CLOSE_TAILS = {"close", "join", "detach", "release_conn", "unlink"}

_OWNS_RE = re.compile(r"#\s*weedlint:\s*owns\[([a-zA-Z0-9_,\s]+)\]\s*(?:[—:-]+\s*(\S.*))?")


@dataclass
class _Resource:
    var: str
    kind: str
    line: int
    daemon_thread: bool = False  # threads only
    started: bool = False  # threads only


@dataclass
class FuncSummary:
    """Interprocedural facts about one function."""

    qualname: str
    # returns a fresh resource of this kind (allocator)
    allocates: str | None = None
    # params (by name) the function takes ownership of: closes, stores,
    # or passes onward to another owner — or annotated owns[param]
    owns_params: set[str] = field(default_factory=set)
    # params only ever borrowed (read/compared/passed to borrowers)
    borrows_params: set[str] = field(default_factory=set)


def _acquisition_kind(node: ast.expr, allocators: dict[str, str],
                      resolve) -> str | None:
    """kind string when `node` is a resource-minting call."""
    if not isinstance(node, ast.Call):
        return None
    dotted = _dotted(node.func)
    tail = dotted.rsplit(".", 1)[-1]
    if tail == "open" and dotted in ("open", "os.open", "io.open"):
        return "file" if dotted != "os.open" else "fd"
    if tail in _ACQUIRE_BUILTIN and tail != "open":
        head = dotted.split(".", 1)[0]
        if tail == "socket" and head not in ("socket",):
            return None  # some_obj.socket attribute, not the module
        return _ACQUIRE_BUILTIN[tail]
    if tail == "Thread" and dotted in ("threading.Thread", "Thread"):
        return "thread"
    ref = resolve(node.func)
    if ref is not None and ref in allocators:
        return allocators[ref]
    return None


def _thread_is_daemon(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return not (
                isinstance(kw.value, ast.Constant)
                and kw.value.value is False
            )
    return False


# ---------------------------------------------------------------------------
# per-function path walk


class _LeakWalker:
    """Walks one function body tracking locally owned resources.

    State: var -> _Resource for resources this frame OWNS. A resource
    leaves the state by being closed (release), escaping (transfer),
    or being reported (leak)."""

    def __init__(self, qual: str, rel_path: str,
                 summaries: dict[str, FuncSummary],
                 allocators: dict[str, str], resolve,
                 funcs: dict | None = None):
        self.qual = qual
        self.rel_path = rel_path
        self.summaries = summaries
        self.allocators = allocators
        self.resolve = resolve
        self.funcs = funcs or {}
        self.open: dict[str, _Resource] = {}
        self.protected: set[str] = set()  # closed by enclosing finally
        self.handler_depth = 0  # inside try-with-except: raises may be caught
        self.findings: list[Finding] = []

    # -- helpers -------------------------------------------------------
    def _escape(self, var: str) -> None:
        self.open.pop(var, None)

    def _close(self, var: str) -> None:
        self.open.pop(var, None)

    def _escapes_in(self, node: ast.expr) -> None:
        """Any tracked var appearing DIRECTLY inside `node` escapes
        (returned, stored into a container, aliased). Names inside
        nested Call nodes are skipped — _handle_call already classified
        those as borrow/transfer."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Call):
                continue
            if isinstance(n, ast.Name) and n.id in self.open:
                self._escape(n.id)
            else:
                stack.extend(ast.iter_child_nodes(n))

    def _leak(self, res: _Resource, line: int, why: str) -> None:
        if res.kind == "thread":
            self.findings.append(Finding(
                "lifecycle-thread-leak", self.rel_path, res.line,
                f"{self.qual} starts a non-daemon Thread "
                f"({res.var!r}) that is never join()ed, stored, or "
                f"returned — the process cannot exit while it runs",
            ))
        else:
            self.findings.append(Finding(
                "lifecycle-fd-leak", self.rel_path, res.line,
                f"{self.qual} acquires {res.var!r} ({res.kind}) here "
                f"but {why} without closing it — under the event-loop "
                f"serving core this is fd exhaustion, not a leak",
            ))

    def _exit_point(self, line: int, why: str) -> None:
        for var, res in list(self.open.items()):
            if var in self.protected:
                continue
            if res.kind == "thread" and not res.started:
                continue  # constructed-never-started: inert object
            self._leak(res, line, why)
            self.open.pop(var, None)

    # -- call classification -------------------------------------------
    def _handle_call(self, call: ast.Call) -> None:
        dotted = _dotted(call.func)
        tail = dotted.rsplit(".", 1)[-1]
        head = dotted.split(".", 1)[0]
        # x.close() / t.join() / x.start()
        if isinstance(call.func, ast.Attribute) and isinstance(
            call.func.value, ast.Name
        ):
            var = call.func.value.id
            if var in self.open:
                if tail in _CLOSE_TAILS:
                    self._close(var)
                    return
                if tail == "start" and self.open[var].kind == "thread":
                    self.open[var].started = True
                    return
                if tail == "setDaemon" and self.open[var].kind == "thread":
                    self.open.pop(var, None)
                    return
                # any other method on the resource is a borrow
                for a in list(call.args) + [k.value for k in call.keywords]:
                    self._escapes_in(a)
                return
        # os.close(fd) — positional release
        if tail in ("close",) and head == "os" and call.args:
            a = call.args[0]
            if isinstance(a, ast.Name) and a.id in self.open:
                self._close(a.id)
                return
        ref = self.resolve(call.func)
        summary = self.summaries.get(ref) if ref else None
        callee_rec = self.funcs.get(ref) if ref else None

        def classify(arg: ast.expr, pname: str | None) -> None:
            names = [
                s.id for s in ast.walk(arg)
                if isinstance(s, ast.Name) and s.id in self.open
            ]
            if not names:
                return
            if tail in _BORROW_TAILS:
                return  # obligation stays here
            if (
                summary is not None
                and pname is not None
                and pname in summary.borrows_params
                and pname not in summary.owns_params
            ):
                return  # proven borrow: caller still owns the close
            for n in names:
                self._escape(n)  # transfer (or unknown callee: lenient)

        for i, a in enumerate(call.args):
            pname = (
                callee_rec.params[i]
                if callee_rec is not None and i < len(callee_rec.params)
                else None
            )
            classify(a, pname)
        for kw in call.keywords:
            classify(kw.value, kw.arg)

    # -- statement walk ------------------------------------------------
    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            # resources entered via with are closed by the protocol
            for item in stmt.items:
                ctx = item.context_expr
                inner = ctx
                if (
                    isinstance(ctx, ast.Call)
                    and _dotted(ctx.func).rsplit(".", 1)[-1] == "closing"
                    and ctx.args
                ):
                    inner = ctx.args[0]
                if isinstance(inner, ast.Name) and inner.id in self.open:
                    self._close(inner.id)
                else:
                    self._expr_calls(ctx)
            self.walk(stmt.body)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # a closure that captures a tracked resource adopts it (the
            # lsm iter_range idiom: the generator's `with f:` owns the
            # close) — ownership leaves this frame
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Name) and sub.id in self.open:
                    self._escape(sub.id)
            return
        if isinstance(stmt, ast.Try):
            # close()s in the finally protect the try body's exits; a
            # raise under an except handler may be caught locally, so
            # raise-exits inside the body go lenient
            finally_closed = self._closed_vars(stmt.finalbody)
            added = finally_closed - self.protected
            self.protected |= added
            base = dict(self.open)  # pre-try state: what handlers see
            if stmt.handlers:
                self.handler_depth += 1
            self.walk(stmt.body)
            if stmt.handlers:
                self.handler_depth -= 1
            after_body = dict(self.open)
            for handler in stmt.handlers:
                # a handler runs when the try body failed PART WAY —
                # resources the body acquired may not exist, so the
                # handler is judged against the pre-try state only
                self.open = dict(base)
                self.walk(handler.body)
            self.open = after_body
            self.walk(stmt.orelse)
            self.protected -= added
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr_calls(stmt.value)
                self._escapes_in(stmt.value)
            self._exit_point(stmt.lineno,
                             f"returns at line {stmt.lineno}")
            return
        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._expr_calls(stmt.exc)
                self._escapes_in(stmt.exc)
            if self.handler_depth == 0:
                self._exit_point(
                    stmt.lineno, f"raises at line {stmt.lineno}"
                )
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assignment(stmt)
            return
        if isinstance(stmt, ast.Expr):
            self._expr_calls(stmt.value)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr_calls(stmt.test)
            base = dict(self.open)
            self.walk(stmt.body)
            after_body = dict(self.open)
            self.open = dict(base)
            self.walk(stmt.orelse)
            # closed-in-any-arm leniency: keep only resources still
            # open after BOTH arms
            self.open = {
                k: v for k, v in after_body.items() if k in self.open
            }
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr_calls(stmt.iter)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.Assert, ast.Delete)):
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    self._handle_call(sub)
            return
        # Pass/Break/Continue/Global/Import: nothing tracked

    def _assignment(self, stmt: ast.stmt) -> None:
        value = getattr(stmt, "value", None)
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        if value is None:
            return
        # `t.daemon = True` after construction lifts the join obligation
        if len(targets) == 1 and isinstance(targets[0], ast.Attribute):
            tgt = targets[0]
            if (
                tgt.attr == "daemon"
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id in self.open
                and self.open[tgt.value.id].kind == "thread"
                and not (
                    isinstance(value, ast.Constant)
                    and value.value is False
                )
            ):
                self.open.pop(tgt.value.id, None)
                return
        kind = _acquisition_kind(value, self.allocators, self.resolve)
        if kind is not None and len(targets) == 1:
            tgt = targets[0]
            if isinstance(tgt, ast.Name):
                # re-assigning over a still-open resource loses it
                prev = self.open.get(tgt.id)
                if prev is not None and tgt.id not in self.protected:
                    if not (prev.kind == "thread" and not prev.started):
                        self._leak(
                            prev, stmt.lineno,
                            f"is overwritten at line {stmt.lineno}",
                        )
                    self.open.pop(tgt.id, None)
                res = _Resource(tgt.id, kind, stmt.lineno)
                # classify the acquisition call's own arguments FIRST:
                # a tracked resource fed INTO the new one transfers
                # ownership (`f = os.fdopen(fd)` — f.close() closes fd;
                # `Thread(args=(sock,))` — the worker owns the socket,
                # daemon or not)
                if isinstance(value, ast.Call):
                    self._handle_call(value)
                if kind == "thread" and isinstance(value, ast.Call):
                    res.daemon_thread = _thread_is_daemon(value)
                    if res.daemon_thread:
                        return  # daemon threads carry no join obligation
                self.open[tgt.id] = res
                return
            if isinstance(tgt, ast.Tuple) and tgt.elts:
                # conn, addr = sock.accept()
                first = tgt.elts[0]
                if isinstance(first, ast.Name):
                    self.open[first.id] = _Resource(
                        first.id, kind, stmt.lineno
                    )
                return
            # acquired straight into self.attr / a container: escaped
            # at birth — the owner is the object, not this frame
            self._expr_calls(value)
            return
        # plain assignment: tracked vars on the RHS escape (alias,
        # store, arithmetic into a struct...) — unless it is a pure
        # self-alias we keep tracking under the new name? No: lenient.
        self._expr_calls(value)
        self._escapes_in(value)

    def _expr_calls(self, node: ast.expr) -> None:
        if node is None:
            return
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._handle_call(sub)

    def _closed_vars(self, body: list[ast.stmt]) -> set[str]:
        out: set[str] = set()
        for stmt in body:
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = _dotted(sub.func)
                tail = dotted.rsplit(".", 1)[-1]
                if tail not in _CLOSE_TAILS:
                    continue
                if (
                    dotted.startswith("os.")
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                ):
                    out.add(sub.args[0].id)  # os.close(fd) closes FD
                elif isinstance(sub.func, ast.Attribute) and isinstance(
                    sub.func.value, ast.Name
                ):
                    out.add(sub.func.value.id)
                elif sub.args and isinstance(sub.args[0], ast.Name):
                    out.add(sub.args[0].id)
        return out

    def finish(self, fn: ast.FunctionDef) -> None:
        end = getattr(fn, "end_lineno", fn.lineno)
        self._exit_point(end, "falls off the end of the function")


# ---------------------------------------------------------------------------
# interprocedural summaries


def _owns_annotations(source: str) -> dict[int, set[str]]:
    """line -> param names force-marked as ownership-transfer. The
    annotation sits on the `def` line or the line above it; a missing
    reason is reported through the standard bare-ignore channel by
    scan_suppressions-alike strictness here (no reason → ignored
    annotation, which then surfaces as the finding it would have
    silenced)."""
    out: dict[int, set[str]] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _OWNS_RE.search(text)
        if m is None or not (m.group(2) or "").strip():
            continue
        params = {p.strip() for p in m.group(1).split(",") if p.strip()}
        target = i + 1 if text.lstrip().startswith("#") else i
        out.setdefault(target, set()).update(params)
    return out


def _make_resolver(index: PackageIndex, rec):
    """Callee-reference resolver mirroring lockorder's strategy:
    self-methods, module functions, package-unique method names."""
    from seaweedfs_tpu.analysis.lockorder import _BUILTIN_METHODS

    cls = index.func_cls.get(rec.qualname)

    def resolve(fn_expr: ast.expr) -> str | None:
        if isinstance(fn_expr, ast.Name):
            return index.module_funcs.get((rec.module, fn_expr.id))
        if isinstance(fn_expr, ast.Attribute):
            if (
                isinstance(fn_expr.value, ast.Name)
                and fn_expr.value.id == "self"
                and cls is not None
            ):
                return cls.methods.get(fn_expr.attr)
            cands = index.methods_by_name.get(fn_expr.attr, [])
            if len(cands) == 1 and fn_expr.attr not in _BUILTIN_METHODS:
                return cands[0]
        return None

    return resolve


def _build_summaries(index: PackageIndex) -> tuple[
    dict[str, FuncSummary], dict[str, str]
]:
    """(summaries by qualname, allocators qual->kind) to fixpoint."""
    summaries: dict[str, FuncSummary] = {}
    allocators: dict[str, str] = {}
    owns_by_path: dict[str, dict[int, set[str]]] = {}
    for rel, src in index.sources.items():
        ann = _owns_annotations(src)
        if ann:
            owns_by_path[rel] = ann

    for qual, fn in index.fn_nodes.items():
        rec = index.funcs.get(qual)
        if rec is None:
            continue
        s = FuncSummary(qual)
        ann = owns_by_path.get(rec.path, {}).get(fn.lineno, set())
        s.owns_params |= ann & set(rec.params)
        summaries[qual] = s

    # fixpoint: allocators (returns a fresh resource) and param
    # ownership (closes/stores/forwards its param)
    for _ in range(10):
        changed = False
        for qual, fn in index.fn_nodes.items():
            rec = index.funcs.get(qual)
            if rec is None:
                continue
            s = summaries[qual]
            resolve = _make_resolver(index, rec)
            params = set(rec.params)
            # vars assigned from an acquisition call anywhere in the
            # body: `fd = os.open(...)` ... `return fd` is an allocator
            acquired_vars: dict[str, str] = {}
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                ):
                    kind = _acquisition_kind(
                        node.value, allocators, resolve
                    )
                    if kind:
                        acquired_vars[node.targets[0].id] = kind
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    kind = _acquisition_kind(
                        node.value, allocators, resolve
                    )
                    if kind is None and isinstance(
                        node.value, ast.Name
                    ):
                        kind = acquired_vars.get(node.value.id)
                    if kind and s.allocates is None:
                        s.allocates = kind
                        allocators[qual] = kind
                        changed = True
                    # `return fd` where fd is a param: caller keeps it
                    # (builder idiom) — treat as borrow, not own
                if not isinstance(node, ast.Call):
                    continue
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                # param.close()/param.join() → owns
                if (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in params
                    and tail in _CLOSE_TAILS
                    and node.func.value.id not in s.owns_params
                ):
                    s.owns_params.add(node.func.value.id)
                    changed = True
                # os.close(param) → owns
                if (
                    tail == "close"
                    and _dotted(node.func).startswith("os.")
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in params
                    and node.args[0].id not in s.owns_params
                ):
                    s.owns_params.add(node.args[0].id)
                    changed = True
                # param forwarded to a callee that owns it → owns
                ref = resolve(node.func)
                callee = summaries.get(ref) if ref else None
                if callee is not None:
                    callee_rec = index.funcs.get(ref)
                    for i, a in enumerate(node.args):
                        if (
                            isinstance(a, ast.Name)
                            and a.id in params
                            and callee_rec is not None
                            and i < len(callee_rec.params)
                            and callee_rec.params[i] in callee.owns_params
                            and a.id not in s.owns_params
                        ):
                            s.owns_params.add(a.id)
                            changed = True
            # param stored on self / into a container → owns
            for node in ast.walk(fn):
                tgt = None
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, (ast.Attribute, ast.Subscript)):
                            tgt = node.value
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ) and node.func.attr in ("append", "add", "put"):
                    for a in node.args:
                        if isinstance(a, ast.Name) and a.id in params:
                            tgt = a
                if isinstance(tgt, ast.Name) and tgt.id in params:
                    if tgt.id not in s.owns_params:
                        s.owns_params.add(tgt.id)
                        changed = True
        if not changed:
            break

    # borrows = params that are USED but never owned (used at all so a
    # never-touched param doesn't read as a safe sink)
    for qual, fn in index.fn_nodes.items():
        rec = index.funcs.get(qual)
        if rec is None:
            continue
        s = summaries[qual]
        used = {
            n.id
            for n in ast.walk(fn)
            if isinstance(n, ast.Name) and n.id in set(rec.params)
        }
        s.borrows_params = used - s.owns_params
    return summaries, allocators


# ---------------------------------------------------------------------------
# entry point


def check(root: str | None = None, index: PackageIndex | None = None
          ) -> tuple[list[Finding], PackageIndex]:
    index = index or build_index(root)
    summaries, allocators = _build_summaries(index)
    findings: list[Finding] = []
    for qual, fn in sorted(index.fn_nodes.items()):
        rec = index.funcs.get(qual)
        if rec is None:
            continue
        resolve = _make_resolver(index, rec)
        walker = _LeakWalker(
            qual, rec.path, summaries, allocators, resolve,
            funcs=index.funcs,
        )
        walker.walk(fn.body)
        walker.finish(fn)
        findings.extend(walker.findings)
    # dedupe (same resource can be reported from several exits)
    seen: set[tuple[str, int, str]] = set()
    out: list[Finding] = []
    for f in findings:
        key = (f.path, f.line, f.rule)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out, index
