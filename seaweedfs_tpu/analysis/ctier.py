"""C-tier hardening checks: the compiler as the shims' lint pass.

The native shims (crc32c.c, gf256.c, needle.c, post.c behind the
needle_ext.c binding) are the one part of the tree no Python-level
tool can see into — and the part that parses adversarial multipart
bytes with the GIL released. Three checks:

  c-warnings     every shim must compile clean under
                 -Wall -Wextra -Werror with the system compiler
                 (the same flags _build.py now ships with, so a
                 warning can never reach production silently — it
                 fails the build into the pure-Python fallback);
                 with WEED_NATIVE_SAN set, the sanitizer variant of
                 the build is what gets exercised
  gil-release    the extension's hot entry points (encode's big-
                 payload branch, decode's big-payload CRC, the whole
                 post span) must wrap their C work in
                 Py_BEGIN/END_ALLOW_THREADS — losing one of those
                 re-serializes every handler thread behind memcpy+CRC
  no-compiler    reported as a note, never a failure: hosts without a
                 toolchain run the pure-Python fallbacks and have no C
                 attack surface to lint
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import tempfile

from seaweedfs_tpu.analysis import Finding
from seaweedfs_tpu.native import _build

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native"
)

# (source, needs_python_includes); needle.c and post.c compile as part
# of the needle_ext.c translation unit, exactly as production builds them
_UNITS = (
    ("crc32c.c", False),
    ("gf256.c", False),
    ("needle_ext.c", True),
    ("serve_ext.c", True),
    ("syscount.c", False),
)


def _compiler() -> str | None:
    for cc in _build._COMPILERS:
        try:
            proc = subprocess.run(
                [cc, "--version"], capture_output=True, timeout=10
            )
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return cc
    return None


def _rel(name: str) -> str:
    return os.path.join("seaweedfs_tpu", "native", name)


def check_warnings() -> list[Finding]:
    cc = _compiler()
    if cc is None:
        return []  # no toolchain: pure-Python fallbacks serve, nothing to lint
    # the sanitizer mode labels the finding: compile_cmd builds the
    # same variant, so a rejection message must say WHICH build broke.
    # (this line also fixes a latent NameError: the f-string below read
    # `mode` that no path ever defined — reachable only on a failing
    # compile, which is exactly when the diagnostics matter most)
    mode = _build.san_mode()
    paths = sysconfig.get_paths()
    py_inc = tuple(
        dict.fromkeys((paths["include"], paths["platinclude"]))
    )
    findings: list[Finding] = []
    for src, needs_py in _UNITS:
        out = tempfile.NamedTemporaryFile(suffix=".so", delete=False)
        out.close()
        # the shared helper IS the production command line — the lint
        # tier compiles exactly what load_ext ships
        cmd = _build.compile_cmd(
            cc,
            os.path.join(_NATIVE_DIR, src),
            out.name,
            includes=py_inc if needs_py else (),
        )
        try:
            proc = subprocess.run(cmd, capture_output=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired) as e:
            findings.append(
                Finding("c-warnings", _rel(src), 1, f"compile failed: {e}")
            )
            continue
        finally:
            try:
                os.unlink(out.name)
            except OSError:
                pass
        if proc.returncode != 0:
            # surface the first few diagnostic lines with their own
            # file:line so the finding is actionable
            diag = proc.stderr.decode("utf-8", "replace")
            lines = [
                ln
                for ln in diag.splitlines()
                if ": error:" in ln or ": warning:" in ln
            ][:8] or diag.splitlines()[:4]
            findings.append(
                Finding(
                    "c-warnings",
                    _rel(src),
                    1,
                    f"{cc} -Wall -Wextra -Werror"
                    + (f" [{mode}]" if mode else "")
                    + " rejected the unit: "
                    + " | ".join(ln.strip() for ln in lines),
                )
            )
    return findings


# entry point -> marker that must appear between its definition and the
# next top-level definition (structural, not a parse: the shims are
# plain C with one function per concern)
_GIL_SPANS = (
    ("py_encode", "needle_ext.c"),
    ("py_decode", "needle_ext.c"),
    ("py_post", "needle_ext.c"),
    # the serving loop parks in epoll_wait for whole idle windows —
    # holding the GIL there would freeze every handler thread in the
    # process for the duration
    ("py_loop", "serve_ext.c"),
)


def check_gil_release() -> list[Finding]:
    findings: list[Finding] = []
    sources: dict[str, str] = {}
    for _, src_name in _GIL_SPANS:
        if src_name in sources:
            continue
        try:
            with open(
                os.path.join(_NATIVE_DIR, src_name), "r", encoding="utf-8"
            ) as f:
                sources[src_name] = f.read()
        except OSError:
            sources[src_name] = ""
    for fn, src_name in _GIL_SPANS:
        source = sources[src_name]
        if not source:
            continue
        start = source.find(f"*{fn}(")
        if start < 0:
            findings.append(
                Finding(
                    "gil-release",
                    _rel(src_name),
                    1,
                    f"hot entry point {fn}() not found in {src_name}",
                )
            )
            continue
        # the function body runs to the next PyObject * definition
        end = source.find("static PyObject *", start + 1)
        body = source[start : end if end > 0 else len(source)]
        if "Py_BEGIN_ALLOW_THREADS" not in body:
            line = source[:start].count("\n") + 1
            findings.append(
                Finding(
                    "gil-release",
                    _rel(src_name),
                    line,
                    f"{fn}() never releases the GIL: its C span "
                    f"serializes every handler thread behind the "
                    f"memcpy/CRC work",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# shm-atomics: the GCRA bucket's cross-process protocol (weedrace v4)

# expressions that READ or WRITE through the shared tat slot array;
# `&weed_shm.tat[` (address-of, the slot-pointer computation) and
# assignment to the `weed_shm.tat` pointer itself are not accesses
_SLOT_ACCESS = ("*slot", "slot[", "weed_shm.tat[")


def check_shm_atomics(
    source: str | None = None,
    rel_path: str = os.path.join("seaweedfs_tpu", "native", "serve.c"),
) -> list[Finding]:
    """Every access to the mmap'd GCRA slot array must be a C11/GCC
    atomic builtin with an EXPLICIT memory order. The bucket is the one
    piece of state shared across `-workers` sibling PROCESSES with no
    lock (that lock-freedom is its crash-safety story — a sibling
    SIGKILLed mid-admit holds nothing), so a single plain load or store
    is a data race the compiler may tear, cache, or reorder at will.
    Structural, statement-granular: a statement touching `*slot` /
    `slot[...]` / `weed_shm.tat[...]` must name `__atomic_*` and an
    `__ATOMIC_` order. `source` overrides the tree's serve.c so the
    planted-bug arm (bench --check race leg) can prove the rule fires
    on a plain-store mutant."""
    if source is None:
        try:
            with open(
                os.path.join(_NATIVE_DIR, "serve.c"), "r", encoding="utf-8"
            ) as f:
                source = f.read()
        except OSError:
            return []  # no serve.c shipped: nothing to check
    findings: list[Finding] = []
    # statement granularity: split on ';' but keep line accounting
    line = 1
    for stmt in source.split(";"):
        stmt_line = line
        line += stmt.count("\n")
        # exempt the address-of slot-pointer computation and the
        # declaration whose `*` is part of the type, not a deref
        probe = stmt.replace("&weed_shm.tat[", "").replace(
            "int64_t *slot", ""
        )
        if not any(p in probe for p in _SLOT_ACCESS):
            continue
        # find the line of the first access within the statement
        first = min(
            (probe.find(p) for p in _SLOT_ACCESS if p in probe),
        )
        at = stmt_line + probe[:first].count("\n")
        if "__atomic_" not in stmt or "__ATOMIC_" not in stmt:
            findings.append(
                Finding(
                    "shm-atomics",
                    rel_path,
                    at,
                    "GCRA shm slot accessed without a C11 atomic "
                    "builtin + explicit memory order: a plain "
                    "load/store on cross-process mmap state is a data "
                    "race the compiler may tear or reorder "
                    "(docs/ANALYSIS.md v4, shm-atomics)",
                )
            )
    return findings


def check() -> list[Finding]:
    return check_warnings() + check_gil_release() + check_shm_atomics()
