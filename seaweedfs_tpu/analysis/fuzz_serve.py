"""Structured fuzzer: the C epoll serving loop vs the threaded mini loop.

native/serve.c re-implements the serving edge — request-head scanning,
keep-alive/pipelining bookkeeping, the zero-copy GET fast path, and
the Connection/Content-Length response tail — all byte-contracted to
the pure-Python path (util/httpd.serve_connection + the volume
server's do_GET): for any request stream the C loop either serves
bytes IDENTICAL to what the threaded loop serves, or hands the
connection off so the threaded loop serves it directly.  This driver
generates adversarial request streams — pipelined bursts, fragmented
and torn heads, hostile Range forms, conditional headers, garbage
request lines, oversized heads, half-closed connections — plays each
stream against TWO live servers over one shared volume store (one on
the epoll loop, one pinned to the threaded path), and diffs every
byte that comes back.

Crash persistence mirrors fuzz_post: each case is written to the
corpus directory BEFORE it is driven, so a segfaulting input survives
the dead process; diverging inputs persist as regression entries
under tests/corpus/serve/ and tests/test_native_serve.py sweeps them
on every tier-1 run.

    python -m seaweedfs_tpu.analysis.fuzz_serve --n 200 --seed 7
    python -m seaweedfs_tpu.analysis.fuzz_serve --seed-corpus
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import random
import socket
import tempfile
import threading
import time
from dataclasses import dataclass, field

from seaweedfs_tpu.analysis import REPO_ROOT

DEFAULT_CORPUS = os.path.join(REPO_ROOT, "tests", "corpus", "serve")


# ---------------------------------------------------------------------------
# fixture: one store, two servers (epoll arm + threaded arm)


class ServePair:
    """A volume store served by two HTTP servers at once: `c_port`
    drives the native epoll loop, `py_port` is pinned to the threaded
    mini loop. The store is written once (deterministic timestamps)
    and every fuzz case reads through both."""

    def __init__(
        self, workdir: str, serve_idle_ms: int = 0, serve_max_reqs: int = 0
    ):
        from seaweedfs_tpu.server.volume_server import VolumeServer
        from seaweedfs_tpu.storage.file_id import format_needle_id_cookie
        from seaweedfs_tpu.storage.needle import Needle
        from seaweedfs_tpu.util import native_serve
        from seaweedfs_tpu.util.httpd import WeedHTTPServer

        self.native_ok = native_serve.available()
        vol_dir = os.path.join(workdir, "vols")
        os.makedirs(vol_dir)
        self.vs = VolumeServer([vol_dir], port=0, scrub_interval=0)
        self.vs.store.add_volume(1, "", "000", "")
        v = self.vs.store.find_volume(1)

        def put(key, cookie, data, **attrs):
            n = Needle(cookie=cookie, id=key, data=data)
            n.last_modified = 1_700_000_000 + key
            n.set_has_last_modified_date()
            for a, val in attrs.items():
                setattr(n, a, val)
            v.write_needle(n)
            return f"1,{format_needle_id_cookie(key, cookie)}"

        rnd = random.Random(42)
        self.fids = {
            "small": put(1, 0x11111111, rnd.randbytes(700)),
            "tiny": put(2, 0x22222222, b"x"),
            "empty": put(3, 0x33333333, b""),
            "big": put(4, 0x44444444, rnd.randbytes(100_000)),
            "edge64k": put(5, 0x55555555, rnd.randbytes(65_530)),
        }
        # flag-bearing needles: the resolver pre-renders Content-Type /
        # Content-Disposition so these stay on the C fast path too
        n = Needle(cookie=0x66666666, id=6, data=b"named blob")
        n.last_modified = 1_700_000_006
        n.set_has_last_modified_date()
        n.name = b"f.bin"
        n.set_has_name()
        v.write_needle(n)
        self.fids["named"] = f"1,{format_needle_id_cookie(6, 0x66666666)}"
        n = Needle(cookie=0x88888888, id=8, data=b"<p>mime blob</p>")
        n.last_modified = 1_700_000_008
        n.set_has_last_modified_date()
        n.mime = b"text/html"
        n.set_has_mime()
        v.write_needle(n)
        self.fids["mime"] = f"1,{format_needle_id_cookie(8, 0x88888888)}"
        # a deleted needle (tombstone) and a never-written fid
        fid_gone = put(7, 0x77777777, b"doomed")
        v.delete_needle(Needle(cookie=0x77777777, id=7))
        self.fids["deleted"] = fid_gone
        self.fids["missing"] = f"1,{format_needle_id_cookie(99, 0xABCD1234)}"
        self.fids["badcookie"] = f"1,{format_needle_id_cookie(1, 0xDEADBEEF)}"

        handler = self.vs._http_handler_class()
        resolver = self.vs._make_fast_resolver()
        self.servers = []
        ports = []
        for native in (True, False):
            srv = WeedHTTPServer(("127.0.0.1", 0), handler)
            srv.trace_name = "volume"
            srv.trace_node = "fuzz"
            srv.fast_resolver = resolver
            srv.native_serve = native
            srv.serve_idle_ms = serve_idle_ms
            srv.serve_max_reqs = serve_max_reqs
            t = threading.Thread(target=srv.serve_forever, daemon=True)
            t.start()
            self.servers.append(srv)
            ports.append(srv.server_address[1])
        self.c_port, self.py_port = ports
        time.sleep(0.1)

    def close(self) -> None:
        for srv in self.servers:
            srv.shutdown()
            srv.server_close()
        self.vs.store.close()


# ---------------------------------------------------------------------------
# case generation

_RANGES = [
    "bytes=0-0", "bytes=0-99", "bytes=100-199", "bytes=-1", "bytes=-100",
    "bytes=-999999", "bytes=699-", "bytes=700-", "bytes=0-",
    "bytes=5-2", "bytes=abc", "bytes=", "bytes=1-2,5-6", "bits=0-1",
    "bytes= 0 - 9", "bytes=00000000000000000001-2", "bytes=-0",
    "bytes=0-99999999999999999999", "BYTES=0-1", "bytes=65529-",
]

_INM_VALUES = [
    '"x"', "*", "", '"067c9745"', 'W/"067c9745"', 'W/"x"',
    '"a", "067c9745"', '"a", "b", "c"', '"unterminated', "W/",
    "067c9745", '  "067c9745"  ', '"067c9745",', ',"067c9745"',
]

_JUNK_LINES = [
    b"NOT A REQUEST\r\n\r\n",
    b"GET\r\n\r\n",
    b"GET /status FTP/9\r\n\r\n",
    b"GET  /status HTTP/1.1\r\n\r\n",
    b"G\x00T / HTTP/1.1\r\n\r\n",
    b"GET /status HTTP/1.1\r\nbad header line\r\n\r\n",
    b"GET /status HTTP/1.1\r\n: empty\r\n\r\n",
    b"\r\n\r\n",
]


def gen_case(rng: random.Random, fids: dict) -> dict:
    """One adversarial connection: {'fragments': [bytes...]} — the
    stream is sent fragment by fragment, then the write side closes."""
    reqs: list[bytes] = []
    n_reqs = rng.randrange(1, 5)
    fid_pool = list(fids.values())
    for _ in range(n_reqs):
        kind = rng.randrange(12)
        if kind == 0:
            reqs.append(rng.choice(_JUNK_LINES))
            break  # the connection dies here on both arms
        method = rng.choice(["GET", "GET", "GET", "HEAD", "BREW", "OPTIONS"])
        path = rng.choice(
            fid_pool
            + [
                "status", "metrics-not", "", "1,zz", "1", "1,",
                fid_pool[0] + "/name.txt", fid_pool[0] + ".bin",
                fid_pool[0] + "?dl=true", "%2e%2e", "a" * 300,
            ]
        )
        version = rng.choice(["HTTP/1.1"] * 4 + ["HTTP/1.0", "HTTP/2"])
        lines = [f"{method} /{path} {version}"]
        if rng.random() < 0.6:
            lines.append(f"Range: {rng.choice(_RANGES)}")
        if rng.random() < 0.15:
            lines.append(f"Range: {rng.choice(_RANGES)}")  # duplicate
        if rng.random() < 0.2:
            lines.append(
                "Connection: " + rng.choice(["close", "keep-alive", "Close",
                                             "upgrade", ""])
            )
        if rng.random() < 0.25:
            # "067c9745" is the deterministic ETag of the `small` needle:
            # against the live store these hit the C 304 arm for real
            lines.append("If-None-Match: " + rng.choice(_INM_VALUES))
        if rng.random() < 0.05:
            lines.append("If-None-Match: " + rng.choice(_INM_VALUES))  # dup
        if rng.random() < 0.1:
            lines.append("If-Modified-Since: Thu, 01 Jan 1970 00:00:00 GMT")
        if rng.random() < 0.1:
            lines.append("Etag-Md5: True")
        if rng.random() < 0.15:
            lines.append(
                "X-Weed-Trace: "
                + rng.choice(
                    ["0123456789abcdef0123456789abcdef:01234567:serve",
                     "garbage", "%s:%s:%s", ""]
                )
            )
        if rng.random() < 0.1:
            lines.append("Content-Length: " + rng.choice(["0", "00", "5"]))
        if rng.random() < 0.05:
            lines.append("Expect: 100-continue")
        if rng.random() < 0.05:
            lines.append("X-Fill: " + "a" * rng.randrange(1, 4000))
        head = "\r\n".join(lines).encode("latin-1", "replace") + b"\r\n\r\n"
        reqs.append(head)
    stream = b"".join(reqs)
    if rng.random() < 0.15 and len(stream) > 4:
        stream = stream[: rng.randrange(1, len(stream))]  # torn head/stream
    # fragment at random cut points so heads straddle recv() calls
    fragments: list[bytes] = []
    if rng.random() < 0.5:
        pos = 0
        while pos < len(stream):
            step = rng.randrange(1, max(2, min(len(stream) - pos + 1, 80)))
            fragments.append(stream[pos : pos + step])
            pos += step
    else:
        fragments = [stream]
    return {"fragments": fragments}


def case_to_json(case: dict) -> str:
    return json.dumps(
        {
            "fragments": [
                base64.b64encode(f).decode() for f in case["fragments"]
            ]
        },
        indent=0,
    )


def case_from_json(text: str) -> dict:
    obj = json.loads(text)
    return {
        "fragments": [base64.b64decode(f) for f in obj["fragments"]]
    }


def _case_name(case: dict, prefix: str) -> str:
    digest = hashlib.sha256(b"\x00".join(case["fragments"])).hexdigest()[:12]
    return f"{prefix}_{digest}.json"


# ---------------------------------------------------------------------------
# the identity oracle


def drive(port: int, case: dict, deadline_s: float = 5.0) -> bytes:
    """Play the case's fragments at 127.0.0.1:port (write side closed
    after the last fragment) and return every response byte."""
    s = socket.create_connection(("127.0.0.1", port), timeout=deadline_s)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
    try:
        frags = case["fragments"]
        for i, frag in enumerate(frags):
            try:
                s.sendall(frag)
            except OSError:
                break  # server already slammed the door (431/garbage)
            if len(frags) > 1 and i % 3 == 2:
                time.sleep(0.002)  # force separate recv()s server-side
        try:
            s.shutdown(socket.SHUT_WR)
        except OSError:
            pass
        out = b""
        end = time.monotonic() + deadline_s
        while time.monotonic() < end:
            s.settimeout(max(0.05, end - time.monotonic()))
            try:
                chunk = s.recv(1 << 20)
            except socket.timeout:
                break
            except OSError:
                break
            if not chunk:
                break
            out += chunk
        return out
    finally:
        s.close()


def run_case(pair: ServePair, case: dict) -> str | None:
    """None, or a divergence description. Drives the C arm first (a
    crash must implicate the native loop, not the control)."""
    c_bytes = drive(pair.c_port, case)
    py_bytes = drive(pair.py_port, case)
    if c_bytes != py_bytes:
        i = next(
            (k for k, (a, b) in enumerate(zip(c_bytes, py_bytes)) if a != b),
            min(len(c_bytes), len(py_bytes)),
        )
        return (
            f"response bytes diverge at offset {i}: "
            f"C[{len(c_bytes)}B]={c_bytes[max(0, i - 20) : i + 40]!r} "
            f"PY[{len(py_bytes)}B]={py_bytes[max(0, i - 20) : i + 40]!r}"
        )
    return None


@dataclass
class FuzzReport:
    iterations: int = 0
    divergences: list[str] = field(default_factory=list)
    corpus_written: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "iterations": self.iterations,
            "divergences": self.divergences,
            "corpus_written": self.corpus_written,
        }


def run(
    iterations: int = 100,
    seed: int = 0,
    corpus_dir: str | None = None,
    persist_divergent: bool = True,
) -> FuzzReport:
    rng = random.Random(seed)
    report = FuzzReport()
    corpus_dir = corpus_dir or DEFAULT_CORPUS
    os.makedirs(corpus_dir, exist_ok=True)
    pending = os.path.join(corpus_dir, f"pending_{seed}.json")
    with tempfile.TemporaryDirectory(prefix="weedfuzzserve") as workdir:
        pair = ServePair(workdir)
        try:
            if not pair.native_ok:
                return report  # no native loop on this host: nothing to diff
            for i in range(iterations):
                case = gen_case(rng, pair.fids)
                # persist BEFORE driving: a C crash leaves the repro
                with open(pending, "w", encoding="utf-8") as f:
                    f.write(case_to_json(case))
                report.iterations += 1
                divergence = run_case(pair, case)
                if divergence is not None:
                    report.divergences.append(
                        f"iter {i} (seed {seed}): {divergence}"
                    )
                    if persist_divergent:
                        name = _case_name(case, "div")
                        # weedlint: ignore[crash-rename-no-dirsync,crash-rename-unsynced-src] — forensic corpus artifact; persistence is best-effort and the fuzzer reruns
                        os.replace(
                            pending, os.path.join(corpus_dir, name)
                        )
                        report.corpus_written.append(name)
        finally:
            pair.close()
            try:
                os.remove(pending)
            except OSError:
                pass
    return report


def _handcrafted_cases() -> list[dict]:
    """Deterministic conditional-GET streams against the fixed ServePair
    store: "067c9745" is the real ETag of `small` (1,0111111111) and
    1,0666666666 is the name-flagged needle — these pin the C 304 arm,
    If-None-Match-beats-Range, and pipelined-304 keep-alive accounting
    as replayable corpus entries."""
    small, named, mime = "1,0111111111", "1,0666666666", "1,0888888888"

    def get(path, *headers):
        head = f"GET /{path} HTTP/1.1\r\n"
        head += "".join(h + "\r\n" for h in headers)
        return (head + "\r\n").encode()

    match = 'If-None-Match: "067c9745"'
    cond_then_plain = get(small, match) + get(small)
    inm_beats_range = (
        get(small, "Range: bytes=0-9", 'If-None-Match: W/"067c9745"')
        + get(small, "Range: bytes=0-9")
    )
    pipelined_304 = (
        get(small, match)
        + get(small, 'If-None-Match: "zz", "067c9745"')
        + get(small, "If-None-Match: *")
        + get(named, "If-None-Match: *")
        + get(mime)
        + get(small, 'If-None-Match: "zz"', "Connection: close")
    )
    # fragment the pipelined stream so a 304 head straddles recv() calls
    cuts = [0, 7, 41, 42, len(pipelined_304) // 2, len(pipelined_304)]
    fragmented = [
        pipelined_304[a:b] for a, b in zip(cuts, cuts[1:]) if b > a
    ]
    return [
        {"fragments": [cond_then_plain]},
        {"fragments": [inm_beats_range]},
        {"fragments": [pipelined_304]},
        {"fragments": fragmented},
    ]


def seed_corpus(
    corpus_dir: str | None = None, seed: int = 20260803, target: int = 16
) -> list[str]:
    """Refresh tests/corpus/serve/ with a deterministic spread of
    request-stream shapes (pipelined/fragmented/torn × Range forms)."""
    rng = random.Random(seed)
    corpus_dir = corpus_dir or DEFAULT_CORPUS
    os.makedirs(corpus_dir, exist_ok=True)
    fids = {  # the ServePair store is deterministic: these fids are real
        "small": "1,0111111111",
        "big": "1,0444444444",
    }
    written: list[str] = []
    for case in _handcrafted_cases():
        name = _case_name(case, "cond")
        with open(os.path.join(corpus_dir, name), "w", encoding="utf-8") as f:
            f.write(case_to_json(case))
        written.append(name)
    seen: set[tuple] = set()
    guard = 0
    while len(written) < target and guard < 10000:
        guard += 1
        case = gen_case(rng, fids)
        stream = b"".join(case["fragments"])
        kind = (
            len(case["fragments"]) > 1,
            stream.count(b"\r\n\r\n") % 4,
            b"Range" in stream,
            b"HTTP/1.0" in stream,
        )
        if kind in seen:
            continue
        seen.add(kind)
        name = _case_name(case, "seed")
        with open(os.path.join(corpus_dir, name), "w", encoding="utf-8") as f:
            f.write(case_to_json(case))
        written.append(name)
    return written


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="fuzz the C epoll serving loop against the threaded "
        "mini loop (byte identity over live sockets)"
    )
    ap.add_argument("--n", type=int, default=100, help="iterations to run")
    ap.add_argument("--seed", type=int, default=0, help="rng seed")
    ap.add_argument(
        "--corpus",
        default=None,
        help="corpus dir for crash/divergence persistence "
        "(default tests/corpus/serve)",
    )
    ap.add_argument(
        "--seed-corpus",
        action="store_true",
        help="write the deterministic seed corpus and exit",
    )
    args = ap.parse_args(argv)
    if args.seed_corpus:
        for name in seed_corpus(args.corpus):
            print(name)
        return 0
    report = run(iterations=args.n, seed=args.seed, corpus_dir=args.corpus)
    print(json.dumps(report.to_dict(), indent=2))
    return 1 if report.divergences else 0


if __name__ == "__main__":
    raise SystemExit(main())
