"""Dynamic lock-order witness: the port's `-race`-style runtime check.

The static pass (analysis/lockorder.py) proves what it can resolve;
everything that flows through callbacks, cross-object references, or
data-dependent dispatch only materializes at runtime. This module
wraps `threading.Lock`/`threading.RLock` ALLOCATION — only for locks
created from files inside this repository, so third-party locks cost
nothing and contribute no noise — and records, per thread, the order
in which witnessed locks nest. Lock identity is the allocation site
(file:line), the same "lock class" granularity the Linux kernel's
lockdep uses: two SharedReadVolume instances share one node, so an
inversion between *classes* of locks is caught even when the two
offending runs touched different instances.

On every acquisition that nests inside held locks, the witness adds
edges held→new to a global order graph. An edge whose REVERSE
direction is already reachable is an inversion: two threads have now
demonstrated both A→B and B→A nesting, which is exactly the deadlock
recipe — it only needs the right interleaving to stick. The witness
records both stacks and the pytest plugin (tests/conftest.py) fails
the test that completed the cycle, tier-1-wide, by default
(WEED_LOCK_WITNESS=0 disables).

Same-site edges (instance A then instance B of the same lock class)
are ignored, as lockdep does without nesting annotations: the
volume-workers design acquires sibling SharedReadVolume locks only
sequentially, never nested, and per-instance tracking would need
object identity that outlives the objects.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock

_state_lock = _RAW_LOCK()
_edges: dict[str, set[str]] = {}  # site -> sites acquired while held
_edge_examples: dict[tuple[str, str], str] = {}
_inversions: list[dict] = []
_installed = False

_tls = threading.local()


def _held() -> list:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _reachable(frm: str, to: str) -> bool:
    """True when `to` is reachable from `frm` in the order graph.
    Caller holds _state_lock."""
    seen = {frm}
    stack = [frm]
    while stack:
        node = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == to:
                return True
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def _note_acquired(site: str) -> None:
    held = _held()
    if held:
        new_edges = []
        with _state_lock:
            for h in held:
                if h == site:
                    continue
                if site in _edges.get(h, ()):
                    continue
                # adding h→site: if site→…→h already exists, two code
                # paths nest these lock classes in opposite orders
                if _reachable(site, h):
                    _inversions.append(
                        {
                            "held": h,
                            "acquiring": site,
                            "established": _edge_examples.get(
                                (site, h),
                                next(
                                    (
                                        _edge_examples[(a, b)]
                                        for (a, b) in _edge_examples
                                        if a == site
                                    ),
                                    "(indirect path)",
                                ),
                            ),
                            "thread": threading.current_thread().name,
                            "stack": "".join(
                                traceback.format_stack(limit=12)[:-2]
                            ),
                        }
                    )
                new_edges.append((h, site))
            for a, b in new_edges:
                _edges.setdefault(a, set()).add(b)
                _edge_examples.setdefault(
                    (a, b),
                    f"thread {threading.current_thread().name}",
                )
    held.append(site)


def _note_released(site: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


class _WitnessLock:
    """Wrapper around one witnessed Lock/RLock instance. Supports the
    full context-manager + acquire/release surface plus the private
    Condition protocol (_release_save/_acquire_restore/_is_owned) so
    `threading.Condition(witnessed_lock)` keeps the held-stack honest
    across wait()."""

    __slots__ = ("_lk", "_site", "_is_rlock")

    def __init__(self, lk, site: str, is_rlock: bool):
        self._lk = lk
        self._site = site
        self._is_rlock = is_rlock

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._lk.acquire(blocking, timeout)
        if ok:
            _note_acquired(self._site)
        return ok

    def release(self) -> None:
        self._lk.release()
        _note_released(self._site)

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    # --- Condition(lock) protocol ------------------------------------
    def _release_save(self):
        state = (
            self._lk._release_save()
            if self._is_rlock
            else self._lk.release()
        )
        _note_released(self._site)
        return state

    def _acquire_restore(self, state) -> None:
        if self._is_rlock:
            self._lk._acquire_restore(state)
        else:
            self._lk.acquire()
        _note_acquired(self._site)

    def _is_owned(self) -> bool:
        if self._is_rlock:
            return self._lk._is_owned()
        # mirror threading.Condition's fallback probe for plain locks
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True

    def _at_fork_reinit(self) -> None:
        self._lk._at_fork_reinit()
        _tls.held = []

    def __repr__(self) -> str:
        return f"<witnessed {self._lk!r} from {self._site}>"


def _alloc_site() -> str | None:
    """file:line of the Lock()/RLock() call when it lives inside the
    repo (package or tests); None for foreign allocations."""
    frame = sys._getframe(2)
    path = frame.f_code.co_filename
    if not path.startswith(_REPO_ROOT):
        return None
    return f"{os.path.relpath(path, _REPO_ROOT)}:{frame.f_lineno}"


def _lock_factory():
    site = _alloc_site()
    raw = _RAW_LOCK()
    if site is None:
        return raw
    return _WitnessLock(raw, site, is_rlock=False)


def _rlock_factory():
    site = _alloc_site()
    raw = _RAW_RLOCK()
    if site is None:
        return raw
    return _WitnessLock(raw, site, is_rlock=True)


def install() -> None:
    """Patch threading.Lock/RLock. Locks allocated BEFORE install (or
    from outside the repo) stay raw — the witness is additive, never
    load-bearing. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    threading.Lock = _RAW_LOCK
    threading.RLock = _RAW_RLOCK
    _installed = False


def inversions() -> list[dict]:
    with _state_lock:
        return list(_inversions)


def snapshot() -> dict:
    """Counters for diagnostics/tests."""
    with _state_lock:
        return {
            "edges": sum(len(v) for v in _edges.values()),
            "nodes": len(_edges),
            "inversions": len(_inversions),
        }


def format_inversions(found: list[dict]) -> str:
    out = []
    for inv in found:
        out.append(
            f"lock-order inversion: thread {inv['thread']} acquired "
            f"{inv['acquiring']} while holding {inv['held']}, but the "
            f"opposite nesting was established earlier "
            f"({inv['established']})\n{inv['stack']}"
        )
    return "\n".join(out)
