"""weedchaos: deterministic cluster fault injection (docs/CHAOS.md).

The single-node robustness planes (weedcrash, scrub, QoS) never
exercise CLUSTER failure: partitions, flaky links, dying disks, a
leader SIGKILLed mid-write. This module is the fault library plus the
declarative scenario runner that drives a LIVE cluster through those
regimes while invariant checkers watch — the regime the warehouse-
cluster failure study (arXiv:1309.0186) shows is exactly where
recovery traffic and serving traffic collide.

Three fault planes, all deterministic (seeded RNG, explicit trigger
points), none needing root:

  * `ChaosProxy` — a runtime-mutable TCP proxy generalizing
    tests/faults.SlowReplicaProxy: per-direction latency/jitter,
    bandwidth caps, probabilistic connection drop, mid-stream RST, and
    full blackhole. Wire a node's advertised address through one and
    the whole cluster reaches it through the fault; `partition()` /
    `heal()` flip at runtime. `ProxyPair` covers a daemon's HTTP port
    and its +10000 gRPC port with one shared fault state, so a
    "partitioned node" is partitioned on both wires at once.

  * `DiskChaos` — an os-level shim (installed like weedcrash's
    Recorder) injecting EIO / ENOSPC / short reads / slow preads into
    os.pread/read/pwrite/write for fds whose path matches a prefix.
    `WEED_CHAOS_DISK` installs it at daemon startup, so subprocess CLI
    clusters are injectable too (`mode:path_prefix[:ops]`, `;`-joined).

  * `ProcChaos` — SIGKILL / SIGSTOP / SIGCONT / restart for daemon
    processes (subprocess.Popen or pid), plus `stop()` for in-process
    servers — the raft-leader-kill lever.

Scenarios are data: a list of (at_s, action) faults applied on a
timeline against a live cluster while a workload runs, then invariants
evaluated over the workload's report. See docs/CHAOS.md for the
catalog (leader-kill during a write fan, partition-during-rebuild,
EIO-on-read, lossy EC gather) and how to reproduce a finding.
"""

from __future__ import annotations

import errno as _errno
import os
import random
import signal
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Callable

from seaweedfs_tpu.util import wlog


def _seed_default() -> int:
    try:
        return int(os.environ.get("WEED_CHAOS_SEED", "0"))
    except ValueError:
        return 0


# ---------------------------------------------------------------------------
# ChaosProxy


@dataclass
class LinkFault:
    """Mutable fault state for ONE direction of a proxied link.

    All fields are live: tests retune them mid-connection and the pump
    threads read them per chunk."""

    latency_s: float = 0.0  # fixed delay per chunk
    jitter_s: float = 0.0  # + uniform[0, jitter] per chunk
    bandwidth_bps: float = 0.0  # 0 = unlimited; else pace chunks
    # two loss granularities: drop_p is evaluated PER CHUNK (a long
    # transfer compounds it — flaky-link modeling), drop_conn_p ONCE
    # per connection ("30% of transfers die" — the scenario-catalog
    # meaning of loss; a doomed connection RSTs at its first chunk)
    drop_p: float = 0.0
    drop_conn_p: float = 0.0
    blackhole: bool = False  # swallow everything until healed
    rst_after_bytes: int = -1  # >=0: RST the conn after N fwd bytes


class ChaosProxy:
    """TCP proxy with runtime-mutable faults on each direction.

    Point clients (or a node's advertised url) at `proxy.addr` and
    every byte each way traverses the fault state. `request` is the
    client→upstream direction, `response` is upstream→client.
    Connections arriving (or bytes flowing) while `blackhole` is set
    PARK until healed — modeling a partition whose packets vanish
    (peers see stalls and timeouts, never RSTs) — except when
    `refuse` is set, where new connections are closed immediately
    (modeling an unreachable-host reject instead)."""

    _POLL_S = 0.05

    def __init__(
        self,
        target: str,
        seed: int | None = None,
        request: LinkFault | None = None,
        response: LinkFault | None = None,
        listener: socket.socket | None = None,
    ):
        host, _, port = target.partition(":")
        self.target = (host, int(port))
        self.request = request or LinkFault()
        self.response = response or LinkFault()
        self.refuse = False
        self._rng = random.Random(seed if seed is not None else _seed_default())
        self._rng_lock = threading.Lock()
        if listener is not None:
            # pre-bound by the caller (ProxyPair needs two listeners
            # whose ports differ by exactly the gRPC offset)
            self._listener = listener
        else:
            self._listener = socket.socket()
            self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._listener.bind(("127.0.0.1", 0))
            self._listener.listen(128)
        self._stop = threading.Event()
        self._conns: list[socket.socket] = []
        self._lock = threading.Lock()
        # observability for scenario reports
        self.conns_total = 0
        self.conns_dropped = 0
        self.conns_rst = 0
        self.bytes_forwarded = 0
        self.chunks_delayed = 0
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    # -- fault controls ----------------------------------------------------
    @property
    def addr(self) -> str:
        return "127.0.0.1:%d" % self._listener.getsockname()[1]

    @property
    def port(self) -> int:
        return self._listener.getsockname()[1]

    def partition(self) -> None:
        """Full two-way blackhole: in-flight bytes stall, new
        connections park. The node is unreachable THROUGH this proxy
        until heal()."""
        self.request.blackhole = True
        self.response.blackhole = True

    def heal(self) -> None:
        """Clear every fault on both directions."""
        for lf in (self.request, self.response):
            lf.latency_s = 0.0
            lf.jitter_s = 0.0
            lf.bandwidth_bps = 0.0
            lf.drop_p = 0.0
            lf.drop_conn_p = 0.0
            lf.blackhole = False
            lf.rst_after_bytes = -1
        self.refuse = False

    @property
    def partitioned(self) -> bool:
        return self.request.blackhole and self.response.blackhole

    def _rand(self) -> float:
        with self._rng_lock:
            return self._rng.random()

    # -- plumbing ----------------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return
            self.conns_total += 1
            if self.refuse:
                client.close()
                self.conns_dropped += 1
                continue
            threading.Thread(
                target=self._open_link, args=(client,), daemon=True
            ).start()

    def _open_link(self, client: socket.socket) -> None:
        # a connection arriving during a partition parks here — the
        # peer's SYN succeeded (the proxy IS reachable) but nothing
        # flows, which is how a blackholed route feels to a client
        while (self.request.blackhole or self.response.blackhole) and (
            not self._stop.is_set()
        ):
            time.sleep(self._POLL_S)
        if self._stop.is_set():
            client.close()
            return
        try:
            upstream = socket.create_connection(self.target, timeout=10)
        except OSError:
            client.close()
            self.conns_dropped += 1
            return
        for s in (client, upstream):
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, True)
        with self._lock:
            self._conns += [client, upstream]
        threading.Thread(
            target=self._pump, args=(client, upstream, self.request), daemon=True
        ).start()
        threading.Thread(
            target=self._pump, args=(upstream, client, self.response), daemon=True
        ).start()

    def _rst(self, sock: socket.socket) -> None:
        """Abortive close: SO_LINGER(on, 0) turns close() into a RST —
        the mid-stream connection-reset fault."""
        try:
            sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
            )
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        self.conns_rst += 1

    def _pump(self, src, dst, lf: LinkFault) -> None:
        forwarded = 0
        doomed = None  # drop_conn_p verdict, drawn at the first chunk
        try:
            while True:
                data = src.recv(1 << 16)
                if not data:
                    break
                # blackhole: park (never forward, never close) until
                # healed — peers observe a stall, exactly like loss
                while lf.blackhole and not self._stop.is_set():
                    time.sleep(self._POLL_S)
                if self._stop.is_set():
                    break
                if doomed is None:
                    doomed = (
                        lf.drop_conn_p > 0
                        and self._rand() < lf.drop_conn_p
                    )
                if doomed or (lf.drop_p > 0 and self._rand() < lf.drop_p):
                    # connection-granularity loss: TCP can't lose bytes
                    # from the middle of a stream, so "30% loss" on a
                    # link means 30% of transfers die mid-flight and
                    # the retry/hedge planes must recover
                    self.conns_dropped += 1
                    self._rst(dst)
                    self._rst(src)
                    return
                d = lf.latency_s
                if lf.jitter_s > 0:
                    d += self._rand() * lf.jitter_s
                if d > 0:
                    self.chunks_delayed += 1
                    time.sleep(d)
                if lf.bandwidth_bps > 0:
                    time.sleep(len(data) / lf.bandwidth_bps)
                if (
                    lf.rst_after_bytes >= 0
                    and forwarded + len(data) > lf.rst_after_bytes
                ):
                    keep = max(0, lf.rst_after_bytes - forwarded)
                    if keep:
                        try:
                            dst.sendall(data[:keep])
                        except OSError:
                            pass
                    self._rst(dst)
                    self._rst(src)
                    return
                dst.sendall(data)
                forwarded += len(data)
                self.bytes_forwarded += len(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns, self._conns = self._conns, []
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


class ProxyPair:
    """One logical node behind chaos: an HTTP-port proxy and a gRPC-
    port proxy (+10000, the cluster convention) listening on a
    matching port pair, faulted together.

    The cluster reaches a daemon by ONE advertised "host:port" and
    derives the gRPC port from it — so to interpose on everything,
    `http.port` and `grpc` must differ by exactly 10000. The pair
    binds a free base port for HTTP and base+10000 for gRPC (retrying
    until both are free), so `addr` drops in anywhere a node address
    does."""

    GRPC_OFFSET = 10000

    def __init__(self, target: str, seed: int | None = None, tries: int = 64):
        host, _, port = target.partition(":")
        p = int(port)
        self.http: ChaosProxy | None = None
        for _ in range(tries):
            cand = self._bindable_pair()
            if cand is None:
                continue
            http_l, grpc_l = cand
            self.http = ChaosProxy(f"{host}:{p}", seed=seed, listener=http_l)
            self.grpc = ChaosProxy(
                f"{host}:{p + self.GRPC_OFFSET}", seed=seed, listener=grpc_l
            )
            break
        if self.http is None:
            raise OSError("could not find a free HTTP/+10000 port pair")

    @staticmethod
    def _bindable_pair():
        l1 = socket.socket()
        l1.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        l1.bind(("127.0.0.1", 0))
        base = l1.getsockname()[1]
        if base + ProxyPair.GRPC_OFFSET > 65535:
            l1.close()
            return None
        l2 = socket.socket()
        l2.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            l2.bind(("127.0.0.1", base + ProxyPair.GRPC_OFFSET))
        except OSError:
            l1.close()
            l2.close()
            return None
        l1.listen(128)
        l2.listen(128)
        return l1, l2

    @property
    def addr(self) -> str:
        return self.http.addr

    def partition(self) -> None:
        self.http.partition()
        self.grpc.partition()

    def heal(self) -> None:
        self.http.heal()
        self.grpc.heal()

    def stop(self) -> None:
        self.http.stop()
        self.grpc.stop()


# ---------------------------------------------------------------------------
# DiskChaos


@dataclass
class DiskFault:
    """One injection rule, matched on the fd's opened path."""

    mode: str  # eio | enospc | short | slow
    path_prefix: str
    ops: tuple = ("read",)  # any of ("read", "write")
    probability: float = 1.0
    delay_s: float = 0.05  # slow mode: sleep before the real op
    short_by: int = 1  # short mode: bytes withheld
    max_hits: int = -1  # -1 = unlimited
    hits: int = 0

    def matches(self, path: str, op: str) -> bool:
        if op not in self.ops:
            return False
        if not path.startswith(self.path_prefix):
            return False
        return self.max_hits < 0 or self.hits < self.max_hits


class DiskChaos:
    """os-level read/write fault shim, installed like weedcrash's
    Recorder: wraps os.open/close to learn fd→path, and
    os.pread/read/pwrite/write/pwritev to inject. Only fds OPENED
    while installed are candidates (matching the Recorder's model);
    pass-through costs one dict probe per call for everything else."""

    def __init__(self, faults: list[DiskFault] | None = None, seed=None):
        self.faults: list[DiskFault] = list(faults or [])
        self._rng = random.Random(seed if seed is not None else _seed_default())
        self._fd_paths: dict[int, str] = {}
        self._lock = threading.Lock()
        self._installed = False
        self._real: dict[str, Callable] = {}

    def add(self, fault: DiskFault) -> DiskFault:
        self.faults.append(fault)
        return fault

    # ------------------------------------------------------------------
    def _pick(self, fd: int, op: str) -> DiskFault | None:
        path = self._fd_paths.get(fd)
        if path is None:
            return None
        for f in self.faults:
            if f.matches(path, op):
                if f.probability >= 1.0 or self._rng.random() < f.probability:
                    f.hits += 1
                    return f
        return None

    def _strike(self, fault: DiskFault, op: str, nbytes: int):
        """Returns ('short', n) to truncate, None to proceed; raises
        for error modes."""
        if fault.mode == "eio":
            raise OSError(_errno.EIO, "chaos: injected EIO")
        if fault.mode == "enospc":
            if op == "write":
                raise OSError(_errno.ENOSPC, "chaos: injected ENOSPC")
            return None
        if fault.mode == "slow":
            time.sleep(fault.delay_s)
            return None
        if fault.mode == "short":
            return ("short", max(0, nbytes - fault.short_by))
        return None

    # ------------------------------------------------------------------
    def install(self) -> "DiskChaos":
        if self._installed:
            return self
        import builtins

        real = self._real
        real["open"] = os.open
        real["bopen"] = builtins.open
        real["close"] = os.close
        real["pread"] = os.pread
        real["read"] = os.read
        real["pwrite"] = os.pwrite
        real["write"] = os.write
        real["pwritev"] = os.pwritev
        chaos = self

        def c_open(path, flags, mode=0o777, *, dir_fd=None):
            fd = real["open"](path, flags, mode, dir_fd=dir_fd)
            with chaos._lock:
                chaos._fd_paths[fd] = os.fspath(path)
            return fd

        def c_bopen(file, *args, **kwargs):
            # buffered opens (EcVolumeShard, Volume) never touch
            # os.open, but their preads DO ride os.pread on the
            # underlying fd — track fileno→path so those match too
            fobj = real["bopen"](file, *args, **kwargs)
            if isinstance(file, (str, os.PathLike)):
                try:
                    fd = fobj.fileno()
                except (OSError, AttributeError, ValueError):
                    return fobj
                with chaos._lock:
                    chaos._fd_paths[fd] = os.fspath(file)
            return fobj

        def c_close(fd):
            with chaos._lock:
                chaos._fd_paths.pop(fd, None)
            return real["close"](fd)

        def c_pread(fd, n, offset):
            f = chaos._pick(fd, "read")
            if f is not None:
                act = chaos._strike(f, "read", n)
                if act is not None:
                    n = act[1]
            return real["pread"](fd, n, offset)

        def c_read(fd, n):
            f = chaos._pick(fd, "read")
            if f is not None:
                act = chaos._strike(f, "read", n)
                if act is not None:
                    n = act[1]
            return real["read"](fd, n)

        def c_pwrite(fd, data, offset):
            f = chaos._pick(fd, "write")
            if f is not None:
                act = chaos._strike(f, "write", len(data))
                if act is not None:
                    return real["pwrite"](fd, data[: act[1]], offset)
            return real["pwrite"](fd, data, offset)

        def c_write(fd, data):
            f = chaos._pick(fd, "write")
            if f is not None:
                act = chaos._strike(f, "write", len(data))
                if act is not None:
                    return real["write"](fd, data[: act[1]])
            return real["write"](fd, data)

        def c_pwritev(fd, buffers, offset, flags=0):
            f = chaos._pick(fd, "write")
            if f is not None:
                total = sum(len(b) for b in buffers)
                chaos._strike(f, "write", total)  # raises for eio/enospc
            return real["pwritev"](fd, buffers, offset, flags)

        os.open = c_open
        builtins.open = c_bopen
        os.close = c_close
        os.pread = c_pread
        os.read = c_read
        os.pwrite = c_pwrite
        os.write = c_write
        os.pwritev = c_pwritev
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        import builtins

        os.open = self._real["open"]
        builtins.open = self._real["bopen"]
        os.close = self._real["close"]
        os.pread = self._real["pread"]
        os.read = self._real["read"]
        os.pwrite = self._real["pwrite"]
        os.write = self._real["write"]
        os.pwritev = self._real["pwritev"]
        self._installed = False
        with self._lock:
            self._fd_paths.clear()

    def __enter__(self) -> "DiskChaos":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def parse_disk_spec(spec: str) -> list[DiskFault]:
    """`mode:path_prefix[:ops]` rules, `;`-joined — the WEED_CHAOS_DISK
    wire format (ops comma-joined, default read). Unparseable rules are
    skipped with a warning: a typo in a chaos knob must degrade to
    no-fault, never crash the daemon it targets."""
    out: list[DiskFault] = []
    for rule in spec.split(";"):
        rule = rule.strip()
        if not rule:
            continue
        parts = rule.split(":")
        if (
            len(parts) < 2
            or not parts[1]  # empty prefix would match EVERY file
            or parts[0] not in ("eio", "enospc", "short", "slow")
        ):
            wlog.warning("chaos: ignoring bad WEED_CHAOS_DISK rule %r", rule)
            continue
        ops = ("read",)
        if len(parts) >= 3 and parts[2]:
            ops = tuple(
                o for o in parts[2].split(",") if o in ("read", "write")
            ) or ("read",)
        out.append(DiskFault(mode=parts[0], path_prefix=parts[1], ops=ops))
    return out


_ENV_DISK: DiskChaos | None = None


def install_disk_chaos_from_env() -> DiskChaos | None:
    """Daemon-startup hook (command/servers.py): when WEED_CHAOS_DISK
    names rules, install a process-wide DiskChaos before any volume
    opens — this is how scenarios reach a subprocess CLI cluster's
    disks. Idempotent; returns the installed shim (or None)."""
    global _ENV_DISK
    spec = os.environ.get("WEED_CHAOS_DISK", "")
    if not spec or _ENV_DISK is not None:
        return _ENV_DISK
    faults = parse_disk_spec(spec)
    if not faults:
        return None
    wlog.warning("chaos: WEED_CHAOS_DISK active: %s", spec)
    _ENV_DISK = DiskChaos(faults).install()
    return _ENV_DISK


# ---------------------------------------------------------------------------
# ProcChaos


class ProcChaos:
    """Kill/pause/resume/restart one daemon.

    Wraps either a subprocess.Popen (CLI clusters) or any in-process
    server object with .stop() (the raft-leader-kill scenarios drive
    in-process MasterServers). `spawn` lets restart() bring a killed
    subprocess back with the same argv/env."""

    def __init__(self, proc=None, spawn: Callable[[], object] | None = None):
        self.proc = proc
        self.spawn = spawn
        self.killed = False
        self.paused = False

    def _pid(self) -> int | None:
        return getattr(self.proc, "pid", None)

    def kill(self) -> None:
        """SIGKILL (subprocess) or .stop() (in-process): the daemon
        vanishes without goodbye — no FIN on its sockets' peers' next
        read, no heartbeat stream teardown."""
        pid = self._pid()
        if pid is not None:
            try:
                os.kill(pid, signal.SIGKILL)
                self.proc.wait()
            except (OSError, AttributeError):
                pass
        else:
            self.proc.stop()
        self.killed = True

    def pause(self) -> None:
        """SIGSTOP: the process freezes with every socket still open —
        the 'gray failure' no liveness check built on TCP accept can
        see (subprocess only)."""
        pid = self._pid()
        if pid is None:
            raise RuntimeError("pause() needs a subprocess (SIGSTOP)")
        os.kill(pid, signal.SIGSTOP)
        self.paused = True

    def resume(self) -> None:
        pid = self._pid()
        if pid is None:
            raise RuntimeError("resume() needs a subprocess (SIGCONT)")
        os.kill(pid, signal.SIGCONT)
        self.paused = False

    def restart(self):
        """Respawn after kill() via the `spawn` callable; returns the
        new proc handle."""
        if self.spawn is None:
            raise RuntimeError("restart() needs a spawn callable")
        self.proc = self.spawn()
        self.killed = False
        return self.proc


def kill_raft_leader(masters: list) -> object | None:
    """SIGKILL-equivalent for the current raft leader among in-process
    MasterServers (or any objects with .is_leader and .stop()).
    Returns the killed server, or None when no leader exists yet."""
    for m in masters:
        if getattr(m, "is_leader", False):
            ProcChaos(m).kill()
            return m
    return None


# ---------------------------------------------------------------------------
# scenario runner + invariants


@dataclass
class Fault:
    """One timed action on the scenario timeline."""

    at_s: float
    action: Callable[[], None]
    name: str = ""


@dataclass
class Scenario:
    """A named fault timeline. `duration_s` bounds the whole run
    (workload included); faults fire at their offsets from start."""

    name: str
    faults: list[Fault]
    duration_s: float = 30.0


@dataclass
class InvariantResult:
    name: str
    ok: bool
    detail: str = ""


class InvariantFailed(AssertionError):
    pass


def run_scenario(
    scenario: Scenario,
    workload: Callable[[], dict],
    invariants: list[Callable[[dict], InvariantResult]] | None = None,
) -> dict:
    """Drive one scenario: start `workload()` on a thread, fire the
    fault timeline, join the workload (bounded by duration_s + grace),
    then evaluate every invariant over the workload's report dict.

    Returns the report with `events` (fault log), `invariants`
    (results), and `ok`. Raises InvariantFailed when any invariant
    fails — with every result in the message, so a CI failure names
    the broken property, not just 'assert False'."""
    events: list[tuple[float, str]] = []
    report: dict = {}
    workload_error: list[BaseException] = []

    def run_workload():
        try:
            report.update(workload() or {})
        except BaseException as e:  # noqa: BLE001 - reported, not lost
            workload_error.append(e)

    t0 = time.monotonic()
    wt = threading.Thread(target=run_workload, daemon=True)
    wt.start()
    for fault in sorted(scenario.faults, key=lambda f: f.at_s):
        wait = fault.at_s - (time.monotonic() - t0)
        if wait > 0:
            time.sleep(wait)
        name = fault.name or getattr(fault.action, "__name__", "fault")
        events.append((round(time.monotonic() - t0, 3), name))
        wlog.warning(
            "chaos[%s] t=%.2fs: %s", scenario.name, events[-1][0], name
        )
        fault.action()
    wt.join(timeout=max(0.0, scenario.duration_s - (time.monotonic() - t0)) + 30.0)
    if wt.is_alive():
        raise InvariantFailed(
            f"chaos[{scenario.name}]: workload still running past "
            f"duration {scenario.duration_s}s + 30s grace"
        )
    if workload_error:
        raise workload_error[0]
    report["scenario"] = scenario.name
    report["events"] = events
    report["wall_s"] = round(time.monotonic() - t0, 3)
    results = [inv(report) for inv in (invariants or [])]
    report["invariants"] = [
        {"name": r.name, "ok": r.ok, "detail": r.detail} for r in results
    ]
    report["ok"] = all(r.ok for r in results)
    if not report["ok"]:
        raise InvariantFailed(
            f"chaos[{scenario.name}] invariants failed: "
            + "; ".join(f"{r.name}: {r.detail}" for r in results if not r.ok)
        )
    return report


# -- the invariant library --------------------------------------------------
# Each helper RETURNS an invariant callable, so scenarios compose them
# declaratively: invariants=[no_acked_write_lost(read_fn), ...]


def no_acked_write_lost(
    read_fn: Callable[[str], bytes], acked_key: str = "acked"
) -> Callable[[dict], InvariantResult]:
    """Every write the workload reports as ACKED must read back byte-
    identical after the fault window (report[acked_key] is
    {fid: expected_bytes}). THE durability invariant: a fault may fail
    a write loudly, it may never eat an acknowledged one."""

    def check(report: dict) -> InvariantResult:
        acked: dict = report.get(acked_key, {})
        lost, corrupt = [], []
        for fid, expect in acked.items():
            try:
                got = read_fn(fid)
            except Exception as e:  # noqa: BLE001 - classified as lost
                lost.append(f"{fid}: {e}")
                continue
            if got != expect:
                corrupt.append(fid)
        ok = not lost and not corrupt
        return InvariantResult(
            "no_acked_write_lost",
            ok,
            "" if ok else f"lost={lost[:3]} corrupt={corrupt[:3]} "
            f"({len(lost)} lost / {len(corrupt)} corrupt of {len(acked)})",
        )

    return check


def no_double_apply() -> Callable[[dict], InvariantResult]:
    """Retries must not double-apply. The workload reports
    `duplicates` — the count of acked fids it saw MORE THAN ONCE (a
    replayed assign reusing a volume-id/needle pair) — and may also
    report the raw `acked_fids` list for an independent uniqueness
    check (the acked DICT's keys are unique by construction, so they
    can never show a collision)."""

    def check(report: dict) -> InvariantResult:
        dupes = int(report.get("duplicates", 0))
        fids = report.get("acked_fids")
        if fids is not None:
            dupes += len(fids) - len(set(fids))
        return InvariantResult(
            "no_double_apply",
            dupes == 0,
            "" if dupes == 0 else f"{dupes} duplicated applies",
        )

    return check


def converges(
    probe: Callable[[], bool], bound_s: float, name: str = "converges"
) -> Callable[[dict], InvariantResult]:
    """The cluster returns to steady state within `bound_s` of the
    workload ending: poll `probe()` (heartbeats resumed, repair queue
    drained, leader elected — caller's definition) until true."""

    def check(report: dict) -> InvariantResult:
        t0 = time.monotonic()
        while time.monotonic() - t0 < bound_s:
            try:
                if probe():
                    report[f"{name}_s"] = round(time.monotonic() - t0, 3)
                    return InvariantResult(name, True)
            except Exception:  # noqa: BLE001 - not converged yet
                pass
            time.sleep(0.1)
        return InvariantResult(name, False, f"not within {bound_s}s")

    return check


def bounded_amplification(
    requests_key: str = "requests_sent",
    acked_key: str = "acked",
    factor: float = 1.15,
) -> Callable[[dict], InvariantResult]:
    """Retry-storm guard: total upstream requests the workload emitted
    may not exceed `factor` × the work acked (the retry budget's
    promise — a blackholed replica degrades latency, it must not
    multiply load)."""

    def check(report: dict) -> InvariantResult:
        sent = report.get(requests_key, 0)
        base = max(1, len(report.get(acked_key, {})) + report.get("failed", 0))
        amp = sent / base
        report["amplification"] = round(amp, 3)
        return InvariantResult(
            "bounded_amplification",
            amp <= factor,
            "" if amp <= factor else f"amplification {amp:.2f} > {factor}",
        )

    return check
