"""Static lock-order analyzer: the package-wide acquisition graph.

What `go vet` + code review gave the reference, this pass gives the
port: every `threading.Lock/RLock/Condition` attribute is mapped to
its owning class, every `with <lock>:` block and explicit
`acquire()/release()` pair contributes edges to a static acquisition
graph (lock A held while lock B is acquired ⇒ edge A→B), and any
cycle in that graph is a deadlock candidate — two threads walking the
cycle from different entry points can block each other forever.

Resolution strategy (precision over recall — a finding here should be
a true positive; recall is the dynamic witness's job, analysis/witness.py):

  * `self.X` resolves against the enclosing class's lock attributes;
  * `obj.X` resolves only when attribute X names a lock in exactly ONE
    class package-wide, or the variable's class is knowable from a
    parameter annotation or a tracked local assignment;
  * `threading.Condition(self.X)` aliases to X (entering the condition
    acquires the wrapped lock);
  * dict-of-locks idioms (`d.setdefault(k, threading.Lock())`) become
    a single `Class.attr[*]` node — per-key instances share ordering;
  * calls made while holding locks propagate one-level interprocedural:
    each function's transitive acquire-set is computed to fixpoint over
    the package call graph (self-methods, module functions, and
    methods whose name is unique package-wide);
  * a LOCAL function passed as an argument (the `precheck=still_owned`
    callback idiom in server/volume_workers.py) is bound to the callee's
    parameter, so locks the callback takes are ordered after locks the
    callee holds at its `param()` call sites.

The same walk also powers the unguarded-write check: an attribute that
the owning class writes under its own lock at some non-constructor
site is "lock-guarded"; any other non-constructor write reached
without that guard is a lost-update candidate (rule unguarded-write).
A method whose every in-package call site already holds the class's
lock inherits that guard context (the `_refill_locked` idiom).
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from seaweedfs_tpu.analysis import Finding, iter_py_files

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_CTOR_METHODS = {"__init__", "__post_init__", "__new__", "__init_subclass__"}
# mutating method calls on `self.attr` that count as writes for the
# unguarded-write check (the attribute itself is reassigned-equivalent)
_MUTATORS = {
    "append", "add", "pop", "clear", "update", "remove", "discard",
    "extend", "insert", "setdefault", "popitem", "appendleft",
}
# method names that collide with builtin container/IO protocols: a
# `x.get(...)` must never resolve to SomeClass.get just because exactly
# one package class defines a `get` method — x is usually a dict
_BUILTIN_METHODS: set[str] = (
    set(dir(list)) | set(dir(dict)) | set(dir(set)) | set(dir(str))
    | set(dir(bytes)) | set(dir(bytearray)) | set(dir(tuple))
    | {
        "read", "write", "close", "open", "flush", "seek", "tell",
        "readline", "readinto", "fileno", "send", "recv", "sendall",
        "connect", "bind", "listen", "accept", "settimeout", "shutdown",
        "join", "start", "wait", "set", "is_set", "put", "get", "result",
        "submit", "cancel", "acquire", "release",
    }
)


# ---------------------------------------------------------------------------
# package index


@dataclass
class FuncRecord:
    qualname: str
    cls: str | None  # owning class name, if a method
    module: str
    path: str  # repo-relative
    is_classmethod: bool = False  # @classmethod/@staticmethod: ctor-ish
    params: list[str] = field(default_factory=list)
    direct_acquires: set[str] = field(default_factory=set)
    # (held frozenset, callee reference, line); callee refs are symbolic
    # ("self.m", "mod.f", "~local.f", "?m") until resolution
    calls: list = field(default_factory=list)
    # param name -> [(held frozenset, line)] where the param is CALLED
    param_call_holds: dict[str, list] = field(default_factory=dict)
    # (attr, line, held frozenset, is_self, target_hint)
    writes: list = field(default_factory=list)
    # acquisition events: (node, line, held frozenset)
    acquisitions: list = field(default_factory=list)


@dataclass
class ClassRecord:
    name: str
    module: str
    path: str
    bases: list[str] = field(default_factory=list)  # base-class names
    lock_attrs: dict[str, str] = field(default_factory=dict)  # attr -> kind
    methods: dict[str, str] = field(default_factory=dict)  # name -> qualname


class PackageIndex:
    def __init__(self) -> None:
        # keyed by a unique per-definition key; bare-name lookups go
        # through classes_by_name, which keeps DISTINCT records for
        # same-named classes in different modules (the package has
        # several: Command, _Reader, VolumeInfo…) — merging them would
        # corrupt method resolution and the uniqueness probes
        self.classes: dict[str, ClassRecord] = {}
        self.classes_by_name: dict[str, list[ClassRecord]] = {}
        self.funcs: dict[str, FuncRecord] = {}  # by qualname
        self.func_cls: dict[str, ClassRecord] = {}  # method qual -> class
        self.module_funcs: dict[tuple[str, str], str] = {}  # (mod, name) -> qual
        # method name -> [qualnames] across every class (uniqueness probe)
        self.methods_by_name: dict[str, list[str]] = {}
        self.sources: dict[str, str] = {}  # rel path -> source text
        self.lock_attr_owners: dict[str, list[str]] = {}  # attr -> [classes]
        self.fn_nodes: dict[str, ast.FunctionDef] = {}  # qual -> AST node
        # (module basename, function) -> [quals]: resolves the
        # `from pkg import write_path; write_path.fn()` idiom
        self.funcs_by_modbase: dict[tuple[str, str], list[str]] = {}

    def class_by_name(self, name: str) -> "ClassRecord | None":
        """The record for a bare class name, or None when the name is
        ambiguous (defined in several modules) — ambiguity means no
        resolution, never a guess."""
        recs = self.classes_by_name.get(name, [])
        return recs[0] if len(recs) == 1 else None

    def finish(self) -> None:
        for cls in self.classes.values():
            for attr in cls.lock_attrs:
                self.lock_attr_owners.setdefault(attr, []).append(cls.name)
            for mname, qual in cls.methods.items():
                self.methods_by_name.setdefault(mname, []).append(qual)
        for (mod, fname), qual in self.module_funcs.items():
            base = mod.rsplit(".", 1)[-1]
            self.funcs_by_modbase.setdefault((base, fname), []).append(qual)


def _is_lock_call(node: ast.expr) -> str | None:
    """'Lock'/'RLock'/'Condition' when node is threading.X(...) (or a
    bare X(...) — the package always imports the module, but be lax)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _LOCK_FACTORIES:
        if isinstance(fn.value, ast.Name) and fn.value.id == "threading":
            return fn.attr
    if isinstance(fn, ast.Name) and fn.id in _LOCK_FACTORIES:
        return fn.id
    return None


def _contains_lock_call(node: ast.expr) -> str | None:
    for sub in ast.walk(node):
        kind = _is_lock_call(sub)
        if kind is not None:
            return kind
    return None


# ---------------------------------------------------------------------------
# per-function symbolic walk


class _FuncWalker:
    """Walks one function body tracking the stack of held locks.

    Control flow is approximated: branches are visited sequentially
    with the entry-held stack, which is exact for the dominant
    `with lock:` idiom and conservative for acquire/release spanning
    branches (an acquire() inside one branch arm is treated as held
    for the remainder of the straight-line walk)."""

    def __init__(self, index: PackageIndex, rec: FuncRecord,
                 cls: ClassRecord | None, local_locks: dict[str, str],
                 annotations: dict[str, str], local_funcs: dict[str, str]):
        self.index = index
        self.rec = rec
        self.cls = cls
        self.held: list[str] = []
        self.local_locks = local_locks  # var name -> lock node
        self.annotations = annotations  # param name -> class name
        self.local_funcs = local_funcs  # local def name -> qualname

    def prescan(self, fn_node: ast.FunctionDef) -> None:
        """Infer entry-held locks: a function that release()s a lock
        more often than it acquire()s it (the begin_transaction /
        commit_transaction split-protocol idiom) holds that lock as a
        precondition — its writes and nested acquisitions are ordered
        under it."""
        balance: dict[str, int] = {}
        for sub in ast.walk(fn_node):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
            ):
                continue
            if sub.func.attr == "acquire":
                lock = self.resolve_lock(sub.func.value)
                if lock is not None:
                    balance[lock] = balance.get(lock, 0) + 1
            elif sub.func.attr == "release":
                lock = self.resolve_lock(sub.func.value)
                if lock is not None:
                    balance[lock] = balance.get(lock, 0) - 1
        for lock, n in balance.items():
            if n < 0:
                self.held.append(lock)

    # -- lock expression resolution ------------------------------------
    def resolve_lock(self, node: ast.expr) -> str | None:
        if isinstance(node, ast.Name):
            if node.id in self.local_locks:
                return self.local_locks[node.id]
            return None
        if isinstance(node, ast.Attribute):
            attr = node.attr
            base = node.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self.cls is not None:
                    target = self.cls.lock_attrs.get(attr)
                    if target is not None:
                        return f"{self.cls.name}.{attr}"
                    return None
                # annotated param / tracked variable of a known class
                cls_name = self.annotations.get(base.id)
                ann_cls = (
                    self.index.class_by_name(cls_name) if cls_name else None
                )
                if ann_cls is not None and attr in ann_cls.lock_attrs:
                    return f"{ann_cls.name}.{attr}"
                # unique lock-attribute name across the package
                owners = self.index.lock_attr_owners.get(attr, [])
                if len(owners) == 1:
                    return f"{owners[0]}.{attr}"
            return None
        return None

    # -- events --------------------------------------------------------
    def _acquire(self, node_id: str, line: int) -> None:
        self.rec.acquisitions.append(
            (node_id, line, frozenset(self.held))
        )
        self.rec.direct_acquires.add(node_id)
        self.held.append(node_id)

    def _release(self, node_id: str) -> None:
        if node_id in self.held:
            # remove the innermost matching hold
            for i in range(len(self.held) - 1, -1, -1):
                if self.held[i] == node_id:
                    del self.held[i]
                    break

    def _record_write(self, attr: str, line: int, is_self: bool,
                      hint: str | None) -> None:
        self.rec.writes.append(
            (attr, line, frozenset(self.held), is_self, hint)
        )

    def _record_call(self, call: ast.Call) -> None:
        ref = self._callee_ref(call.func)
        held = frozenset(self.held)
        cb_args: list[tuple[object, str]] = []  # (pos|kw, local func qual)
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id in self.local_funcs:
                cb_args.append((i, self.local_funcs[a.id]))
        for kw in call.keywords:
            if (
                kw.arg is not None
                and isinstance(kw.value, ast.Name)
                and kw.value.id in self.local_funcs
            ):
                cb_args.append((kw.arg, self.local_funcs[kw.value.id]))
        if ref is not None or cb_args:
            self.rec.calls.append((held, ref, call.lineno, cb_args))
        # a call on a tracked PARAM name: witness point for callbacks
        if isinstance(call.func, ast.Name) and call.func.id in self.rec.params:
            self.rec.param_call_holds.setdefault(call.func.id, []).append(
                (held, call.lineno)
            )

    def _callee_ref(self, fn: ast.expr) -> str | None:
        if isinstance(fn, ast.Name):
            if fn.id in self.local_funcs:
                return self.local_funcs[fn.id]
            qual = self.index.module_funcs.get((self.rec.module, fn.id))
            if qual:
                return qual
            ctor_cls = self.index.class_by_name(fn.id)
            if ctor_cls is not None:
                return ctor_cls.methods.get("__init__")
            return None
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name):
                if fn.value.id == "self" and self.cls is not None:
                    qual = self.cls.methods.get(fn.attr)
                    if qual:
                        return qual
                cls_name = self.annotations.get(fn.value.id)
                ann_cls = (
                    self.index.class_by_name(cls_name) if cls_name else None
                )
                if ann_cls is not None:
                    qual = ann_cls.methods.get(fn.attr)
                    if qual:
                        return qual
                # `write_path.fn()`: module referenced by basename
                mods = self.index.funcs_by_modbase.get(
                    (fn.value.id, fn.attr), []
                )
                if len(mods) == 1:
                    return mods[0]
            # method name unique across every class in the package AND
            # not shadowing a builtin protocol name (x.append must not
            # resolve to the one package class that defines append)
            cands = self.index.methods_by_name.get(fn.attr, [])
            if (
                len(cands) == 1
                and fn.attr not in _CTOR_METHODS
                and fn.attr not in _BUILTIN_METHODS
            ):
                return cands[0]
            return None
        return None

    # -- statement walk ------------------------------------------------
    def walk(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            pushed: list[str] = []
            for item in stmt.items:
                self._expr(item.context_expr)
                lock = self.resolve_lock(item.context_expr)
                if lock is not None:
                    self._acquire(lock, stmt.lineno)
                    pushed.append(lock)
            self.walk(stmt.body)
            for lock in reversed(pushed):
                self._release(lock)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are indexed separately
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assignment(stmt)
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._expr(stmt.value)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._expr(stmt.test)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            self._target_write(stmt.target)
            self.walk(stmt.body)
            self.walk(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for handler in stmt.handlers:
                self.walk(handler.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Raise, ast.Assert)):
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    self._record_call(sub)
            return
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                self._target_write(tgt)
            return
        # Pass/Break/Continue/Import/Global/...: nothing to track

    def _assignment(self, stmt: ast.stmt) -> None:
        value = getattr(stmt, "value", None)
        if value is not None:
            self._expr(value)
        targets = (
            stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
        )
        for tgt in targets:
            self._target_write(tgt)
            # local lock tracking: var = <lock expr>
            if isinstance(tgt, ast.Name) and value is not None:
                kind = _is_lock_call(value)
                if kind is not None:
                    self.local_locks[tgt.id] = (
                        f"{self.rec.qualname}.{tgt.id}"
                    )
                    return
                resolved = self.resolve_lock(value)
                if resolved is not None:
                    self.local_locks[tgt.id] = resolved
                    return
                # d.setdefault(key, threading.Lock()) on self.attr
                if (
                    isinstance(value, ast.Call)
                    and isinstance(value.func, ast.Attribute)
                    and value.func.attr in ("setdefault", "get")
                    and _contains_lock_call(value) is not None
                    and isinstance(value.func.value, ast.Attribute)
                    and isinstance(value.func.value.value, ast.Name)
                    and value.func.value.value.id == "self"
                    and self.cls is not None
                ):
                    self.local_locks[tgt.id] = (
                        f"{self.cls.name}.{value.func.value.attr}[*]"
                    )

    def _target_write(self, tgt: ast.expr) -> None:
        if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name):
            is_self = tgt.value.id == "self"
            hint = None if is_self else self.annotations.get(tgt.value.id)
            self._record_write(tgt.attr, tgt.lineno, is_self, hint)
        elif isinstance(tgt, ast.Subscript):
            inner = tgt.value
            if isinstance(inner, ast.Attribute) and isinstance(
                inner.value, ast.Name
            ):
                is_self = inner.value.id == "self"
                hint = (
                    None if is_self else self.annotations.get(inner.value.id)
                )
                self._record_write(inner.attr, tgt.lineno, is_self, hint)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target_write(el)

    def _expr(self, node: ast.expr) -> None:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if isinstance(fn, ast.Attribute):
                if fn.attr == "acquire":
                    lock = self.resolve_lock(fn.value)
                    if lock is not None:
                        self._acquire(lock, sub.lineno)
                        continue
                elif fn.attr == "release":
                    lock = self.resolve_lock(fn.value)
                    if lock is not None:
                        self._release(lock)
                        continue
                elif (
                    fn.attr in _MUTATORS
                    and isinstance(fn.value, ast.Attribute)
                    and isinstance(fn.value.value, ast.Name)
                ):
                    is_self = fn.value.value.id == "self"
                    hint = (
                        None
                        if is_self
                        else self.annotations.get(fn.value.value.id)
                    )
                    self._record_write(
                        fn.value.attr, sub.lineno, is_self, hint
                    )
            self._record_call(sub)


# ---------------------------------------------------------------------------
# index construction


def _param_annotations(fn: ast.FunctionDef) -> dict[str, str]:
    out: dict[str, str] = {}
    for arg in list(fn.args.args) + list(fn.args.kwonlyargs):
        ann = arg.annotation
        if isinstance(ann, ast.Name):
            out[arg.arg] = ann.id
        elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            out[arg.arg] = ann.value.strip("'\" ").split(".")[-1].split(
                " "
            )[0]
        elif isinstance(ann, ast.Attribute):
            out[arg.arg] = ann.attr
    return out


def build_index(root: str | None = None) -> PackageIndex:
    index = PackageIndex()
    _PENDING.clear()  # defensive: a prior failed build must not leak
    for abs_path, rel_path in iter_py_files(root):
        try:
            with open(abs_path, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel_path)
        except (OSError, SyntaxError):
            continue
        index.sources[rel_path] = source
        module = os.path.splitext(rel_path)[0].replace(os.sep, ".")
        _index_module(index, module, rel_path, tree)
    index.finish()
    # walk every function body now that class lock maps are complete
    for qual, (fn_node, cls) in list(_PENDING.items()):
        rec = index.funcs[qual]
        index.fn_nodes[qual] = fn_node
        if cls is not None:
            index.func_cls[qual] = cls
        local_funcs = {
            n.name: f"{qual}.{n.name}"
            for n in ast.walk(fn_node)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn_node
        }
        walker = _FuncWalker(
            index, rec, cls, {}, _param_annotations(fn_node), local_funcs
        )
        walker.prescan(fn_node)
        walker.walk(fn_node.body)
    _PENDING.clear()
    return index


_PENDING: dict[str, tuple[ast.FunctionDef, "ClassRecord | None"]] = {}


def _index_module(
    index: PackageIndex, module: str, path: str, tree: ast.Module
) -> None:
    def add_func(fn, cls, prefix):
        qual = f"{prefix}.{fn.name}"
        rec = FuncRecord(
            qualname=qual,
            cls=cls.name if cls is not None else None,
            module=module,
            path=path,
            params=[a.arg for a in fn.args.args if a.arg != "self"]
            + [a.arg for a in fn.args.kwonlyargs],
        )
        index.funcs[qual] = rec
        _PENDING[qual] = (fn, cls)
        for sub in fn.body:
            _walk_defs(sub, cls, qual)

    def _walk_defs(node, cls, prefix):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            add_func(node, cls, prefix)
        elif isinstance(node, ast.ClassDef):
            _index_class(node, f"{prefix}.{node.name}")
        elif hasattr(node, "body") and isinstance(
            getattr(node, "body", None), list
        ):
            for sub in node.body:
                _walk_defs(sub, cls, prefix)
            for sub in getattr(node, "orelse", []) or []:
                _walk_defs(sub, cls, prefix)
            for h in getattr(node, "handlers", []) or []:
                for sub in h.body:
                    _walk_defs(sub, cls, prefix)
            for sub in getattr(node, "finalbody", []) or []:
                _walk_defs(sub, cls, prefix)

    def _index_class(node: ast.ClassDef, qual_prefix: str) -> None:
        # one record PER DEFINITION, keyed by the (unique) qualname:
        # distinct classes sharing a bare name must never merge, or the
        # method-uniqueness probe and lock-attr maps lie about both
        cls = ClassRecord(name=node.name, module=module, path=path)
        index.classes[qual_prefix] = cls
        index.classes_by_name.setdefault(node.name, []).append(cls)
        for b in node.bases:
            if isinstance(b, ast.Name):
                cls.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                cls.bases.append(b.attr)
        # lock attributes: self.X = threading.Lock() anywhere in the class
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                tgt = sub.targets[0]
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    kind = _is_lock_call(sub.value)
                    if kind is not None:
                        cls.lock_attrs[tgt.attr] = kind
                        # Condition(self.X) aliases the wrapped lock
                        if (
                            kind == "Condition"
                            and isinstance(sub.value, ast.Call)
                            and sub.value.args
                            and isinstance(sub.value.args[0], ast.Attribute)
                            and isinstance(
                                sub.value.args[0].value, ast.Name
                            )
                            and sub.value.args[0].value.id == "self"
                        ):
                            cls.lock_attrs[tgt.attr] = (
                                f"alias:{sub.value.args[0].attr}"
                            )
        # resolve aliases to the canonical attr
        for attr, kind in list(cls.lock_attrs.items()):
            if kind.startswith("alias:"):
                cls.lock_attrs[attr] = cls.lock_attrs.get(
                    kind[6:], "Lock"
                )
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{qual_prefix}.{item.name}"
                cls.methods[item.name] = qual
                rec = FuncRecord(
                    qualname=qual,
                    cls=node.name,
                    module=module,
                    path=path,
                    is_classmethod=any(
                        isinstance(d, ast.Name)
                        and d.id in ("classmethod", "staticmethod")
                        for d in item.decorator_list
                    ),
                    params=[
                        a.arg for a in item.args.args if a.arg != "self"
                    ]
                    + [a.arg for a in item.args.kwonlyargs],
                )
                index.funcs[qual] = rec
                _PENDING[qual] = (item, cls)
                for sub in item.body:
                    _walk_defs(sub, cls, qual)
            elif isinstance(item, ast.ClassDef):
                _index_class(item, f"{qual_prefix}.{item.name}")

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = f"{module}.{node.name}"
            index.module_funcs[(module, node.name)] = qual
            add_func(node, None, module)
        elif isinstance(node, ast.ClassDef):
            _index_class(node, f"{module}.{node.name}")


# ---------------------------------------------------------------------------
# graph construction + reporting


def _transitive_acquires(index: PackageIndex) -> dict[str, set[str]]:
    ta = {q: set(rec.direct_acquires) for q, rec in index.funcs.items()}
    changed = True
    # bounded fixpoint; the package call graph is small
    for _ in range(40):
        if not changed:
            break
        changed = False
        for qual, rec in index.funcs.items():
            for _, ref, _, cb_args in rec.calls:
                if ref in ta and not ta[ref] <= ta[qual]:
                    ta[qual] |= ta[ref]
                    changed = True
                for _, cb_qual in cb_args:
                    if cb_qual in ta and not ta[cb_qual] <= ta[qual]:
                        ta[qual] |= ta[cb_qual]
                        changed = True
    return ta


def build_lock_graph(
    index: PackageIndex,
) -> dict[tuple[str, str], list[tuple[str, int]]]:
    """edges[(A, B)] = [(path, line), ...]: lock B acquired while A held."""
    ta = _transitive_acquires(index)
    edges: dict[tuple[str, str], list[tuple[str, int]]] = {}

    def add(a: str, b: str, path: str, line: int) -> None:
        if a == b:
            return  # same-site pairs: witness territory (per-instance)
        edges.setdefault((a, b), []).append((path, line))

    for rec in index.funcs.values():
        for node, line, held in rec.acquisitions:
            for h in held:
                add(h, node, rec.path, line)
        for held, ref, line, cb_args in rec.calls:
            callee_locks: set[str] = set()
            if ref is not None and ref in ta:
                callee_locks |= ta[ref]
            for h in held:
                for b in callee_locks:
                    add(h, b, rec.path, line)
            # callback params: locks the callee holds when it CALLS the
            # parameter are ordered before locks the callback takes
            if cb_args and ref is not None and ref in index.funcs:
                callee = index.funcs[ref]
                for key, cb_qual in cb_args:
                    pname = (
                        key
                        if isinstance(key, str)
                        else (
                            callee.params[key]
                            if isinstance(key, int)
                            and key < len(callee.params)
                            else None
                        )
                    )
                    if pname is None or cb_qual not in ta:
                        continue
                    for cheld, cline in callee.param_call_holds.get(
                        pname, []
                    ):
                        for h in cheld:
                            for b in ta[cb_qual]:
                                add(h, b, callee.path, cline)
    return edges


def _find_cycles(
    edges: dict[tuple[str, str], list[tuple[str, int]]]
) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    # Tarjan SCC
    idx_counter = [0]
    stack: list[str] = []
    lowlink: dict[str, int] = {}
    number: dict[str, int] = {}
    on_stack: set[str] = set()
    sccs: list[list[str]] = []

    def strongconnect(v: str) -> None:
        work = [(v, iter(sorted(graph[v])))]
        number[v] = lowlink[v] = idx_counter[0]
        idx_counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in number:
                    number[w] = lowlink[w] = idx_counter[0]
                    idx_counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph[w]))))
                    advanced = True
                    break
                if w in on_stack:
                    lowlink[node] = min(lowlink[node], number[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == number[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(graph):
        if v not in number:
            strongconnect(v)
    return sccs


# ---------------------------------------------------------------------------
# unguarded-write analysis


def _call_contexts(index: PackageIndex) -> tuple[set[str], set[str]]:
    """(ctor_exempt, guarded): a method is CTOR-EXEMPT when every
    in-package call site lives in a constructor, a classmethod
    (`load()`-style alternate constructors), or another ctor-exempt
    method — the object isn't shared yet, so its writes need no lock.
    It is GUARDED when every remaining call site holds some lock —
    the `_refill_locked` idiom of helpers only ever invoked under the
    caller's lock."""
    call_sites: dict[str, list[tuple[str, frozenset]]] = {}
    for rec in index.funcs.values():
        for held, ref, _line, _cb in rec.calls:
            if ref is not None:
                call_sites.setdefault(ref, []).append((rec.qualname, held))

    def own_locks(qual: str) -> frozenset:
        """Lock node-ids belonging to the function's OWN class — a
        write is only 'guarded' under one of these; holding some other
        object's lock does not protect this object's state."""
        rec = index.funcs.get(qual)
        cls = index.func_cls.get(qual)
        if rec is None or rec.cls is None or cls is None:
            return frozenset()
        return frozenset(f"{rec.cls}.{a}" for a in cls.lock_attrs)

    def is_ctor_like(qual: str) -> bool:
        rec = index.funcs.get(qual)
        return (
            qual.rsplit(".", 1)[-1] in _CTOR_METHODS
            or (rec is not None and rec.is_classmethod)
        )

    ctor_exempt: set[str] = set()
    for _ in range(20):  # fixpoint over the (small) call graph
        changed = False
        for qual in index.funcs:
            if qual in ctor_exempt:
                continue
            sites = call_sites.get(qual, [])
            if sites and all(
                is_ctor_like(c) or c in ctor_exempt for c, _ in sites
            ):
                ctor_exempt.add(qual)
                changed = True
        if not changed:
            break
    guarded: set[str] = set()
    for _ in range(20):  # transitive: guarded callers confer the guard
        changed = False
        for qual in index.funcs:
            if qual in guarded:
                continue
            sites = [
                (c, held)
                for c, held in call_sites.get(qual, [])
                if not (is_ctor_like(c) or c in ctor_exempt)
            ]
            if sites and all(
                (held & own_locks(qual)) or c in guarded
                for c, held in sites
            ):
                guarded.add(qual)
                changed = True
        if not changed:
            break
    return ctor_exempt, guarded


def check_unguarded_writes(index: PackageIndex) -> list[Finding]:
    ctor_exempt, guarded_ctx = _call_contexts(index)
    # (class, attr) -> [(line, path, guarded_bool, func_qual)]
    writes: dict[tuple[str, str], list] = {}
    for rec in index.funcs.values():
        if rec.cls is None:
            continue
        name = rec.qualname.rsplit(".", 1)[-1]
        if (
            name in _CTOR_METHODS
            or rec.is_classmethod
            or rec.qualname in ctor_exempt
        ):
            continue
        cls = index.func_cls.get(rec.qualname)
        if cls is None or not cls.lock_attrs:
            continue
        ctx = rec.qualname in guarded_ctx
        own = frozenset(f"{rec.cls}.{a}" for a in cls.lock_attrs)
        for attr, line, held, is_self, _hint in rec.writes:
            if not is_self or attr in cls.lock_attrs:
                continue
            writes.setdefault((cls.module, rec.cls, attr), []).append(
                (line, rec.path, ctx or bool(held & own), rec.qualname,
                 ", ".join(sorted(cls.lock_attrs)))
            )
    findings: list[Finding] = []
    for (_mod, cls_name, attr), sites in sorted(writes.items()):
        if not any(g for _, _, g, _, _ in sites):
            continue  # never lock-guarded: not a guarded attribute
        for line, path, guarded, qual, lock_names in sites:
            if guarded:
                continue
            findings.append(
                Finding(
                    "unguarded-write",
                    path,
                    line,
                    f"{qual} writes {cls_name}.{attr} without holding "
                    f"the class lock ({lock_names}) that guards it at "
                    f"other write sites",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# entry point


def check(root: str | None = None, index: PackageIndex | None = None
          ) -> tuple[list[Finding], PackageIndex]:
    index = index or build_index(root)
    findings: list[Finding] = []
    edges = build_lock_graph(index)
    for scc in _find_cycles(edges):
        locs = []
        in_scc = set(scc)
        for (a, b), sites in sorted(edges.items()):
            if a in in_scc and b in in_scc:
                path, line = sites[0]
                locs.append(f"{a}→{b} at {path}:{line}")
        anchor_path, anchor_line = "seaweedfs_tpu", 1
        for (a, b), sites in sorted(edges.items()):
            if a in in_scc and b in in_scc:
                anchor_path, anchor_line = sites[0]
                break
        findings.append(
            Finding(
                "lock-order",
                anchor_path,
                anchor_line,
                "lock-order cycle (deadlock candidate): "
                + " | ".join(locs),
            )
        )
    findings.extend(check_unguarded_writes(index))
    return findings, index
