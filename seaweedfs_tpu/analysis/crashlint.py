"""weedlint v3: the crash-consistency (durability-order) lint.

PR 9's group commit and PR 2's quarantine/rebuild plane make hard
durability claims ("idx entries only after the batch write", "rename so
rebuild regenerates") that until now lived in comments. This tier turns
the ordering rules themselves into machine-checked contracts, the way
lockorder turned "take the volume lock" into one. The model is the
ALICE observation (PAPERS.md, arXiv:1309.0186 context): a crash
preserves an arbitrary prefix-consistent subset of un-fsynced work, so
any publish that relies on ordering the kernel never promised is a
latent data-loss bug that only fires in the field.

Rules (all statically checked per function, line-order sensitive):

  crash-rename-unsynced-src   os.replace/os.rename whose source file
                              was written in the same function with no
                              fsync of those bytes before the rename —
                              a crash can publish an empty or partial
                              file under the final name
  crash-rename-no-dirsync     a rename with no parent-directory fsync
                              after it — the rename itself may not
                              survive the crash (durable.publish and
                              durable.fsync_dir are the recognized
                              idioms)
  crash-fsync-after-close     fsync/flush of a handle after it was
                              closed — the barrier silently became a
                              no-op (or an EBADF) and everything
                              ordered "after" it is unordered
  crash-idx-before-dat        (storage/ only) a needle-map/.idx publish
                              ordered before the .dat write it indexes
                              — a crash between them surfaces an index
                              entry for bytes that never landed
  crash-replace-unflushed     os.replace of a file whose writing handle
                              is still open with no flush/close — the
                              rename publishes the OS-level bytes,
                              which may be missing the Python buffer
  crash-critical-write        recovery-critical state (scrub_state.json,
                              .vif) opened for direct in-place write
                              instead of the tmp + atomic-publish idiom

Precision over recall, like every weedlint tier: path expressions are
matched structurally (same unparsed expression, or a local variable
holding it); anything the pass cannot resolve is not a finding.
Suppressions use the standard `# weedlint: ignore[rule] — reason`
grammar and the `--stale-suppressions` audit.

The dynamic complement — recording a live workload's effect trace and
re-running recovery against every legal post-crash state — lives in
analysis/crash.py (docs/ANALYSIS.md v3).
"""

from __future__ import annotations

import ast

from seaweedfs_tpu.analysis import Finding, dotted_name as _dotted
from seaweedfs_tpu.analysis.lockorder import PackageIndex, build_index

# Structural exemptions (module-path prefix -> mandatory reason), the
# hotloop._EXEMPT_QUALS convention: the durable helpers ARE the
# publish idiom the rules point at, and the crash-state enumerator
# deliberately materializes arbitrary (including torn) disk states.
_EXEMPT_PATHS: dict[str, str] = {
    "seaweedfs_tpu/util/durable.py": (
        "the fsync/rename/dirsync publish idiom itself — the helper "
        "every rule resolves to (docs/ANALYSIS.md v3)"
    ),
    "seaweedfs_tpu/analysis/crash.py": (
        "the crash-state enumerator: materializing legal POST-crash "
        "states (including torn and unsynced ones) is its purpose"
    ),
}

# basenames whose direct overwrite is a crash window for recovery
# itself (the scrub cursor and the tier metadata are what restart
# reads first); publishes must go through tmp + durable.publish
_CRITICAL_NAMES = ("scrub_state.json", ".vif")

_WRITE_MODES = ("w", "x", "a", "+")


def _is_write_mode(mode: str) -> bool:
    return any(m in mode for m in _WRITE_MODES) and "r" != mode


def _expr_keys(node: ast.expr) -> set[str]:
    """Structural identity keys for a path expression: its unparsed
    text, plus the bare name when it is one (so `tmp = p + ".t"` /
    `open(tmp)` / `os.replace(tmp, p)` all meet)."""
    keys = set()
    try:
        keys.add(ast.unparse(node))
    except Exception:  # pragma: no cover - unparse is total on stdlib ast
        pass
    if isinstance(node, ast.Name):
        keys.add(node.id)
    return keys


def _const_parts(node: ast.expr) -> list[str]:
    """Every literal string fragment inside a path expression
    (concats, f-strings, os.path.join args)."""
    out: list[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
    return out


class _Op:
    __slots__ = ("kind", "line", "keys", "var", "extra")

    def __init__(self, kind, line, keys=frozenset(), var=None, extra=None):
        self.kind = kind
        self.line = line
        self.keys = set(keys)
        self.var = var
        self.extra = extra


def _iter_stmts_excluding_defs(body: list[ast.stmt]):
    """Walk statements without descending into nested function/class
    definitions (those are scanned as their own units)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Lambda):
                continue
            stack.append(child)


def _collect_ops(body: list[ast.stmt], var_exprs: dict[str, set[str]]
                 ) -> list[_Op]:
    """One linear pass over a function body: every durability-relevant
    operation with its line and structural path keys."""
    ops: list[_Op] = []
    for node in _iter_stmts_excluding_defs(body):
        # var = <expr> : remember what path expression a local holds
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
            if isinstance(node.value, ast.Call):
                call = node.value
                dotted = _dotted(call.func)
                if dotted.rsplit(".", 1)[-1] == "open" and call.args:
                    mode = ""
                    if len(call.args) > 1:
                        m = call.args[1]
                        if isinstance(m, ast.Constant) and isinstance(
                            m.value, str
                        ):
                            mode = m.value
                    for kw in call.keywords:
                        if kw.arg == "mode" and isinstance(
                            kw.value, ast.Constant
                        ):
                            mode = str(kw.value.value)
                    keys = _expr_keys(call.args[0])
                    for k in list(keys):
                        keys |= var_exprs.get(k, set())
                    ops.append(_Op(
                        "open", node.lineno, keys, var=target,
                        extra={
                            "mode": mode,
                            "with": False,
                            "consts": _const_parts(call.args[0]),
                            "os_open": dotted.startswith("os."),
                        },
                    ))
                    continue
                # any other call result rebinds the name: a close mark
                # on the old value must not follow the new one
                ops.append(_Op("assign", node.lineno, var=target))
                continue
            else:
                var_exprs[target] = _expr_keys(node.value)
                ops.append(_Op("assign", node.lineno, var=target))
                continue
        if isinstance(node, ast.withitem) and isinstance(
            node.context_expr, ast.Call
        ):
            call = node.context_expr
            dotted = _dotted(call.func)
            if dotted.rsplit(".", 1)[-1] == "open" and call.args:
                mode = ""
                if len(call.args) > 1 and isinstance(
                    call.args[1], ast.Constant
                ) and isinstance(call.args[1].value, str):
                    mode = call.args[1].value
                for kw in call.keywords:
                    if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                        mode = str(kw.value.value)
                var = (
                    node.optional_vars.id
                    if isinstance(node.optional_vars, ast.Name)
                    else None
                )
                keys = _expr_keys(call.args[0])
                for k in list(keys):
                    keys |= var_exprs.get(k, set())
                ops.append(_Op(
                    "open", call.lineno, keys, var=var,
                    extra={
                        "mode": mode, "with": True,
                        "consts": _const_parts(call.args[0]),
                        "os_open": False,
                    },
                ))
            continue
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        tail = dotted.rsplit(".", 1)[-1]
        if dotted in ("os.replace", "os.rename") and len(node.args) >= 2:
            src_keys = _expr_keys(node.args[0])
            for k in list(src_keys):
                src_keys |= var_exprs.get(k, set())
            ops.append(_Op("rename", node.lineno, src_keys))
        elif dotted == "os.fsync" and node.args:
            arg = node.args[0]
            # os.fsync(f.fileno()) -> barrier on f's file; os.fsync(fd)
            # -> barrier on the fd variable
            if (
                isinstance(arg, ast.Call)
                and isinstance(arg.func, ast.Attribute)
                and arg.func.attr == "fileno"
            ):
                ops.append(_Op(
                    "fsync", node.lineno, var=_dotted(arg.func.value) or None
                ))
            else:
                ops.append(_Op(
                    "fsync", node.lineno, _expr_keys(arg),
                    var=_dotted(arg) or None,
                ))
        elif tail == "fsync_path" and node.args:
            keys = _expr_keys(node.args[0])
            for k in list(keys):
                keys |= var_exprs.get(k, set())
            ops.append(_Op("fsync", node.lineno, keys))
        elif tail == "fsync_dir":
            ops.append(_Op("dirsync", node.lineno))
        elif tail == "publish" and (
            dotted in ("publish", "durable.publish")
            or dotted.endswith(".durable.publish")
        ) and len(node.args) >= 2:
            # durable.publish = fsync(src) + rename + dirsync in one
            keys = _expr_keys(node.args[0])
            for k in list(keys):
                keys |= var_exprs.get(k, set())
            ops.append(_Op("fsync", node.lineno, keys))
            ops.append(_Op("dirsync", node.lineno))
        elif dotted == "os.close" and node.args:
            ops.append(_Op("close", node.lineno, var=_dotted(node.args[0]) or None))
        elif tail == "close" and isinstance(node.func, ast.Attribute):
            ops.append(_Op("close", node.lineno, var=_dotted(node.func.value) or None))
        elif tail == "flush" and isinstance(node.func, ast.Attribute):
            ops.append(_Op("flush", node.lineno, var=_dotted(node.func.value) or None))
        elif dotted in ("os.pwrite", "os.pwritev") or tail == "_append_blob":
            ops.append(_Op("dat-write", node.lineno))
        elif tail in ("put", "delete", "_append_index") and isinstance(
            node.func, ast.Attribute
        ):
            recv = _dotted(node.func.value)
            if recv.endswith("nm") or tail == "_append_index":
                ops.append(_Op("idx-publish", node.lineno))
    ops.sort(key=lambda o: o.line)
    return ops


def _scan_unit(path: str, body: list[ast.stmt], in_storage: bool,
               qual: str) -> list[Finding]:
    var_exprs: dict[str, set[str]] = {}
    ops = _collect_ops(body, var_exprs)
    findings: list[Finding] = []
    opens = [o for o in ops if o.kind == "open"]
    renames = [o for o in ops if o.kind == "rename"]
    fsyncs = [o for o in ops if o.kind == "fsync"]
    dirsyncs = [o for o in ops if o.kind == "dirsync"]

    def file_barriers(open_op: _Op) -> list[int]:
        """Lines at which open_op's bytes were fsynced (by path key or
        through its handle variable)."""
        lines = []
        for f in fsyncs:
            if f.keys & open_op.keys:
                lines.append(f.line)
            elif f.var and open_op.var and f.var == open_op.var:
                lines.append(f.line)
        return lines

    for rn in renames:
        # --- crash-rename-unsynced-src --------------------------------
        written = [
            o for o in opens
            if o.line < rn.line and o.keys & rn.keys
            and _is_write_mode(o.extra["mode"])
        ]
        if written:
            src_open = written[-1]
            synced = any(
                src_open.line <= line <= rn.line
                for line in file_barriers(src_open)
            )
            if not synced:
                findings.append(Finding(
                    "crash-rename-unsynced-src", path, rn.line,
                    f"{qual}: source file written at line {src_open.line} "
                    f"is renamed with no fsync of its bytes first — a "
                    f"crash can publish an empty or partial file under "
                    f"the destination name (use util.durable.publish)",
                ))
            # --- crash-replace-unflushed ------------------------------
            if not src_open.extra["with"] and src_open.var:
                closed = any(
                    o.kind in ("close", "flush")
                    and o.var == src_open.var
                    and src_open.line <= o.line <= rn.line
                    for o in ops
                ) or synced
                if not closed:
                    findings.append(Finding(
                        "crash-replace-unflushed", path, rn.line,
                        f"{qual}: renaming a file whose writing handle "
                        f"(line {src_open.line}) was neither flushed nor "
                        f"closed — the rename publishes OS-level bytes "
                        f"that may be missing the Python buffer",
                    ))
        # --- crash-rename-no-dirsync ----------------------------------
        if not any(d.line >= rn.line for d in dirsyncs):
            findings.append(Finding(
                "crash-rename-no-dirsync", path, rn.line,
                f"{qual}: rename is never followed by a parent-directory "
                f"fsync in this function — the rename itself may not "
                f"survive a crash (durable.fsync_dir / durable.publish)",
            ))

    # --- crash-fsync-after-close --------------------------------------
    closes: dict[str, int] = {}
    for o in ops:
        if o.kind == "close" and o.var:
            closes[o.var] = o.line
        elif o.kind in ("open", "assign") and o.var in closes:
            del closes[o.var]  # rebound/reopened: the close mark is stale
        elif o.kind in ("fsync", "flush") and o.var and o.var in closes:
            findings.append(Finding(
                "crash-fsync-after-close", path, o.line,
                f"{qual}: {o.kind} of {o.var!r} after its close at line "
                f"{closes[o.var]} — the durability barrier is a no-op "
                f"and everything ordered after it is unordered",
            ))

    # --- crash-idx-before-dat (storage/ only) -------------------------
    if in_storage:
        dat_lines = [o.line for o in ops if o.kind == "dat-write"]
        idx_lines = [o.line for o in ops if o.kind == "idx-publish"]
        if dat_lines and idx_lines and min(idx_lines) < min(dat_lines):
            findings.append(Finding(
                "crash-idx-before-dat", path, min(idx_lines),
                f"{qual}: needle-map/.idx publish at line "
                f"{min(idx_lines)} precedes the first .dat write at "
                f"line {min(dat_lines)} — a crash between them surfaces "
                f"an index entry for bytes that never landed",
            ))

    # --- crash-critical-write -----------------------------------------
    for o in opens:
        if not _is_write_mode(o.extra["mode"]) or "a" in o.extra["mode"]:
            continue
        consts = o.extra["consts"]
        if any(
            crit in c for c in consts for crit in _CRITICAL_NAMES
        ) and not any(".tmp" in c for c in consts):
            findings.append(Finding(
                "crash-critical-write", path, o.line,
                f"{qual}: recovery-critical state opened for direct "
                f"in-place write — a crash mid-write leaves a torn file "
                f"where restart recovery reads first; write a .tmp and "
                f"durable.publish it",
            ))
    return findings


def check(root: str | None = None, index: PackageIndex | None = None
          ) -> tuple[list[Finding], PackageIndex]:
    index = index or build_index(root)
    findings: list[Finding] = []
    for path, source in sorted(index.sources.items()):
        if path.replace("\\", "/") in _EXEMPT_PATHS:
            continue
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:  # pragma: no cover - index already parsed it
            continue
        in_storage = "/storage/" in path.replace("\\", "/")
        module_qual = path.replace("\\", "/")
        # module level (rare but legal place for a publish)
        findings += _scan_unit(
            path,
            [n for n in tree.body],
            in_storage,
            f"{module_qual} (module level)",
        )
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings += _scan_unit(
                    path, node.body, in_storage, node.name
                )
    # one site can surface through module-level AND nested walks
    seen: set[tuple[str, int, str]] = set()
    out: list[Finding] = []
    for f in findings:
        key = (f.path, f.line, f.rule)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out, index
