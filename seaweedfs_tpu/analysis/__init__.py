"""weedlint: the repo-native static-analysis & sanitizer plane.

The Go reference inherited `go vet` and the `-race` detector for free;
this Python/C port lost both exactly as it grew the things they exist
to catch — ~70 lock sites across the threaded volume/scrub/repair/
replication planes, and ~900 lines of hand-written C parsing
adversarial multipart bytes with the GIL released. This package is the
replacement tooling, purpose-built for THIS codebase's invariants
rather than generic style lint:

  lockorder   static lock-acquisition graph over the whole package
              (with-blocks, explicit acquire/release, one-level
              interprocedural closure incl. callback parameters);
              reports cycles as deadlock candidates, plus writes to
              lock-guarded attributes reached without the guard
  hotloop     blocking calls (sleep, subprocess, socket ops without a
              timeout, unbounded reads) reachable from the data-plane
              dispatch paths (FastHandler do_* / serve_connection)
  ctier       the C shim tier compiled under -Wall -Wextra -Werror
              (the compiler is the lint tier for code no Python tool
              can see into), sanitizer build modes, and structural
              GIL-release checks on the hot entry points
  witness     the DYNAMIC lock-order witness: a pytest plugin that
              wraps threading.Lock/RLock allocation and fails the run
              on any runtime acquisition-order inversion — our
              `-race`-style complement for lock orders that only
              materialize through callbacks and cross-object calls
              the static pass cannot resolve
  fuzz_post   structured fuzzer hammering the C multipart/POST parser
              against the byte-identical Python fallback; diverging
              or crashing inputs persist to tests/corpus/
  crashlint   crash-consistency durability-order lint (v3): rename
              without fsync of file+parent dir, fsync-after-close,
              .idx published before its .dat write, unflushed rename
              sources, recovery-critical state mutated outside the
              tmp + durable.publish idiom
  crash       the DYNAMIC crash plane: records a live workload's
              effect trace (pwrite/pwritev/fsync/rename shim),
              enumerates every legal post-crash disk state (prefix
              writes, torn final write, renames landing before data),
              and re-runs real recovery against each one asserting
              no acked needle lost / no torn record valid / idx never
              past .dat
  racelint    shared-state escape lint (v4): check-then-act on
              attributes of classes whose instances escape to another
              thread (Thread/Timer/pool-submit/module singleton,
              containment fixpoint), where check and act sit under
              different lock states — including two SEPARATE holds of
              the same lock (atomicity is the span, not the lock)
  race        the DYNAMIC race plane: a controlled scheduler running
              the tree's concurrency shapes (admission, tile cache,
              group commit, first-k gather, handoff, single-flight)
              under explored interleavings with replay tokens; plus
              the bounded cross-process model check of the shm GCRA
              bucket (load/CAS interleavings incl. SIGKILL arms)

CLI: `python -m seaweedfs_tpu.analysis` (exit 0 = clean tree).

Suppression policy: a finding is silenced ONLY by an inline

    # weedlint: ignore[rule] — reason

comment on the flagged line (or the line directly above it). The
reason is mandatory; an ignore without one is itself a finding
(rule `bare-ignore`), so the tree can never accumulate unexplained
silence. docs/ANALYSIS.md is the checker catalog.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

PACKAGE_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPO_ROOT = os.path.dirname(PACKAGE_ROOT)

# `# weedlint: ignore[rule-a,rule-b] — why this is fine`; markdown
# files (contract findings anchor in docs) use the same grammar inside
# an HTML comment: `<!-- weedlint: ignore[rule] — reason -->`
_IGNORE_RE = re.compile(
    r"(?:#|<!--)\s*weedlint:\s*ignore\[([a-z0-9_,\s-]+)\]\s*(?:[—:-]+\s*(.*))?"
)


def dotted_name(node: ast.expr) -> str:
    """'urllib.request.urlopen'-style dotted name, '' when the chain
    bottoms out in anything but a plain Name. Shared by every AST
    checker (hotloop, contracts, lifecycle) — one definition, so a
    future fix cannot silently diverge between tiers."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_str(node: ast.expr) -> "str | None":
    """The literal string value of a Constant node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class Suppressions:
    """Parsed `# weedlint: ignore[...]` comments for one file."""

    # line -> set of rules silenced at that line
    by_line: dict[int, set[str]] = field(default_factory=dict)
    # ignores missing the mandatory reason (line, rules)
    bare: list[tuple[int, str]] = field(default_factory=list)
    # every well-formed ignore: (comment_line, target_line, rules) —
    # the substrate of the --stale-suppressions audit
    records: list[tuple[int, int, frozenset]] = field(
        default_factory=list
    )


def scan_suppressions(source: str) -> Suppressions:
    sup = Suppressions()
    for i, text in enumerate(source.splitlines(), start=1):
        m = _IGNORE_RE.search(text)
        if m is None:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        if reason.endswith("-->"):  # markdown comment closer
            reason = reason[:-3].rstrip()
        if len(reason) < 3:
            sup.bare.append((i, ",".join(sorted(rules))))
            continue
        if text.lstrip().startswith(("#", "<!--")):
            # a comment on its OWN line silences only the statement
            # below it — an inline ignore must never bleed onto the
            # next line, or an adjacent unannotated finding ships
            # under a neighbor's justification
            target = i + 1
        else:
            target = i
        sup.by_line.setdefault(target, set()).update(rules)
        sup.records.append((i, target, frozenset(rules)))
    return sup


def iter_py_files(root: str | None = None):
    """Yield (abs_path, rel_path) for every package .py file."""
    root = root or PACKAGE_ROOT
    base = os.path.dirname(root)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d != "__pycache__" and not d.startswith(".")
        ]
        for name in sorted(filenames):
            if name.endswith(".py"):
                abs_path = os.path.join(dirpath, name)
                yield abs_path, os.path.relpath(abs_path, base)


def apply_suppressions(
    findings: list[Finding], sources: dict[str, str]
) -> tuple[list[Finding], list[Finding]]:
    """(kept, suppressed). Bare ignores surface as `bare-ignore`
    findings in `kept` — an unjustified suppression never makes the
    tree greener."""
    sup_cache: dict[str, Suppressions] = {}
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    for path, src in sources.items():
        sup_cache[path] = scan_suppressions(src)
    for f in findings:
        sup = sup_cache.get(f.path)
        rules = sup.by_line.get(f.line, set()) if sup else set()
        if f.rule in rules or "all" in rules:
            suppressed.append(f)
        else:
            kept.append(f)
    for path, sup in sup_cache.items():
        for line, rules in sup.bare:
            kept.append(
                Finding(
                    "bare-ignore",
                    path,
                    line,
                    f"weedlint ignore[{rules}] without a reason — the "
                    f"justification is mandatory",
                )
            )
    return kept, suppressed


# rule tokens that mark a grammar EXAMPLE, not a live suppression —
# the docs and these modules' docstrings spell the syntax with them
_PLACEHOLDER_RULES = frozenset({"rule", "rule-a", "rule-b", "rule-name"})


def find_stale_suppressions(
    suppressed: list[Finding], sources: dict[str, str]
) -> list[Finding]:
    """`--stale-suppressions`: every well-formed ignore comment whose
    rule no longer fires on its line is itself a finding — silence that
    outlived its bug reads as an active hazard to the next maintainer
    (and hides the NEXT real finding that lands on that line). An
    ignore citing a rule NAME no checker emits is the worst case —
    PR 5 shipped one (`hot-loop-lock`) that suppressed nothing for two
    whole PRs."""
    fired: set[tuple[str, int, str]] = {
        (f.path, f.line, f.rule) for f in suppressed
    }
    fired_lines: set[tuple[str, int]] = {
        (f.path, f.line) for f in suppressed
    }
    out: list[Finding] = []
    for path, src in sources.items():
        for comment_line, target, rules in scan_suppressions(src).records:
            if rules <= _PLACEHOLDER_RULES:
                continue  # syntax documentation, not a suppression
            live = (
                ("all" in rules and (path, target) in fired_lines)
                or any((path, target, r) in fired for r in rules)
            )
            if not live:
                out.append(
                    Finding(
                        "stale-suppression",
                        path,
                        comment_line,
                        f"ignore[{','.join(sorted(rules))}] no longer "
                        f"suppresses anything — the rule does not fire "
                        f"here; delete the comment (it hides the next "
                        f"real finding on this line)",
                    )
                )
    return out
