"""weedlint CLI: `python -m seaweedfs_tpu.analysis`.

Runs every checker over the package tree and exits 0 only when the
tree is clean (no unsuppressed findings — and no suppression missing
its mandatory reason). This is the same gate `bench.py --check` and
`make lint` drive; docs/ANALYSIS.md is the catalog.

    python -m seaweedfs_tpu.analysis                # all checkers
    python -m seaweedfs_tpu.analysis --rules lock-order,hot-loop
    python -m seaweedfs_tpu.analysis --json         # machine-readable
    python -m seaweedfs_tpu.analysis --fuzz 200     # + fuzz smoke
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from seaweedfs_tpu.analysis import Finding, apply_suppressions

# rule families, in the order they run; --rules filters by prefix,
# e.g. `--rules lock-order`. lock-order and unguarded-write are
# separate families that share one index walk — selecting either
# runs the walk once and keeps only the selected family's findings
_FAMILIES = ("lock-order", "unguarded-write", "hot-loop", "c")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="python -m seaweedfs_tpu.analysis")
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated rule prefixes to run (default: all)",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    ap.add_argument(
        "--fuzz",
        type=int,
        default=0,
        metavar="N",
        help="also run N iterations of the C-vs-Python POST fuzzer",
    )
    args = ap.parse_args(argv)
    wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
    for w in wanted:
        if not any(
            w.startswith(f) or f.startswith(w) for f in _FAMILIES
        ):
            ap.error(
                f"--rules {w!r} matches no checker family "
                f"{list(_FAMILIES)}"
            )

    def active(family: str) -> bool:
        # both directions: `--rules lock-order` selects the family,
        # and `--rules hot-loop-no-timeout` (a full rule name) selects
        # its `hot-loop` family rather than silently selecting nothing
        return not wanted or any(
            w.startswith(family) or family.startswith(w) for w in wanted
        )

    t0 = time.time()
    findings: list[Finding] = []
    index = None

    if active("lock-order") or active("unguarded-write"):
        from seaweedfs_tpu.analysis import lockorder

        lock_findings, index = lockorder.check()
        if active("lock-order"):
            findings += [f for f in lock_findings if f.rule == "lock-order"]
        if active("unguarded-write"):
            findings += [
                f for f in lock_findings if f.rule == "unguarded-write"
            ]
    elif active("hot-loop"):
        # hot-loop alone only needs the package index, not the full
        # lock-graph/cycle/unguarded-write analyses
        from seaweedfs_tpu.analysis import lockorder

        index = lockorder.build_index()
    if active("hot-loop"):
        from seaweedfs_tpu.analysis import hotloop

        hot_findings, index = hotloop.check(index=index)
        findings += hot_findings
    if active("c"):
        from seaweedfs_tpu.analysis import ctier

        findings += ctier.check()

    if index is None:
        # `--rules c` alone never walked the package, but the bare-ignore
        # contract (every suppression carries a reason) must hold on
        # every invocation path, so build the source index regardless
        from seaweedfs_tpu.analysis import lockorder

        index = lockorder.build_index()
    kept, suppressed = apply_suppressions(findings, index.sources)

    fuzz_report = None
    if args.fuzz > 0:
        from seaweedfs_tpu.analysis import fuzz_post

        fuzz_report = fuzz_post.run(iterations=args.fuzz)
        for div in fuzz_report.divergences:
            kept.append(
                Finding(
                    "fuzz-divergence",
                    "seaweedfs_tpu/native/post.c",
                    1,
                    f"C and Python POST paths diverged: {div}",
                )
            )

    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json:
        out = {
            "findings": [f.__dict__ for f in kept],
            "suppressed": [f.__dict__ for f in suppressed],
            "elapsed_s": round(time.time() - t0, 2),
            "ok": not kept,
        }
        if fuzz_report is not None:
            out["fuzz"] = fuzz_report.to_dict()
        print(json.dumps(out, indent=2))
    else:
        for f in kept:
            print(f.format())
        note = (
            f"weedlint: {len(kept)} finding(s), "
            f"{len(suppressed)} suppressed (justified), "
            f"{time.time() - t0:.1f}s"
        )
        if fuzz_report is not None:
            note += (
                f"; fuzz {fuzz_report.iterations} iters, "
                f"{fuzz_report.handled} C-handled, "
                f"{len(fuzz_report.divergences)} divergence(s)"
            )
        print(note)
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
