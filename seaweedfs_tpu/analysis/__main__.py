"""weedlint CLI: `python -m seaweedfs_tpu.analysis`.

Runs every checker over the package tree and exits 0 only when the
tree is clean (no unsuppressed findings — and no suppression missing
its mandatory reason). This is the same gate `bench.py --check` and
`make lint` drive; docs/ANALYSIS.md is the catalog.

    python -m seaweedfs_tpu.analysis                   # all checkers
    python -m seaweedfs_tpu.analysis --rules contracts,lifecycle
    python -m seaweedfs_tpu.analysis --json            # machine-readable
    python -m seaweedfs_tpu.analysis --fuzz 200        # + fuzz smoke
    python -m seaweedfs_tpu.analysis --stale-suppressions  # audit ignores
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from seaweedfs_tpu.analysis import (
    Finding,
    apply_suppressions,
    find_stale_suppressions,
)

# rule families, in the order they run; --rules filters by prefix,
# e.g. `--rules lock-order`. lock-order and unguarded-write are
# separate families that share one index walk — selecting either
# runs the walk once and keeps only the selected family's findings
_FAMILIES = {
    "lock-order": (
        "static lock-acquisition graph: cycles are deadlock candidates"
    ),
    "unguarded-write": (
        "writes to lock-guarded attributes reached without the guard"
    ),
    "hot-loop": (
        "blocking calls (sleep/subprocess/deadline-less IO) reachable "
        "from the FastHandler dispatch tree"
    ),
    "c": (
        "C shim tier: -Wall -Wextra -Werror compile + structural "
        "Py_BEGIN_ALLOW_THREADS checks"
    ),
    "contracts": (
        "cross-component string contracts: served routes vs client "
        "paths, registered vs referenced metrics, stamped vs parsed "
        "headers, fast_reply statuses vs _REASON, WEED_* env vars and "
        "CLI flags vs docs"
    ),
    "lifecycle": (
        "fd/socket/thread acquire-release pairing: early-return leaks, "
        "started-never-joined threads (interprocedural, owns[] aware)"
    ),
    "crash": (
        "crash-consistency durability ordering: write-then-rename "
        "without fsync of file + parent dir, fsync-after-close, .idx "
        "publish before its .dat write, unflushed os.replace sources, "
        "recovery-critical state mutated outside atomic publish"
    ),
    "race": (
        "shared-state escape lint: check-then-act on attributes of "
        "objects that escape to another thread (Thread targets/args, "
        "pool submits, module-global singletons) where check and act "
        "share no continuous lock hold — two separate holds of the "
        "SAME lock count as torn"
    ),
}


def main(argv: list[str] | None = None) -> int:
    tier_help = "; ".join(f"{k}: {v}" for k, v in _FAMILIES.items())
    ap = argparse.ArgumentParser(
        prog="python -m seaweedfs_tpu.analysis",
        description="weedlint — the repo-native static-analysis plane "
        "(docs/ANALYSIS.md). Tiers: " + tier_help,
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated tier prefixes to run (default: all of "
        + ", ".join(_FAMILIES) + ")",
    )
    ap.add_argument(
        "--json", action="store_true", help="machine-readable output "
        "(includes the contract registries when the contracts tier runs)"
    )
    ap.add_argument(
        "--fuzz",
        type=int,
        default=0,
        metavar="N",
        help="also run N iterations of the C-vs-Python POST fuzzer",
    )
    ap.add_argument(
        "--stale-suppressions",
        action="store_true",
        help="audit mode: run every tier, then report each "
        "`# weedlint: ignore[...]` whose rule no longer fires on its "
        "line (silence that outlived its bug)",
    )
    args = ap.parse_args(argv)
    wanted = [r.strip() for r in args.rules.split(",") if r.strip()]
    if args.stale_suppressions and wanted:
        ap.error("--stale-suppressions audits ALL tiers; drop --rules")

    def matches(w: str, family: str) -> bool:
        # exact family, a full rule name within it (`hot-loop-no-timeout`
        # → hot-loop, `contract-route` → contracts), or a shorthand
        # prefix (`lock` → lock-order). A token that IS another family's
        # exact name never prefix-matches across the boundary — `c` must
        # select only the C tier, never `contracts` (and vice versa).
        if w == family:
            return True
        if w.startswith(family + "-"):
            return True
        if family == "contracts" and w.startswith("contract-"):
            return True
        if family == "contracts" and w == "no-deadline":
            return True  # the deadline-bypass rule rides this tier
        return w not in _FAMILIES and family.startswith(w)

    for w in wanted:
        if not any(matches(w, f) for f in _FAMILIES):
            ap.error(
                f"--rules {w!r} matches no checker family "
                f"{list(_FAMILIES)}"
            )

    def active(family: str) -> bool:
        return not wanted or any(matches(w, family) for w in wanted)

    t0 = time.time()
    findings: list[Finding] = []
    index = None
    registry = None

    if active("lock-order") or active("unguarded-write"):
        from seaweedfs_tpu.analysis import lockorder

        lock_findings, index = lockorder.check()
        if active("lock-order"):
            findings += [f for f in lock_findings if f.rule == "lock-order"]
        if active("unguarded-write"):
            findings += [
                f for f in lock_findings if f.rule == "unguarded-write"
            ]
    if index is None and (
        active("hot-loop") or active("contracts") or active("lifecycle")
        or active("crash") or active("race")
    ):
        # these tiers only need the package index, not the full
        # lock-graph/cycle/unguarded-write analyses
        from seaweedfs_tpu.analysis import lockorder

        index = lockorder.build_index()
    if active("hot-loop"):
        from seaweedfs_tpu.analysis import hotloop

        hot_findings, index = hotloop.check(index=index)
        findings += hot_findings
    if active("contracts"):
        from seaweedfs_tpu.analysis import contracts

        contract_findings, index, registry = contracts.check(index=index)
        findings += contract_findings
    if active("lifecycle"):
        from seaweedfs_tpu.analysis import lifecycle

        life_findings, index = lifecycle.check(index=index)
        findings += life_findings
    if active("crash"):
        from seaweedfs_tpu.analysis import crashlint

        crash_findings, index = crashlint.check(index=index)
        findings += crash_findings
    if active("race"):
        from seaweedfs_tpu.analysis import racelint

        race_findings, index = racelint.check(index=index)
        findings += race_findings
    if active("c"):
        from seaweedfs_tpu.analysis import ctier

        findings += ctier.check()

    if index is None:
        # `--rules c` alone never walked the package, but the bare-ignore
        # contract (every suppression carries a reason) must hold on
        # every invocation path, so build the source index regardless
        from seaweedfs_tpu.analysis import lockorder

        index = lockorder.build_index()
    kept, suppressed = apply_suppressions(findings, index.sources)
    if args.stale_suppressions:
        kept += find_stale_suppressions(suppressed, index.sources)

    fuzz_report = None
    if args.fuzz > 0:
        from seaweedfs_tpu.analysis import fuzz_post

        fuzz_report = fuzz_post.run(iterations=args.fuzz)
        for div in fuzz_report.divergences:
            kept.append(
                Finding(
                    "fuzz-divergence",
                    "seaweedfs_tpu/native/post.c",
                    1,
                    f"C and Python POST paths diverged: {div}",
                )
            )

    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.json:
        out = {
            "findings": [f.__dict__ for f in kept],
            "suppressed": [f.__dict__ for f in suppressed],
            "elapsed_s": round(time.time() - t0, 2),
            "ok": not kept,
        }
        if registry is not None:
            out["contracts"] = registry.to_dict()
        if fuzz_report is not None:
            out["fuzz"] = fuzz_report.to_dict()
        print(json.dumps(out, indent=2))
    else:
        for f in kept:
            print(f.format())
        note = (
            f"weedlint: {len(kept)} finding(s), "
            f"{len(suppressed)} suppressed (justified), "
            f"{time.time() - t0:.1f}s"
        )
        if fuzz_report is not None:
            note += (
                f"; fuzz {fuzz_report.iterations} iters, "
                f"{fuzz_report.handled} C-handled, "
                f"{len(fuzz_report.divergences)} divergence(s)"
            )
        print(note)
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
