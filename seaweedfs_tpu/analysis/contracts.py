"""Cross-component contract checker: the string-keyed edges nothing
else verifies.

PRs 4-6 grew the system into a genuinely distributed stack held
together by string contracts: HTTP routes a daemon serves vs paths its
clients dial, metric families registered in stats/metrics.py vs names
alert rules and docs query, internal hop headers stamped on one side
vs parsed on the other, `WEED_*` env vars read vs documented. Python
checks none of these — the filer UI shipped a `/metrics` link its own
router 404'd for a whole PR, and a renamed metric would silently turn
an alert rule into a constant-false no-op. This pass extracts every
side of each contract into a registry and reports one-sided edges:

  contract-route        a literal path dialed by an in-repo client
                        (op/http_call, urlopen, shell commands,
                        announce loop, UI href) that NO dispatch table
                        serves; relative UI links must be served by
                        the SAME module's handler (that is exactly the
                        drift the filer UI bug rode in on)
  contract-metric       a metric name queried (ring rate_sum/quantile/
                        increase_sum, alert wiring) or documented that
                        no Registry call registers
  contract-metric-orphan a registered family with no writer and no
                        reader anywhere — it renders constant-zero
                        rows that LOOK like instrumentation
  contract-header       an internal hop header (x-weed-*, x-shard-*)
                        stamped but never parsed, or parsed but never
                        stamped
  contract-status-reason a literal status code passed to fast_reply
                        (or the _json/_html/_reply wrappers) missing
                        from util/httpd._REASON — the reply line lies
                        `200 OK`-style ("404 OK") to the peer
  contract-env          a `WEED_*` env var read in code but absent
                        from docs/OPERATIONS (operators cannot know
                        it), or documented but read nowhere (doc rot)
  contract-flag         a `-flag` token documented in docs that no
                        add_argument defines (doc rot), or a defined
                        flag with no help= text (the CLI's only
                        self-documentation)
  no-deadline           a raw urlopen() on a data-plane module
                        (server/client/filer/ec/qos/scrub/s3api/
                        webdav): it can never inherit the request's
                        X-Weed-Deadline budget (docs/CHAOS.md) the way
                        op.http_call and the gRPC Stub do, so a
                        multi-hop request outlives its caller's intent
                        there — migrate to http_call or state why the
                        bounded one-hop timeout suffices

Suppression uses the standard `# weedlint: ignore[rule] — reason`
mechanism; findings anchored in markdown use the same comment inside
`<!-- ... -->`.

Like every weedlint pass: precision over recall. Dynamic paths
(`f"/{fid}"`), constructed env names, and prefix-routed gateways (S3
bucket routing, WebDAV) are skipped, not guessed at.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from seaweedfs_tpu.analysis import (
    Finding,
    REPO_ROOT,
    const_str as _const_str,
    dotted_name as _dotted,
)
from seaweedfs_tpu.analysis.lockorder import PackageIndex, build_index

# handler base classes — a class deriving (transitively) from one of
# these owns a dispatch table; its methods are where route comparisons
# live (mirrors hotloop's entry-point discovery)
_HANDLER_BASES = {
    "FastHandler",
    "FastRequestMixin",
    "BaseHTTPRequestHandler",
    "StreamRequestHandler",
}

# module (repo-relative path substring) -> daemon key. Relative UI
# links and host-hinted client calls are checked against the daemon's
# own route set plus the mini-loop funnel, not the whole-cluster union
# — a route another daemon serves must not mask this daemon's 404.
_DAEMON_MODULES = {
    os.path.join("server", "master_server.py"): "master",
    os.path.join("server", "volume_server.py"): "volume",
    os.path.join("server", "volume_workers.py"): "volume",
    os.path.join("server", "filer_server.py"): "filer",
    os.path.join("s3api", "s3api_server.py"): "s3",
    os.path.join("webdav", "webdav_server.py"): "webdav",
}

# The mini-loop funnel (util/httpd.serve_connection/_serve_debug)
# serves these on EVERY daemon, before per-server routing; extracted
# from util/httpd.py like any other dispatch, but kept as their own
# daemon key so per-daemon checks can union them in.
_FUNNEL_DAEMON = "_funnel"

# client-call sites whose URLs leave the cluster — their paths belong
# to an external service's contract, not ours. Reasons are mandatory,
# mirroring hotloop._EXEMPT_QUALS.
_EXTERNAL_CLIENT_MODULES: dict[str, str] = {
    os.path.join("seaweedfs_tpu", "util", "etcd.py"): (
        "etcd v2/v3 HTTP API paths are etcd's contract"
    ),
    os.path.join("seaweedfs_tpu", "notification", "cloud_queues.py"): (
        "SQS/PubSub-style endpoints are the cloud provider's contract"
    ),
    os.path.join("seaweedfs_tpu", "replication", "cloud_sinks.py"): (
        "object-store sink endpoints are the cloud provider's contract"
    ),
    os.path.join("seaweedfs_tpu", "stats", "metrics.py"): (
        "the push loop POSTs to an external pushgateway "
        "(/metrics/job/<job> is its API, not ours)"
    ),
    os.path.join("seaweedfs_tpu", "s3api", "client.py"): (
        "S3 SDK client: bucket/key routing is dynamic by design"
    ),
    os.path.join("seaweedfs_tpu", "filesys"): (
        "filer paths are user namespace entries, not routes"
    ),
}

# -flag tokens that appear in docs but belong to EXTERNAL tools (the
# compiler, Go's race detector, pytest) — documented deliberately,
# never defined by our argparse surface.
_EXTERNAL_DOC_FLAGS: dict[str, str] = {
    "race": "Go's -race detector, cited as prior art in ANALYSIS.md",
    "fsanitize": "compiler flag in sanitizer-build recipes",
    "print": "cc -print-file-name in the ASan preload recipe",
    "rdonly": "mount(8) option in operational recipes",
    "Wall": "compiler flag: the C tier's production command line",
    "Wextra": "compiler flag: the C tier's production command line",
    "Werror": "compiler flag: the C tier's production command line",
}

_METRIC_NAME_RE = re.compile(r"\b[a-z][a-z0-9_]*_(?:total|seconds|bytes)\b")
_WEED_METRIC_RE = re.compile(r"\bweed_[a-z0-9_]+\b")
_ENV_VAR_RE = re.compile(r"\bWEED_[A-Z0-9_]+\b")
# the lookbehind rejects `X`-style prose where the "opening" backtick
# is really the CLOSING backtick of a previous code span
_DOC_FLAG_RE = re.compile(
    r"(?<![\w`])`-([a-zA-Z][a-zA-Z0-9]{2,})(?:[ =][^`]*)?`"
)
_HREF_RE = re.compile(r"""(?:href|src|action)=["'](/[^"'?#\s]*)""")
_INTERNAL_HEADER_RE = re.compile(r"^(x-weed-|x-shard-)", re.IGNORECASE)


@dataclass
class Site:
    path: str  # repo-relative
    line: int


@dataclass
class ContractRegistry:
    """Every side of every extracted contract, for --json dumps, the
    docs, and the cross-checks below."""

    # daemon -> {route -> [sites]} ; "_funnel" = mini-loop-served
    served: dict[str, dict[str, list[Site]]] = field(default_factory=dict)
    served_prefixes: dict[str, dict[str, list[Site]]] = field(
        default_factory=dict
    )
    # (kind "exact"|"prefix", path, daemon_hint|None, site)
    client_routes: list[tuple[str, str, str | None, Site]] = field(
        default_factory=list
    )
    metric_registered: dict[str, Site] = field(default_factory=dict)
    metric_var_names: dict[str, str] = field(default_factory=dict)
    metric_queried: dict[str, list[Site]] = field(default_factory=dict)
    metric_doc_refs: dict[str, list[Site]] = field(default_factory=dict)
    header_stamped: dict[str, list[Site]] = field(default_factory=dict)
    header_parsed: dict[str, list[Site]] = field(default_factory=dict)
    status_known: set[int] = field(default_factory=set)
    status_used: dict[int, list[Site]] = field(default_factory=dict)
    env_read: dict[str, list[Site]] = field(default_factory=dict)
    env_documented: dict[str, list[Site]] = field(default_factory=dict)
    flag_defined: dict[str, list[Site]] = field(default_factory=dict)
    flag_no_help: list[tuple[str, Site]] = field(default_factory=list)
    flag_documented: dict[str, list[Site]] = field(default_factory=dict)
    # raw urlopen() call sites on data-plane modules (no-deadline rule)
    deadline_bypass: list[Site] = field(default_factory=list)

    def to_dict(self) -> dict:
        def sites(lst):
            return [f"{s.path}:{s.line}" for s in lst]

        return {
            "served_routes": {
                d: sorted(rs) for d, rs in sorted(self.served.items())
            },
            "served_prefixes": {
                d: sorted(rs)
                for d, rs in sorted(self.served_prefixes.items())
            },
            "client_routes": sorted(
                {p for _k, p, _hint, _s in self.client_routes}
            ),
            "metrics_registered": sorted(self.metric_registered),
            "metrics_queried": sorted(self.metric_queried),
            "headers_stamped": sorted(self.header_stamped),
            "headers_parsed": sorted(self.header_parsed),
            "status_codes_known": sorted(self.status_known),
            "status_codes_used": sorted(self.status_used),
            "env_read": sorted(self.env_read),
            "env_documented": sorted(self.env_documented),
            "flags_defined": sorted(self.flag_defined),
            "flags_documented": sorted(self.flag_documented),
            "deadline_bypass": sites(self.deadline_bypass),
        }


# ---------------------------------------------------------------------------
# shared AST helpers


def _handler_class_names(index: PackageIndex) -> set[str]:
    out: set[str] = set()
    changed = True
    while changed:
        changed = False
        for cls in index.classes.values():
            if cls.name in out:
                continue
            if any(b in _HANDLER_BASES or b in out for b in cls.bases):
                out.add(cls.name)
                changed = True
    return out


def _daemon_for_path(rel_path: str) -> str | None:
    for suffix, daemon in _DAEMON_MODULES.items():
        if rel_path.endswith(suffix):
            return daemon
    if rel_path.endswith(os.path.join("util", "httpd.py")):
        return _FUNNEL_DAEMON
    return None


# ---------------------------------------------------------------------------
# (a) routes: served side


def _extract_served(index: PackageIndex, reg: ContractRegistry) -> None:
    """Route literals from every dispatch table: `path == "/x"`,
    `path in ("/a", "/b")`, `path.startswith("/pfx")` inside handler
    classes (plus util/httpd's funnel functions)."""
    handler_names = _handler_class_names(index)
    funnel_path_suffix = os.path.join("util", "httpd.py")

    def in_scope(rec) -> str | None:
        daemon = _daemon_for_path(rec.path)
        if rec.cls is not None and rec.cls in handler_names:
            return daemon or "other"
        if rec.path.endswith(funnel_path_suffix):
            return _FUNNEL_DAEMON
        return None

    for qual, fn in index.fn_nodes.items():
        rec = index.funcs.get(qual)
        if rec is None:
            continue
        daemon = in_scope(rec)
        if daemon is None:
            continue
        exact = reg.served.setdefault(daemon, {})
        prefixes = reg.served_prefixes.setdefault(daemon, {})
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                consts: list[tuple[str, int]] = []
                for comp in node.comparators:
                    s = _const_str(comp)
                    if s is not None:
                        consts.append((s, node.lineno))
                    elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                        for el in comp.elts:
                            s = _const_str(el)
                            if s is not None:
                                consts.append((s, node.lineno))
                s = _const_str(node.left)
                if s is not None:
                    consts.append((s, node.lineno))
                for s, line in consts:
                    if s.startswith("/"):
                        exact.setdefault(s, []).append(Site(rec.path, line))
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "startswith"
                and node.args
            ):
                s = _const_str(node.args[0])
                if s is not None and s.startswith("/"):
                    prefixes.setdefault(s, []).append(
                        Site(rec.path, node.lineno)
                    )


# ---------------------------------------------------------------------------
# (a) routes: client side


def _joined_template(node: ast.JoinedStr) -> str:
    """Render an f-string with \\x00 placeholders for formatted values."""
    out: list[str] = []
    for part in node.values:
        if isinstance(part, ast.Constant):
            out.append(str(part.value))
        else:
            out.append("\x00")
    return "".join(out)


def _url_to_path(template: str) -> tuple[str, str] | None:
    """(kind, path) out of a URL template, or None when it has no
    usable literal path. A placeholder directly after a literal path
    (`f"/scrub/trigger{qs}"`) degrades to a prefix check; a
    placeholder mid-path (`f"/{fid}"`) disqualifies it — precision
    over recall."""
    rest = template
    if "://" in rest:
        rest = rest.partition("://")[2]
        slash = rest.find("/")
        if slash < 0:
            return None
        host = rest[:slash]
        if "\x00" not in host and not host.startswith(
            ("127.0.0.1", "localhost", "[::1]")
        ):
            return None  # literal external host: not our contract
        rest = rest[slash:]
    elif rest.startswith("\x00"):
        # f"{master}/dir/assign?{q}" — host placeholder first
        slash = rest.find("/")
        if slash < 0:
            return None
        rest = rest[slash:]
    if not rest.startswith("/"):
        return None
    path = rest.partition("?")[0].partition("#")[0]
    # URLs embedded in rendered HTML carry markup right after the path
    path = re.split(r"""["'<>\s]""", path, maxsplit=1)[0]
    if "\x00" in path:
        prefix = path.partition("\x00")[0]
        if len(prefix) < 2:
            return None  # fully dynamic (`/{fid}`)
        return ("prefix", prefix)
    return ("exact", path) if path else None


_CLIENT_CALL_TAILS = {"http_call", "urlopen", "Request", "_pooled_request"}

# deadline plane (docs/CHAOS.md): modules on these data-plane paths
# must make internal hops through deadline-inheriting transports
# (op.http_call, pb/rpc.Stub). A raw urlopen there is flagged
# `no-deadline` unless suppressed with a reason.
_DEADLINE_SCOPE = tuple(
    os.path.join("seaweedfs_tpu", d) + os.sep
    for d in (
        "server", "client", "filer", "ec", "qos", "scrub", "s3api",
        "webdav",
    )
)
# words in a host placeholder's expression that mark it as a NETWORK
# location (so `f"{master}/dir/assign"` counts but `f"{dirpath}/x.json"`
# never does)
_HOSTISH = ("master", "filer", "url", "addr", "host", "server",
            "netloc", "location", "target", "peer", "leader")


def _extract_client_routes(
    index: PackageIndex, trees: dict[str, ast.Module],
    reg: ContractRegistry
) -> None:
    for rel_path, tree in trees.items():
        source = index.sources[rel_path]
        if any(
            rel_path.startswith(pfx) or rel_path == pfx
            for pfx in _EXTERNAL_CLIENT_MODULES
        ):
            continue
        sites: list[tuple[str, str, str | None, int]] = []
        in_client_arg: set[int] = set()  # id()s of client-call args
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                args = list(node.args) + [
                    kw.value for kw in node.keywords if kw.arg == "url"
                ]
                if tail in _CLIENT_CALL_TAILS:
                    for arg in args:
                        in_client_arg.add(id(arg))
                        # bare literal path args (_pooled_request)
                        s = _const_str(arg)
                        if s and s.startswith("/"):
                            sites.append(
                                ("exact", s.partition("?")[0], None,
                                 node.lineno)
                            )
                elif tail == "status_page":
                    # nav-link route lists rendered into every UI page
                    daemon = _daemon_for_path(rel_path)
                    for arg in node.args:
                        if not isinstance(arg, (ast.List, ast.Tuple)):
                            continue
                        els = [_const_str(e) for e in arg.elts]
                        if els and all(
                            s is not None and s.startswith("/")
                            for s in els
                        ):
                            for s in els:
                                sites.append(
                                    ("exact", s, daemon or "relative",
                                     node.lineno)
                                )
            if isinstance(node, ast.JoinedStr):
                template = _joined_template(node)
                if "://" in template:
                    hit = _url_to_path(template)
                elif id(node) in in_client_arg and template.startswith(
                    "\x00"
                ):
                    # host-placeholder-first form, only inside a known
                    # client call and only with a host-shaped expr
                    hit = (
                        _url_to_path(template)
                        if _host_hint(node) is not None
                        or _looks_hosty(node)
                        else None
                    )
                else:
                    hit = None
                if hit is not None:
                    kind, path = hit
                    sites.append(
                        (kind, path, _host_hint(node), node.lineno)
                    )
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                s = node.value
                if (
                    s.startswith("http://")
                    and "\n" not in s
                    and " " not in s
                ):
                    hit = _url_to_path(s)
                    if hit is not None:
                        sites.append(
                            (hit[0], hit[1], None, node.lineno)
                        )
        for kind, path, hint, line in sites:
            reg.client_routes.append(
                (kind, path, hint, Site(rel_path, line))
            )
        # UI links: every href/src/action in rendered HTML templates is
        # a client-side route consumer — RELATIVE to the serving module
        daemon = _daemon_for_path(rel_path)
        for i, text in enumerate(source.splitlines(), start=1):
            for m in _HREF_RE.finditer(text):
                reg.client_routes.append(
                    ("exact", m.group(1), daemon or "relative",
                     Site(rel_path, i))
                )


def _looks_hosty(node: ast.JoinedStr) -> bool:
    for part in node.values:
        if isinstance(part, ast.FormattedValue):
            blob = ast.dump(part.value).lower()
            return any(w in blob for w in _HOSTISH)
        if isinstance(part, ast.Constant) and "/" in str(part.value):
            return False
    return False


def _host_hint(node: ast.JoinedStr) -> str | None:
    """Which daemon an f-string URL dials, inferred from the HOST
    placeholder's source expression (`f"http://{env.master}/..."` →
    master). Only the placeholder(s) before the first literal '/' are
    the host."""
    host_exprs: list[str] = []
    for part in node.values:
        if isinstance(part, ast.Constant):
            s = str(part.value)
            if "/" in s and not s.endswith("://") and s != "http://":
                break
        elif isinstance(part, ast.FormattedValue):
            host_exprs.append(ast.dump(part.value).lower())
    blob = " ".join(host_exprs)
    if "master" in blob:
        return "master"
    if "filer" in blob:
        return "filer"
    return None


# ---------------------------------------------------------------------------
# (b) metrics


_REGISTRY_FACTORY_TAILS = {"counter", "gauge", "histogram"}
_RING_QUERY_TAILS = {"rate_sum", "increase_sum", "quantile", "series"}
_METRIC_SUFFIX_STRIP = ("_bucket", "_sum", "_count")


def _base_metric(name: str) -> str:
    for sfx in _METRIC_SUFFIX_STRIP:
        if name.endswith(sfx):
            return name[: -len(sfx)]
    return name


def _extract_metrics(
    trees: dict[str, ast.Module], reg: ContractRegistry
) -> None:
    for rel_path, tree in trees.items():
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                call = node.value
                tail = _dotted(call.func).rsplit(".", 1)[-1]
                if tail in _REGISTRY_FACTORY_TAILS and call.args:
                    name = _const_str(call.args[0])
                    if name and "_" in name:
                        reg.metric_registered[name] = Site(
                            rel_path, node.lineno
                        )
                        if len(node.targets) == 1 and isinstance(
                            node.targets[0], ast.Name
                        ):
                            reg.metric_var_names[name] = node.targets[0].id
            if isinstance(node, ast.Call):
                tail = _dotted(node.func).rsplit(".", 1)[-1]
                if tail in _RING_QUERY_TAILS and node.args:
                    name = _const_str(node.args[0])
                    if name:
                        reg.metric_queried.setdefault(
                            _base_metric(name), []
                        ).append(Site(rel_path, node.lineno))


def _extract_doc_metrics(
    docs: dict[str, str], reg: ContractRegistry
) -> None:
    for rel_path, text in docs.items():
        for i, line in enumerate(text.splitlines(), start=1):
            for m in _WEED_METRIC_RE.finditer(line):
                reg.metric_doc_refs.setdefault(
                    _base_metric(m.group(0)), []
                ).append(Site(rel_path, i))


# ---------------------------------------------------------------------------
# (c) headers + status codes


def _module_str_constants(tree: ast.Module) -> dict[str, str]:
    out: dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            s = _const_str(node.value)
            if s is not None:
                out[node.targets[0].id] = s
    return out


def _global_attr_constants(trees: dict[str, ast.Module]) -> dict[str, str]:
    """UPPER_CASE module-level string constants by bare name across the
    whole package, kept only when every definition agrees — so
    `_trace.TRACE_HEADER` resolves from any module."""
    values: dict[str, set[str]] = {}
    for tree in trees.values():
        for name, s in _module_str_constants(tree).items():
            if name.isupper():
                values.setdefault(name, set()).add(s)
    return {n: next(iter(v)) for n, v in values.items() if len(v) == 1}


def _local_alias_constants(
    tree: ast.Module, global_attrs: dict[str, str]
) -> dict[str, str]:
    """Name → string for EVERY simple assignment in the file, any
    scope: `trace_hdr_key = _trace.TRACE_HEADER` makes the later
    `.get(trace_hdr_key)` resolvable."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            continue
        s = _const_str(node.value)
        if s is None and isinstance(node.value, ast.Attribute):
            s = global_attrs.get(node.value.attr)
        if s is None and isinstance(node.value, ast.Name):
            s = global_attrs.get(node.value.id)
        if s is not None:
            out[node.targets[0].id] = s
    return out


_PARSE_TAILS = {"get", "pop", "getheader"}
_STAMP_TAILS = {"send_header", "add_header", "putheader", "setdefault"}
_REPLY_TAILS = {"fast_reply", "_reply", "_json", "_html", "_err"}


def _extract_headers_and_statuses(
    trees: dict[str, ast.Module], reg: ContractRegistry
) -> None:
    # resolve TRACE_HEADER-style constants — module-level, cross-module
    # attribute (`_trace.TRACE_HEADER`), and local aliases
    # (`trace_hdr_key = _trace.TRACE_HEADER`) — so `headers[HDR] = v`
    # and `.get(trace_hdr_key)` count as stamp/parse sites
    global_attrs = _global_attr_constants(trees)
    const_maps: dict[str, dict[str, str]] = {
        rel: _local_alias_constants(tree, global_attrs)
        for rel, tree in trees.items()
    }

    def header_name(node: ast.expr, rel_path: str) -> str | None:
        s = _const_str(node)
        if s is None and isinstance(node, ast.Name):
            s = const_maps.get(rel_path, {}).get(node.id) or global_attrs.get(
                node.id
            )
        if s is None and isinstance(node, ast.Attribute):
            s = global_attrs.get(node.attr)
        if s is not None and _INTERNAL_HEADER_RE.match(s):
            return s.lower()
        return None

    for rel_path, tree in trees.items():
        for node in ast.walk(tree):
            # headers.get("x-weed-trace") / headers.pop(...) / the
            # `"x-shard-hop" in headers` membership probe
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                tail = node.func.attr
                if tail in _PARSE_TAILS and node.args:
                    h = header_name(node.args[0], rel_path)
                    if h:
                        reg.header_parsed.setdefault(h, []).append(
                            Site(rel_path, node.lineno)
                        )
                elif tail in _STAMP_TAILS and node.args:
                    h = header_name(node.args[0], rel_path)
                    if h:
                        reg.header_stamped.setdefault(h, []).append(
                            Site(rel_path, node.lineno)
                        )
                if tail in _REPLY_TAILS:
                    for arg in node.args:
                        if (
                            isinstance(arg, ast.Constant)
                            and isinstance(arg.value, int)
                            and not isinstance(arg.value, bool)
                            and 100 <= arg.value <= 599
                        ):
                            reg.status_used.setdefault(
                                arg.value, []
                            ).append(Site(rel_path, node.lineno))
            elif isinstance(node, ast.Compare):
                # `"x-shard-hop" in headers` and `k == TRACE_HEADER`
                # are both parse-side probes
                for side in [node.left] + list(node.comparators):
                    h = header_name(side, rel_path)
                    if h:
                        reg.header_parsed.setdefault(h, []).append(
                            Site(rel_path, node.lineno)
                        )
            elif (
                isinstance(node, ast.Tuple)
                and len(node.elts) == 2
                and not isinstance(node.ctx, ast.Store)
            ):
                # gRPC invocation metadata: ((TRACE_HEADER, v),)
                h = header_name(node.elts[0], rel_path)
                if h:
                    reg.header_stamped.setdefault(h, []).append(
                        Site(rel_path, node.lineno)
                    )
            elif isinstance(node, ast.Subscript):
                h = header_name(node.slice, rel_path)
                if h:
                    bucket = (
                        reg.header_stamped
                        if isinstance(node.ctx, ast.Store)
                        else reg.header_parsed
                    )
                    bucket.setdefault(h, []).append(
                        Site(rel_path, node.lineno)
                    )
            elif isinstance(node, ast.Dict):
                # outbound header dict literals: {"x-shard-hop": "1"}
                for key in node.keys:
                    if key is None:
                        continue
                    h = header_name(key, rel_path)
                    if h:
                        reg.header_stamped.setdefault(h, []).append(
                            Site(rel_path, node.lineno)
                        )
        # _REASON: the one status→reason table fast_reply renders from
        if rel_path.endswith(os.path.join("util", "httpd.py")):
            for node in ast.walk(tree):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_REASON"
                    and isinstance(node.value, ast.Dict)
                ):
                    for key in node.value.keys:
                        if isinstance(key, ast.Constant) and isinstance(
                            key.value, int
                        ):
                            reg.status_known.add(key.value)


# ---------------------------------------------------------------------------
# (d) env vars + CLI flags


def _extract_env_reads(
    trees: dict[str, ast.Module], reg: ContractRegistry
) -> None:
    for rel_path, tree in trees.items():
        for node in ast.walk(tree):
            name = None
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if (
                    dotted.endswith("environ.get")
                    or dotted.rsplit(".", 1)[-1] == "getenv"
                ) and node.args:
                    name = _const_str(node.args[0])
            elif isinstance(node, ast.Subscript) and _dotted(
                node.value
            ).endswith("environ"):
                name = _const_str(node.slice)
            if name and _ENV_VAR_RE.fullmatch(name):
                reg.env_read.setdefault(name, []).append(
                    Site(rel_path, node.lineno)
                )


def _extract_flags(
    trees: dict[str, ast.Module], reg: ContractRegistry
) -> None:
    for rel_path, tree in trees.items():
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args
            ):
                continue
            flag = _const_str(node.args[0])
            if not flag or not flag.startswith("-"):
                continue
            name = flag.lstrip("-")
            site = Site(rel_path, node.lineno)
            reg.flag_defined.setdefault(name, []).append(site)
            has_help = any(
                kw.arg == "help"
                and not (
                    isinstance(kw.value, ast.Constant)
                    and not kw.value.value
                )
                for kw in node.keywords
            )
            if not has_help:
                reg.flag_no_help.append((name, site))


def _extract_docs(
    docs: dict[str, str], reg: ContractRegistry
) -> None:
    for rel_path, text in docs.items():
        for i, line in enumerate(text.splitlines(), start=1):
            for m in _ENV_VAR_RE.finditer(line):
                reg.env_documented.setdefault(m.group(0), []).append(
                    Site(rel_path, i)
                )
            for m in _DOC_FLAG_RE.finditer(line):
                reg.flag_documented.setdefault(m.group(1), []).append(
                    Site(rel_path, i)
                )


# ---------------------------------------------------------------------------
# registry assembly


_DOC_FILES = ("OPERATIONS.md", "README.md")


def _load_docs(repo_root: str) -> dict[str, str]:
    docs: dict[str, str] = {}
    candidates = [os.path.join(repo_root, n) for n in _DOC_FILES]
    docs_dir = os.path.join(repo_root, "docs")
    if os.path.isdir(docs_dir):
        candidates += [
            os.path.join(docs_dir, n)
            for n in sorted(os.listdir(docs_dir))
            if n.endswith(".md")
        ]
    for path in candidates:
        try:
            with open(path, "r", encoding="utf-8") as f:
                docs[os.path.relpath(path, repo_root)] = f.read()
        except OSError:
            continue
    return docs


def _load_extra_sources(repo_root: str) -> dict[str, str]:
    """bench.py and tests/conftest.py read WEED_* vars and reference
    metric names; they are part of the operational contract surface."""
    out: dict[str, str] = {}
    for rel in ("bench.py", os.path.join("tests", "conftest.py")):
        try:
            with open(
                os.path.join(repo_root, rel), "r", encoding="utf-8"
            ) as f:
                out[rel] = f.read()
        except OSError:
            continue
    return out


def _parse_all(sources: dict[str, str]) -> dict[str, ast.Module]:
    trees: dict[str, ast.Module] = {}
    for rel_path, source in sources.items():
        try:
            trees[rel_path] = ast.parse(source)
        except SyntaxError:
            continue
    return trees


def _extract_deadline_bypass(
    trees: dict[str, ast.Module], reg: ContractRegistry
) -> None:
    """urlopen() calls on data-plane modules: the transports that
    inherit the ambient X-Weed-Deadline (op.http_call, rpc.Stub) do so
    by construction, so the only statically-detectable bypass is a raw
    urlopen — which has no deadline seam at all."""
    for rel_path, tree in trees.items():
        if not rel_path.startswith(_DEADLINE_SCOPE):
            continue
        if any(
            rel_path.startswith(pfx) or rel_path == pfx
            for pfx in _EXTERNAL_CLIENT_MODULES
        ):
            continue  # external-service clients: not our deadline plane
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and _dotted(node.func).rsplit(".", 1)[-1] == "urlopen"
            ):
                reg.deadline_bypass.append(Site(rel_path, node.lineno))


def _check_deadline(reg: ContractRegistry) -> list[Finding]:
    return [
        Finding(
            "no-deadline",
            s.path,
            s.line,
            "raw urlopen() on a data-plane module cannot inherit the "
            "request's X-Weed-Deadline budget (docs/CHAOS.md) — a "
            "multi-hop request outlives its caller's intent here; use "
            "op.http_call / the gRPC Stub, or state why the bounded "
            "one-hop timeout suffices",
        )
        for s in reg.deadline_bypass
    ]


def build_registry(
    index: PackageIndex,
    docs: dict[str, str] | None = None,
    extra_sources: dict[str, str] | None = None,
) -> ContractRegistry:
    reg = ContractRegistry()
    # one parse per file, shared by every extractor (build_index's own
    # trees aren't kept, so this is the tier's single parse pass)
    trees = _parse_all(index.sources)
    extra_trees = _parse_all(extra_sources) if extra_sources else {}
    _extract_served(index, reg)
    _extract_client_routes(index, trees, reg)
    _extract_metrics(trees, reg)
    _extract_headers_and_statuses(trees, reg)
    _extract_env_reads(trees, reg)
    _extract_flags(trees, reg)
    _extract_deadline_bypass(trees, reg)
    if extra_trees:
        _extract_env_reads(extra_trees, reg)
        _extract_flags(extra_trees, reg)
    if docs:
        _extract_doc_metrics(docs, reg)
        _extract_docs(docs, reg)
    return reg


# ---------------------------------------------------------------------------
# cross-checks


def _route_served(
    reg: ContractRegistry, kind: str, path: str, daemon: str | None
) -> bool:
    def in_daemon(d: str) -> bool:
        routes = reg.served.get(d, {})
        if path in routes:
            return True
        if any(
            path.startswith(pfx) for pfx in reg.served_prefixes.get(d, {})
        ):
            return True
        if kind == "prefix":
            # `f"/scrub/trigger{qs}"`: the literal prefix names the
            # route; a served route equal to (or extending) it matches
            return any(r.startswith(path) for r in routes)
        return False

    if daemon in (None, "other", "relative"):
        return any(
            in_daemon(d)
            for d in set(reg.served) | set(reg.served_prefixes)
        )
    return in_daemon(daemon) or in_daemon(_FUNNEL_DAEMON)


def _check_routes(reg: ContractRegistry) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[str, str, int]] = set()
    for kind, path, hint, site in reg.client_routes:
        key = (path, site.path, site.line)
        if key in seen:
            continue
        seen.add(key)
        if _route_served(reg, kind, path, hint):
            continue
        scope = (
            f"the {hint} dispatch table"
            if hint and hint not in ("other", "relative")
            else "any dispatch table"
        )
        findings.append(
            Finding(
                "contract-route",
                site.path,
                site.line,
                f"client dials {path!r} but {scope} never serves it "
                f"(the consuming side of this hop will 404)",
            )
        )
    return findings


def _sources_blob_without(
    sources: dict[str, str], skip_suffix: str
) -> str:
    return "\n".join(
        src
        for rel, src in sources.items()
        if not rel.endswith(skip_suffix)
    )


def _check_metrics(
    reg: ContractRegistry,
    index: PackageIndex,
    extra_sources: dict[str, str] | None,
) -> list[Finding]:
    findings: list[Finding] = []
    registered = set(reg.metric_registered)
    # (1) queried/documented but never registered
    for name, sites in sorted(reg.metric_queried.items()):
        if name not in registered:
            for s in sites:
                findings.append(
                    Finding(
                        "contract-metric",
                        s.path,
                        s.line,
                        f"queries metric {name!r} that no Registry "
                        f"registers — the query returns empty forever "
                        f"(a renamed family silently disables this "
                        f"rule)",
                    )
                )
    for name, sites in sorted(reg.metric_doc_refs.items()):
        if name not in registered and _METRIC_NAME_RE.fullmatch(name):
            for s in sites:
                findings.append(
                    Finding(
                        "contract-metric",
                        s.path,
                        s.line,
                        f"documents metric {name!r} that no Registry "
                        f"registers (doc rot: operators will query a "
                        f"name that never exists)",
                    )
                )
    # (2) registered but written/read nowhere: constant-zero exposition.
    # Judged only for the registration module itself (any metrics.py, so
    # fixture trees exercise the rule) — ad-hoc registries elsewhere are
    # their own consumers.
    metrics_py = "metrics.py"
    blob = _sources_blob_without(index.sources, metrics_py)
    if extra_sources:
        blob += "\n" + "\n".join(extra_sources.values())
    for name, site in sorted(reg.metric_registered.items()):
        if not site.path.endswith(metrics_py):
            continue  # fixture/other registries judge themselves
        var = reg.metric_var_names.get(name)
        referenced = (
            name in reg.metric_queried
            or name in reg.metric_doc_refs
            or name in blob
            or bool(var and re.search(rf"\b{re.escape(var)}\b", blob))
        )
        if not referenced:
            findings.append(
                Finding(
                    "contract-metric-orphan",
                    site.path,
                    site.line,
                    f"metric {name!r} is registered but no code writes "
                    f"or reads it and no doc mentions it — it renders "
                    f"constant-zero rows that look like real "
                    f"instrumentation",
                )
            )
    return findings


def _check_headers(reg: ContractRegistry) -> list[Finding]:
    findings: list[Finding] = []
    for h, sites in sorted(reg.header_stamped.items()):
        if h not in reg.header_parsed:
            s = sites[0]
            findings.append(
                Finding(
                    "contract-header",
                    s.path,
                    s.line,
                    f"internal header {h!r} is stamped here but no "
                    f"consuming side ever parses it (dead bytes on "
                    f"every hop, or the parser was renamed away)",
                )
            )
    for h, sites in sorted(reg.header_parsed.items()):
        if h not in reg.header_stamped:
            s = sites[0]
            findings.append(
                Finding(
                    "contract-header",
                    s.path,
                    s.line,
                    f"internal header {h!r} is parsed here but no "
                    f"in-repo side ever stamps it (the branch below "
                    f"is dead, or the stamping side drifted)",
                )
            )
    return findings


def _check_statuses(reg: ContractRegistry) -> list[Finding]:
    if not reg.status_known:
        return []  # fixture trees without util/httpd.py
    findings: list[Finding] = []
    for code, sites in sorted(reg.status_used.items()):
        if code in reg.status_known:
            continue
        for s in sites:
            findings.append(
                Finding(
                    "contract-status-reason",
                    s.path,
                    s.line,
                    f"status {code} has no entry in util/httpd._REASON "
                    f'— fast_reply will emit "{code} OK" to the peer',
                )
            )
    return findings


def _check_env(reg: ContractRegistry) -> list[Finding]:
    findings: list[Finding] = []
    for name, sites in sorted(reg.env_read.items()):
        if name not in reg.env_documented:
            s = sites[0]
            findings.append(
                Finding(
                    "contract-env",
                    s.path,
                    s.line,
                    f"env var {name} is read here but documented "
                    f"nowhere (docs/OPERATIONS/README) — operators "
                    f"cannot discover the knob",
                )
            )
    for name, sites in sorted(reg.env_documented.items()):
        if name not in reg.env_read:
            s = sites[0]
            findings.append(
                Finding(
                    "contract-env",
                    s.path,
                    s.line,
                    f"env var {name} is documented here but no code "
                    f"reads it (doc rot: the knob does nothing)",
                )
            )
    return findings


def _check_flags(reg: ContractRegistry) -> list[Finding]:
    findings: list[Finding] = []
    defined = set(reg.flag_defined)
    for name, sites in sorted(reg.flag_documented.items()):
        if name in defined or name in _EXTERNAL_DOC_FLAGS:
            continue
        # docs write `-traceSlowMs`; argparse may define `-traceSlowMs`
        # or `--trace-slow-ms` — try the dashed normalization too
        dashed = re.sub(r"(?<!^)([A-Z])", r"-\1", name).lower()
        if dashed in defined:
            continue
        for s in sites:
            findings.append(
                Finding(
                    "contract-flag",
                    s.path,
                    s.line,
                    f"flag -{name} is documented here but no "
                    f"add_argument defines it (doc rot: the flag "
                    f"errors out)",
                )
            )
    for name, site in reg.flag_no_help:
        findings.append(
            Finding(
                "contract-flag",
                site.path,
                site.line,
                f"flag -{name} has no help= text — argparse --help is "
                f"the CLI's only self-documentation",
            )
        )
    return findings


# ---------------------------------------------------------------------------
# entry point


def check(
    root: str | None = None,
    index: PackageIndex | None = None,
    docs: dict[str, str] | None = None,
) -> tuple[list[Finding], PackageIndex, ContractRegistry]:
    """Returns (findings, index, registry). `docs` overrides the repo
    doc set (fixture trees pass their own or none)."""
    index = index or build_index(root)
    if root is None:
        if docs is None:
            docs = _load_docs(REPO_ROOT)
        extra = _load_extra_sources(REPO_ROOT)
    else:
        docs = docs or {}
        extra = None
    reg = build_registry(index, docs=docs, extra_sources=extra)
    findings: list[Finding] = []
    findings += _check_routes(reg)
    findings += _check_metrics(reg, index, extra)
    findings += _check_headers(reg)
    findings += _check_statuses(reg)
    findings += _check_env(reg)
    findings += _check_flags(reg)
    findings += _check_deadline(reg)
    # findings anchored outside the package (docs, bench.py,
    # tests/conftest.py) need those texts in the suppression scan, or
    # the documented `# weedlint: ignore[...]` escape hatch silently
    # does nothing for them
    for rel, text in {**(docs or {}), **(extra or {})}.items():
        index.sources.setdefault(rel, text)
    return findings, index, reg
