"""`.ecc` sidecar: persisted encode-time shard CRCs for cheap scrub.

The fused encode/rebuild pipelines (ec/ec_stream.py + ec/crc_kernel.py)
already hand back a whole-file CRC-32C per shard for free — the device
computes them while the tile is VMEM-resident. Until now those CRCs
were only logged. Persisting them as a per-volume ``{base}.ecc`` JSON
sidecar turns the scrubber's 14-shard parity re-verify (read all
shards, recompute 4 GF parity rows per tile, compare) into a plain
read+CRC pass per shard — no GF math, no cross-shard staging — freeing
scrub CPU and memory bandwidth for serving.

Crash ordering: the sidecar ATTESTS shard bytes, so it must never
reach its final name before the bytes it attests are durable (a crash
could then materialize a sidecar vouching for shards that lost their
tails — scrub would "verify" garbage against a confident CRC and
report clean). Emitters call write_sidecar only after the shard files
are fsynced (the durable=True arm of write_ec_files/rebuild), and the
sidecar itself goes through util/durable.publish (fsync bytes → rename
→ fsync dir). analysis/crash.py's `ecc_publish` workload sweeps this
ordering and proves the unsynced variant is DETECTED.

Staleness: a rebuild rewrites shard files. Rebuilt shards are
byte-identical to the originals (RS determinism), so existing entries
stay CORRECT — but the sidecar's mtime now predates the shards', which
is indistinguishable from "sidecar predates an overwrite that changed
bytes". The rebuild verbs therefore merge the rebuilt shards' fresh
CRCs and republish (making the sidecar newest again); any sidecar
older than a shard it attests, or disagreeing with a shard's on-disk
size, is reported stale and the scrubber falls back to the full parity
re-verify LOUDLY (wlog + weed_scrub_ecc_fallback_total) — never a
silent skip.

``WEED_EC_ECC=0`` disables both emit and verify.
"""

from __future__ import annotations

import json
import os

from seaweedfs_tpu.util import durable

ECC_EXT = ".ecc"
_VERSION = 1


def ecc_enabled() -> bool:
    """`WEED_EC_ECC` env knob: any value but "0" keeps the sidecar
    emit + scrub verify on."""
    return os.environ.get("WEED_EC_ECC", "1") != "0"


def sidecar_path(base: str) -> str:
    return base + ECC_EXT


def load_sidecar(base: str) -> dict | None:
    """Parsed sidecar doc, or None when absent/unreadable/garbled (a
    torn sidecar must degrade to the parity path, not crash a sweep)."""
    try:
        with open(sidecar_path(base), "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("shards"), dict):
        return None
    return doc


def write_sidecar(
    base: str,
    crcs,
    *,
    total_shards: int = 14,
    durable_publish: bool = True,
) -> str | None:
    """Publish ``{base}.ecc`` attesting per-shard whole-file CRC + size.

    `crcs` is either a full [total_shards] list (the generate verbs) or
    a partial {sid: crc} dict (the rebuild verbs), merged over any
    existing sidecar. A partial update with no prior sidecar cannot
    attest the untouched shards and is skipped (returns None) — the
    scrubber then takes the parity path for this volume until the next
    full generate.

    PRECONDITION: the shard files' bytes are already durable (the
    callers' durable=True fsync) — analysis/crash.py's `ecc-publish`
    workload sweeps this ordering and its planted arm (shard fsyncs
    skipped) proves violations are DETECTED. durable_publish=False
    exists ONLY for tests proving a torn under-final-name sidecar
    degrades to the parity path rather than a false-clean."""
    if isinstance(crcs, dict):
        entries = {int(k): int(v) for k, v in crcs.items()}
        existing = load_sidecar(base)
        if existing is not None:
            for k, v in existing["shards"].items():
                entries.setdefault(int(k), int(v["crc"]))
        if len(entries) < total_shards:
            return None
    else:
        if len(crcs) != total_shards:
            raise ValueError(
                f"expected {total_shards} shard CRCs, got {len(crcs)}"
            )
        entries = {sid: int(c) for sid, c in enumerate(crcs)}

    from seaweedfs_tpu.ec import ec_files

    shards = {}
    for sid in range(total_shards):
        path = base + ec_files.to_ext(sid)
        try:
            size = os.path.getsize(path)
        except OSError:
            return None  # shard vanished under us: attest nothing
        shards[str(sid)] = {"crc": entries[sid] & 0xFFFFFFFF, "size": size}

    dst = sidecar_path(base)
    tmp = dst + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"version": _VERSION, "shards": shards}, f)
    if durable_publish:
        durable.publish(tmp, dst)
    else:
        # weedlint: ignore[crash-rename-no-dirsync] — deliberate planted-bug arm (tests + analysis/crash.py ecc-publish): proves a torn/unordered sidecar publish degrades to the parity path
        os.replace(tmp, dst)
    return dst


def sidecar_status(
    base: str, shard_paths: dict[int, str], total_shards: int = 14
) -> tuple[str, dict | None]:
    """("ok", doc) when the sidecar attests every shard in
    `shard_paths` and is no older than any of them; ("missing", None) /
    ("stale", doc-or-None) otherwise. Size disagreement and
    shard-newer-than-sidecar both count as stale (the attested CRCs may
    describe bytes that are no longer on disk)."""
    doc = load_sidecar(base)
    if doc is None:
        return "missing", None
    try:
        ecc_mtime = os.stat(sidecar_path(base)).st_mtime_ns
    except OSError:
        return "missing", None
    for sid, path in shard_paths.items():
        ent = doc["shards"].get(str(sid))
        if ent is None:
            return "stale", doc
        try:
            st = os.stat(path)
        except OSError:
            return "stale", doc
        if st.st_size != ent.get("size"):
            return "stale", doc
        if st.st_mtime_ns > ecc_mtime:
            return "stale", doc
    if len(doc["shards"]) < total_shards:
        return "stale", doc
    return "ok", doc
