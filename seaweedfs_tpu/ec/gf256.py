"""GF(2^8) arithmetic and matrices, field-compatible with the reference.

The reference's EC math lives in github.com/klauspost/reedsolomon (a Go
port of Backblaze's JavaReedSolomon), imported at
weed/storage/erasure_coding/ec_encoder.go:13. That library fixes:

  * the field: GF(2^8) with reducing polynomial x^8+x^4+x^3+x^2+1
    (0x11D), generator element 2;
  * the code matrix: a systematic matrix derived from the Vandermonde
    matrix V[r][c] = r^c (element exponentiation in the field) as
    A = V · (V[:k])^-1, so A's top k rows are the identity.

Shards produced here are therefore byte-identical to shards produced
by the reference, which is what makes mixed clusters and on-disk
compatibility possible. Everything in this module is numpy/host-side;
the bulk byte streams go through the codec backends.
"""

from __future__ import annotations

import numpy as np

_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
FIELD_SIZE = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(510, dtype=np.uint8)  # exp[i] = 2^i, doubled to skip mod
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]
    log[0] = -1  # log(0) undefined
    return exp, log


EXP_TABLE, LOG_TABLE = _build_tables()

# Full 256x256 multiplication table: MUL[a, b] = a·b in the field.
# 64 KB; the CPU codec indexes rows of this as per-coefficient LUTs.
_a = np.arange(256)
MUL_TABLE = np.zeros((256, 256), dtype=np.uint8)
_nz = _a[1:]
_log_sum = LOG_TABLE[_nz][:, None] + LOG_TABLE[_nz][None, :]
MUL_TABLE[1:, 1:] = EXP_TABLE[_log_sum]
del _a, _nz, _log_sum


def gf_mul(a: int, b: int) -> int:
    return int(MUL_TABLE[a, b])


def gf_div(a: int, b: int) -> int:
    if b == 0:
        raise ZeroDivisionError("division by zero in GF(2^8)")
    if a == 0:
        return 0
    return int(EXP_TABLE[(LOG_TABLE[a] - LOG_TABLE[b]) % 255])


def gf_inv(a: int) -> int:
    return gf_div(1, a)


def gf_exp(a: int, n: int) -> int:
    """a^n in the field — matches the reference library's galExp:
    n==0 → 1 (even for a==0), a==0 → 0."""
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP_TABLE[(int(LOG_TABLE[a]) * n) % 255])


# --- matrices over GF(2^8), stored as uint8 numpy arrays -------------------

def identity(n: int) -> np.ndarray:
    return np.eye(n, dtype=np.uint8)


def mat_mul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Matrix product in GF(2^8): XOR-accumulate of MUL_TABLE gathers."""
    assert a.shape[1] == b.shape[0]
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for k in range(a.shape[1]):
        out ^= MUL_TABLE[a[:, k][:, None], b[k, :][None, :]]
    return out


def mat_inv(m: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion in GF(2^8). Raises on singular input."""
    n = m.shape[0]
    assert m.shape == (n, n)
    work = np.concatenate([m.astype(np.uint8), identity(n)], axis=1)
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if work[row, col] != 0:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("matrix is singular in GF(2^8)")
        if pivot != col:
            work[[col, pivot]] = work[[pivot, col]]
        inv_p = gf_inv(int(work[col, col]))
        work[col] = MUL_TABLE[inv_p, work[col]]
        for row in range(n):
            if row != col and work[row, col] != 0:
                work[row] ^= MUL_TABLE[int(work[row, col]), work[col]]
    return work[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r][c] = r^c, the reference library's starting matrix."""
    return np.array(
        [[gf_exp(r, c) for c in range(cols)] for r in range(rows)], dtype=np.uint8
    )


def build_code_matrix(data_shards: int, total_shards: int) -> np.ndarray:
    """The systematic RS code matrix used by the reference library:
    A = V · (V[:k])^-1. Top k rows are the identity; rows k..n are the
    parity coefficient rows."""
    vm = vandermonde(total_shards, data_shards)
    top_inv = mat_inv(vm[:data_shards])
    a = mat_mul(vm, top_inv)
    assert np.array_equal(a[:data_shards], identity(data_shards))
    return a


def sub_matrix_for_survivors(
    code_matrix: np.ndarray, survivor_rows: list[int]
) -> np.ndarray:
    """Rows of the code matrix for a set of surviving shards."""
    return code_matrix[np.array(survivor_rows, dtype=np.intp)].copy()


def decode_rows(
    code_matrix: np.ndarray,
    survivors: "tuple[int, ...] | list[int]",
    targets: "tuple[int, ...] | list[int]",
) -> np.ndarray:
    """GF coefficient rows mapping k survivor shards → target shards.

    survivors: the k shard ids present (ascending), targets: shard ids
    to produce. Data targets are rows of the inverted survivor
    submatrix; parity targets compose the parity row with that inverse.
    Single home for the survivor-decode algebra used by the host codec,
    the TPU kernels, and the mesh codec."""
    k = code_matrix.shape[1]
    sub = sub_matrix_for_survivors(code_matrix, list(survivors))
    inv = mat_inv(sub)  # [k, k]: survivors → data shards
    rows = []
    for t in targets:
        if t < k:
            rows.append(inv[t])
        else:
            rows.append(mat_mul(code_matrix[t : t + 1], inv)[0])
    return np.stack(rows)
