"""Erasure-coding tier: RS(10,4) over GF(2^8), TPU-first.

The codec is the framework's north-star component (BASELINE.json):
encode/reconstruct run as JAX bitsliced XOR-matmul programs on TPU,
with a numpy CPU backend kept as the bit-exact reference. Striping
layout and shard file formats are wire-compatible with the reference
implementation (weed/storage/erasure_coding/)."""

from seaweedfs_tpu.ec.codec import (  # noqa: F401
    DATA_SHARDS,
    PARITY_SHARDS,
    TOTAL_SHARDS,
    new_encoder,
)
