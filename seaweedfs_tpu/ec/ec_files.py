"""Streaming shard-file generation: .dat → .ec00….ec13, and rebuild.

Behavioral match of reference weed/storage/erasure_coding/ec_encoder.go:
  * two-tier striping: rows of 1 GB blocks while more than one full
    large row of data remains, then 1 MB rows, zero-padded at the tail
    (encodeDatFile:188-225 — note both loops use a strict `>` test);
  * each .ec file is that shard's blocks concatenated: all large-row
    blocks then all small-row blocks (encodeDataOneBatch writes all 14
    buffers, so .ec00-.ec09 hold plain data copies);
  * rebuild streams all surviving shards in lockstep chunks and
    reconstructs the missing ones positionwise (rebuildEcFiles:227-281);
  * .ecx = the .idx entries deduped last-wins and sorted ascending by
    key, same 16-byte entry format (WriteSortedFileFromIdx:26-50 via
    CompactMap.AscendingVisit — deleted keys stay, tombstoned);
  * .ecj = raw 8-byte big-endian needle ids (ec_volume_delete.go:38-47).

The byte math goes through a codec.ReedSolomon, so `backend="tpu"`
streams batches through the JAX bitsliced kernels; output bytes are
identical for every backend and batch size.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np

from seaweedfs_tpu.ec import locate
from seaweedfs_tpu.ec.codec import ReedSolomon, new_encoder
from seaweedfs_tpu.storage import idx as idx_codec
from seaweedfs_tpu.storage import types as t

DATA_SHARDS = locate.DATA_SHARDS
PARITY_SHARDS = locate.PARITY_SHARDS
TOTAL_SHARDS = locate.TOTAL_SHARDS
LARGE_BLOCK_SIZE = locate.LARGE_BLOCK_SIZE
SMALL_BLOCK_SIZE = locate.SMALL_BLOCK_SIZE

DEFAULT_BUFFER_SIZE = 4 * 1024 * 1024  # per-shard IO batch (ref used 256 KB)


def to_ext(ec_index: int) -> str:
    """Shard-file extension: ".ec00" … ".ec13" (ec_encoder.go ToExt)."""
    return f".ec{ec_index:02d}"


def shard_row_counts(
    dat_size: int,
    large: int = LARGE_BLOCK_SIZE,
    small: int = SMALL_BLOCK_SIZE,
) -> tuple[int, int]:
    """(large rows, small rows) a .dat of `dat_size` encodes to.

    Mirrors encodeDatFile's strict-greater loops: a file of exactly
    n·(10·large) bytes produces n-1 large rows (the last full row goes
    through the small-block tier)."""
    n_large = 0
    remaining = dat_size
    while remaining > large * DATA_SHARDS:
        n_large += 1
        remaining -= large * DATA_SHARDS
    n_small = 0
    while remaining > 0:
        n_small += 1
        remaining -= small * DATA_SHARDS
    return n_large, n_small


def shard_file_size(
    dat_size: int,
    large: int = LARGE_BLOCK_SIZE,
    small: int = SMALL_BLOCK_SIZE,
) -> int:
    n_large, n_small = shard_row_counts(dat_size, large, small)
    return n_large * large + n_small * small


def shard_presence(base_file_name: str) -> tuple[list[bool], list[int]]:
    """(present flags, missing ids) over the 14 shard files."""
    present = [
        os.path.exists(base_file_name + to_ext(i)) for i in range(TOTAL_SHARDS)
    ]
    return present, [i for i, p in enumerate(present) if not p]


def _use_stream_driver(rs: ReedSolomon) -> bool:
    """Route to the pipelined ec_stream driver when the codec would run
    on an attached TPU anyway — output bytes are identical; the stream
    driver overlaps disk IO, H2D, kernel, and D2H instead of
    round-tripping synchronously per batch. WEED_EC_PIPELINE=0 (the
    pipeline kill switch) forces the serial classic loop wholesale."""
    if rs._backend_name != "tpu":
        return False
    from seaweedfs_tpu.ec import ec_stream
    from seaweedfs_tpu.ec.codec_tpu import _on_tpu

    return ec_stream.pipeline_enabled() and _on_tpu()


def _stream_host_codec(rs: ReedSolomon) -> bool:
    """Route host codec backends that release the GIL (the native SIMD
    shim's ctypes call) through the pipelined driver too: the reader
    and pwritev writer pools overlap disk IO with the C encode, and the
    flush-free raw-fd writes drop the serial close tail the classic
    loop pays. The numpy "cpu" backend stays on the classic loop — it
    is the bit-exact reference the others are judged against. The
    WEED_EC_PIPELINE=0 kill switch overrides here too."""
    if rs._backend_name != "native":
        return False
    from seaweedfs_tpu.ec import ec_stream

    return ec_stream.pipeline_enabled()


def iter_ec_tiles(dat_size: int, tile: int, large: int, small: int):
    """Yield (row_offset, block_size, batch_off, step) sub-tiles
    covering the two-tier row layout (strict-`>` row counting,
    ec_encoder.go:188-225). The reader takes [10, step] at
    row_offset + i*block_size + batch_off for shard i. Single source
    of the tiling math for the classic and pipelined drivers."""
    n_large, n_small = shard_row_counts(dat_size, large, small)
    processed = 0
    for block_size, n_rows in ((large, n_large), (small, n_small)):
        step = min(tile, block_size)
        for _ in range(n_rows):
            for batch_off in range(0, block_size, step):
                yield processed, block_size, batch_off, min(
                    step, block_size - batch_off
                )
            processed += block_size * DATA_SHARDS


def read_dat_tile(
    dat, dat_size: int, row_off: int, block: int, batch_off: int, step: int
) -> np.ndarray:
    """[10, step] uint8 tile of the .dat, zero-padded past EOF
    (encodeDataOneBatch:158-170). Rows are read with readinto straight
    into the tile (file.read would allocate a bytes object and pay a
    second memcpy per row — at stream rates that extra pass is a
    measurable fraction of the whole read phase)."""
    buf = np.zeros((DATA_SHARDS, step), dtype=np.uint8)
    for i in range(DATA_SHARDS):
        off = row_off + i * block + batch_off
        if off >= dat_size:
            continue
        dat.seek(off)
        n = min(step, dat_size - off)
        view = memoryview(buf[i])
        got = 0
        while got < n:
            r = dat.readinto(view[got:n])
            if not r:
                break
            got += r
    return buf


def write_ec_files(
    base_file_name: str,
    rs: ReedSolomon | None = None,
    buffer_size: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    stats: dict | None = None,
    durable: bool = False,
    want_crcs: bool = False,
) -> None:
    """Generate .ec00-.ec13 next to `base_file_name`.dat
    (ec_encoder.go:53 WriteEcFiles). durable=True fsyncs the shard
    files before returning (see stream_write_ec_files — the ordering
    the generate verb's .ecx publish relies on after a crash).

    buffer_size=None lets each driver pick its default (4 MiB classic
    IO batches; 4 MiB pipelined tiles on TPU/native hosts). A `stats` dict
    collects per-phase busy seconds so e2e throughput numbers stay
    attributable (bench.py stream): the classic loop reports
    read_s/encode_s/write_s; the pipelined stream driver reports
    read_s/stage_s/device_s/writeback_s/compute_s/write_s plus its
    pipeline depth (overlapped stages — each pool's busy seconds).

    want_crcs=True lands `shard_crcs` (14 whole-file CRC-32C values)
    in `stats` on every driver: fused into the device pass on the
    pipelined paths, a running table CRC on the classic loop — the
    value contract is identical, so the WEED_EC_PIPELINE=0 kill switch
    changes nothing callers can observe but speed."""
    rs = rs or new_encoder()
    if rs.data_shards != DATA_SHARDS or rs.parity_shards != PARITY_SHARDS:
        raise ValueError("shard-file layout is fixed at RS(10,4)")

    if _use_stream_driver(rs) or _stream_host_codec(rs):
        from seaweedfs_tpu.ec import ec_stream

        parity_fn = fetch_fn = None
        if not _use_stream_driver(rs):
            parity_fn, fetch_fn = ec_stream.local_encode_fns(
                rs, want_crcs=want_crcs
            )
        ec_stream.stream_write_ec_files(
            base_file_name,
            tile_bytes=buffer_size,
            large_block_size=large_block_size,
            small_block_size=small_block_size,
            parity_fn=parity_fn,
            fetch_fn=fetch_fn,
            stats=stats,
            durable=durable,
            want_crcs=want_crcs,
        )
        return

    buffer_size = buffer_size or DEFAULT_BUFFER_SIZE
    for block in (large_block_size, small_block_size):
        if block % buffer_size != 0 and buffer_size % block != 0:
            raise ValueError("buffer size must tile the block sizes")

    import time as _time

    wall0 = _time.perf_counter()
    read_s = encode_s = write_s = 0.0
    crcs = [0] * TOTAL_SHARDS  # running per-shard-file CRC (want_crcs)
    dat_size = os.path.getsize(base_file_name + ".dat")
    outputs = [open(base_file_name + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
    try:
        from seaweedfs_tpu.util.crc import crc32c

        with open(base_file_name + ".dat", "rb") as dat:
            for row_off, block, batch_off, step in iter_ec_tiles(
                dat_size, buffer_size, large_block_size, small_block_size
            ):
                t0 = _time.perf_counter()
                tile = read_dat_tile(dat, dat_size, row_off, block, batch_off, step)
                t1 = _time.perf_counter()
                shards: list[np.ndarray | None] = [
                    tile[i] for i in range(DATA_SHARDS)
                ] + [None] * PARITY_SHARDS
                rs.encode(shards)
                t2 = _time.perf_counter()
                for i in range(TOTAL_SHARDS):
                    # numpy arrays expose the buffer protocol: write the
                    # row directly instead of paying a tobytes() copy
                    outputs[i].write(shards[i])  # type: ignore[arg-type]
                    if want_crcs:
                        # the serial loop writes in stream order, so
                        # the table CRC simply continues — same value
                        # contract as the pipelined drivers' fused fold
                        crcs[i] = crc32c(shards[i].tobytes(), crcs[i])
                t3 = _time.perf_counter()
                read_s += t1 - t0
                encode_s += t2 - t1
                write_s += t3 - t2
        if durable:
            # success path only (inside the try): a failed durability
            # fsync must fail the encode, never be swallowed by close
            for f in outputs:
                f.flush()
                os.fsync(f.fileno())
    finally:
        tc0 = _time.perf_counter()
        try:
            for f in outputs:
                f.close()
        finally:
            for f in outputs:
                if not f.closed:  # a failed close must not leak the rest
                    try:
                        f.close()
                    except OSError:
                        pass
        flush_s = _time.perf_counter() - tc0
        if stats is not None:
            wall = _time.perf_counter() - wall0
            stats.update(
                read_s=round(read_s, 4),
                encode_s=round(encode_s, 4),
                write_s=round(write_s, 4),
                # closing 14 buffered writers is where the KERNEL's
                # dirty-page writeback throttling lands on disk-backed
                # paths — round 4's "40% unattributed wall" was exactly
                # this, not Python glue (on tmpfs it is ~0)
                flush_s=round(flush_s, 4),
                wall_s=round(wall, 4),
                # driver overhead outside every measured phase (tile
                # iteration, buffer setup): the e2e number is only
                # honest if this stays small (measured ~7% on tmpfs)
                loop_s=round(
                    wall - read_s - encode_s - write_s - flush_s, 4
                ),
            )
            if want_crcs:
                stats["shard_crcs"] = crcs


def write_ec_files_batch(
    base_file_names: list[str],
    codec=None,
    tile_bytes: int | None = None,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
    stats: dict | None = None,
    durable: bool = False,
    want_crcs: bool = False,
) -> None:
    """Encode N sealed volumes' .dat files through ONE mesh program per
    tile round — the §2.6.2 volume-parallelism story end-to-end: each
    round stacks one [10, W] tile per volume into a [B, 10, W/4]-lane
    batch laid out P('vol', None, 'stripe') over the process Mesh
    (parallel/mesh_codec.py; SWAR per device on TPU meshes). Output
    bytes are identical to write_ec_files per volume — the reference's
    goroutine-per-volume encode fan-out (command_ec_encode.go:153),
    lifted to SPMD.

    The production arm is the PIPELINED driver
    (ec_stream.stream_write_ec_files_batch): staging-ring overlap of
    reads, H2D, the mesh program, D2H and shard writes, with fused
    per-shard CRCs when want_crcs. WEED_EC_PIPELINE=0 restores this
    serial per-round loop wholesale — byte-identical, no overlap, and
    the same durable contract (durable=True fsyncs every shard file
    before returning on BOTH arms, so the BatchGenerate verb's .ecx
    publish ordering holds regardless of the kill switch).

    Shapes stay static across rounds (finished volumes contribute zero
    tiles that are discarded) so each driver compiles its program
    once."""
    from seaweedfs_tpu.ec import ec_stream

    if not base_file_names:
        return
    if ec_stream.pipeline_enabled():
        ec_stream.stream_write_ec_files_batch(
            base_file_names,
            codec=codec,
            tile_bytes=tile_bytes,
            large_block_size=large_block_size,
            small_block_size=small_block_size,
            stats=stats,
            durable=durable,
            want_crcs=want_crcs,
        )
        return
    if codec is None:
        # same self-provisioning recipe as the pipelined arm: the vol
        # axis sized to gcd(batch, devices) so any batch shards cleanly
        codec = ec_stream._default_mesh_codec(len(base_file_names))
    tile_bytes = tile_bytes or DEFAULT_BUFFER_SIZE
    for block in (large_block_size, small_block_size):
        if block % tile_bytes != 0 and tile_bytes % block != 0:
            raise ValueError("tile size must tile the block sizes")

    b = len(base_file_names)
    stripe = codec.mesh.devices.shape[1]
    if b % codec.mesh.devices.shape[0]:
        raise ValueError(
            f"batch of {b} volumes does not shard over the mesh's "
            f"{codec.mesh.devices.shape[0]}-way 'vol' axis"
        )
    tiles: list[list] = []
    dats = []
    sizes = []
    outs = []
    try:
        for base in base_file_names:
            size = os.path.getsize(base + ".dat")
            sizes.append(size)
            dats.append(open(base + ".dat", "rb"))
            outs.append(
                [open(base + to_ext(i), "wb") for i in range(TOTAL_SHARDS)]
            )
            tiles.append(
                list(
                    iter_ec_tiles(
                        size, tile_bytes, large_block_size, small_block_size
                    )
                )
            )
        if not any(tiles):
            # all .dat files empty: 14 empty shards each, done —
            # durably, when asked: the verb's .ecx publish must never
            # outlive shard files a crash can drop
            if durable:
                for fs in outs:
                    for f in fs:
                        os.fsync(f.fileno())
            if stats is not None and want_crcs:
                stats["shard_crcs"] = [[0] * TOTAL_SHARDS for _ in range(b)]
            return
        # one static tile width for every round: the max step, rounded
        # so the u32 lane count splits over the stripe axis in whole
        # SWAR-friendly chunks (1024 lanes per device minimum)
        max_step = max(step for ts in tiles for _, _, _, step in ts)
        gran = 4 * 1024 * stripe
        width = -(-max_step // gran) * gran
        rounds = max(len(ts) for ts in tiles)
        batch = np.zeros((b, DATA_SHARDS, width), dtype=np.uint8)
        crcs = [[0] * TOTAL_SHARDS for _ in range(b)]
        from seaweedfs_tpu.util.crc import crc32c

        for r in range(rounds):
            batch[:] = 0
            steps = [0] * b
            for v in range(b):
                if r >= len(tiles[v]):
                    continue  # volume done: zero tile, output discarded
                row_off, block, batch_off, step = tiles[v][r]
                batch[v, :, :step] = read_dat_tile(
                    dats[v], sizes[v], row_off, block, batch_off, step
                )
                steps[v] = step
            parity = np.asarray(
                codec.encode_batch_u32(
                    codec.shard_volumes(batch.view(np.uint32))
                )
            ).view(np.uint8)
            for v in range(b):
                step = steps[v]
                if not step:
                    continue
                for i in range(DATA_SHARDS):
                    chunk = batch[v, i, :step].tobytes()
                    outs[v][i].write(chunk)
                    if want_crcs:
                        crcs[v][i] = crc32c(chunk, crcs[v][i])
                for i in range(PARITY_SHARDS):
                    chunk = parity[v, i, :step].tobytes()
                    outs[v][DATA_SHARDS + i].write(chunk)
                    if want_crcs:
                        crcs[v][DATA_SHARDS + i] = crc32c(
                            chunk, crcs[v][DATA_SHARDS + i]
                        )
        if stats is not None and want_crcs:
            stats["shard_crcs"] = crcs
        if durable:
            # same contract as the pipelined arm: a durable batch
            # encode must not return until the shard bytes are on disk
            # (success path only — a failed fsync fails the encode)
            for fs in outs:
                for f in fs:
                    f.flush()
                    os.fsync(f.fileno())
    except BaseException:
        # abort contract, matching the pipelined arm: no partial (or
        # written-but-unsynced, when the durable fsync failed) shard
        # set may survive for ANY volume — shard_presence counts any
        # existing .ecNN as a valid shard, so leftovers would read as
        # complete volumes to a later rebuild/scrub
        for fs in outs:
            for f in fs:
                try:
                    f.close()
                except OSError:
                    pass
        for base in base_file_names:
            for i in range(TOTAL_SHARDS):
                try:
                    os.remove(base + to_ext(i))
                except OSError:
                    pass
        raise
    finally:
        for f in dats:
            f.close()
        for fs in outs:
            for f in fs:
                if not f.closed:
                    try:
                        f.close()
                    except OSError:
                        pass


def rebuild_ec_files(
    base_file_name: str,
    rs: ReedSolomon | None = None,
    buffer_size: int | None = None,
    durable: bool = False,
    stats: dict | None = None,
    want_crcs: bool = False,
) -> list[int]:
    """Regenerate whichever .ec files are missing from the ones present
    (ec_encoder.go:83 generateMissingEcFiles). Returns rebuilt ids.

    buffer_size=None lets each driver pick its default (1 MiB classic
    batches; 8 MiB pipelined tiles on TPU/native hosts). want_crcs
    lands {rebuilt shard id: whole-file CRC-32C} in `stats` on every
    driver (see write_ec_files)."""
    rs = rs or new_encoder()
    if rs.data_shards != DATA_SHARDS or rs.parity_shards != PARITY_SHARDS:
        raise ValueError("shard-file layout is fixed at RS(10,4)")
    if _use_stream_driver(rs) or _stream_host_codec(rs):
        from seaweedfs_tpu.ec import ec_stream

        rebuild_fn = fetch_fn = None
        if not _use_stream_driver(rs):
            rebuild_fn, fetch_fn = ec_stream.local_rebuild_fns(
                rs, want_crcs=want_crcs
            )
        return ec_stream.stream_rebuild_ec_files(
            base_file_name,
            tile_bytes=buffer_size,
            rebuild_fn=rebuild_fn,
            fetch_fn=fetch_fn,
            durable=durable,
            stats=stats,
            want_crcs=want_crcs,
        )
    buffer_size = buffer_size or SMALL_BLOCK_SIZE
    present, missing = shard_presence(base_file_name)
    if not missing:
        return []
    if sum(present) < rs.data_shards:
        raise ValueError(
            f"too few shard files to rebuild: {sum(present)} of {rs.data_shards}"
        )

    from seaweedfs_tpu.stats.metrics import (
        EC_REPAIR_BYTES_READ,
        EC_REPAIR_BYTES_WRITTEN,
    )

    read_local = EC_REPAIR_BYTES_READ.labels("local")
    inputs = {
        i: open(base_file_name + to_ext(i), "rb")
        for i in range(TOTAL_SHARDS)
        if present[i]
    }
    outputs = {i: open(base_file_name + to_ext(i), "wb") for i in missing}
    crcs = {i: 0 for i in missing}  # running rebuilt-file CRCs (want_crcs)
    try:
        from seaweedfs_tpu.util.crc import crc32c

        shard_size = os.path.getsize(
            base_file_name + to_ext(next(iter(inputs)))
        )
        offset = 0
        while offset < shard_size:
            step = min(buffer_size, shard_size - offset)
            shards: list[np.ndarray | None] = [None] * TOTAL_SHARDS
            for i, f in inputs.items():
                f.seek(offset)
                raw = f.read(step)
                if len(raw) != step:
                    raise ValueError(
                        f"ec shard {i} truncated: expected {step} at {offset}"
                    )
                read_local.inc(len(raw))
                shards[i] = np.frombuffer(raw, dtype=np.uint8)
            rs.reconstruct(shards)
            for i in missing:
                chunk = shards[i].tobytes()  # type: ignore[union-attr]
                outputs[i].write(chunk)
                if want_crcs:
                    crcs[i] = crc32c(chunk, crcs[i])
                EC_REPAIR_BYTES_WRITTEN.inc(step)
            offset += step
        if stats is not None and want_crcs:
            stats["shard_crcs"] = crcs
        if durable:
            for f in outputs.values():
                f.flush()
                os.fsync(f.fileno())
    except BaseException:
        # partial (or written-but-unsynced, when the durable fsync
        # failed) targets must not survive: shard_presence counts ANY
        # existing .ecNN as a valid shard, so a retry would see "not
        # missing", skip the rebuild AND the fsync, and a later crash
        # could lose the shard bytes under a complete .ecx — the same
        # contract the stream driver enforces on its failure paths
        for i in missing:
            try:
                os.remove(base_file_name + to_ext(i))
            except OSError:
                pass
        raise
    finally:
        for f in inputs.values():
            f.close()
        for f in outputs.values():
            f.close()
    return missing


def rebuild_ec_files_batch(
    base_file_names: list[str],
    codec=None,
    tile_bytes: int | None = None,
    stats: dict | None = None,
    durable: bool = False,
    want_crcs: bool = False,
) -> list[list[int]]:
    """Regenerate missing .ec files for N volumes, batched: volumes
    sharing a (survivors, targets) signature ride ONE sharded mesh
    decode program per tile round
    (ec_stream.stream_rebuild_ec_files_batch over
    parallel/mesh_codec.reconstruct_batch_u32) — the BatchRebuild
    verb's driver, so the RepairScheduler amortizes dispatch latency
    over concurrent small-volume rebuilds instead of paying it per
    volume. Every survivor must be local (the remote rack-gather path
    stays per-volume).

    WEED_EC_PIPELINE=0 restores a serial per-volume rebuild_ec_files
    loop wholesale — byte-identical output, same durable contract.
    Returns the rebuilt id lists in input order; want_crcs lands
    `shard_crcs` in stats as one {rebuilt id: whole-file CRC-32C} dict
    per volume on both arms."""
    from seaweedfs_tpu.ec import ec_stream

    if not base_file_names:
        return []
    if ec_stream.pipeline_enabled():
        return ec_stream.stream_rebuild_ec_files_batch(
            base_file_names,
            codec=codec,
            tile_bytes=tile_bytes,
            stats=stats,
            durable=durable,
            want_crcs=want_crcs,
        )
    results = []
    all_crcs = []
    for base in base_file_names:
        s: dict = {}
        results.append(
            rebuild_ec_files(
                base,
                buffer_size=tile_bytes,
                durable=durable,
                stats=s,
                want_crcs=want_crcs,
            )
        )
        all_crcs.append(s.get("shard_crcs") or {})
    if stats is not None:
        stats["batch_volumes"] = len(base_file_names)
        if want_crcs:
            stats["shard_crcs"] = all_crcs
    return results


# --- .ecx sorted index ------------------------------------------------------

def compact_idx_entries(idx_data: bytes) -> bytes:
    """Replay .idx entries last-wins into sorted .ecx bytes.

    Mirrors readCompactMap + AscendingVisit (ec_encoder.go:283-302,
    compact_map.go): live entries are set; a delete tombstones an
    existing entry in place (the key stays, size=TombstoneFileSize)
    when the entry was inserted in ascending key order (the reference's
    sorted `values` array) — a delete of an out-of-order insert (the
    reference's `overflow` array) removes the key entirely, and a
    delete of a zero-size entry is a no-op (CompactSection.Delete only
    tombstones Size > 0). Unknown keys are ignored."""
    state: dict[int, tuple[int, int]] = {}
    in_order: dict[int, bool] = {}
    max_key_seen = -1
    for key, offset, size in idx_codec.iter_entries(idx_data):
        if offset != 0 and size != t.TOMBSTONE_FILE_SIZE:
            if key not in state:
                in_order[key] = key > max_key_seen
            state[key] = (offset, size)
            max_key_seen = max(max_key_seen, key)
        else:
            old = state.get(key)
            if old is None:
                continue
            if not in_order.get(key, True):
                del state[key]  # overflow entries are removed outright
            elif old[1] > 0 and old[1] != t.TOMBSTONE_FILE_SIZE:
                state[key] = (old[0], t.TOMBSTONE_FILE_SIZE)
    keys = np.array(sorted(state), dtype=np.uint64)
    offsets = np.array([state[int(k)][0] for k in keys], dtype=np.uint64)
    sizes = np.array([state[int(k)][1] for k in keys], dtype=np.uint32)
    return idx_codec.arrays_to_entries(keys, offsets, sizes)


def write_sorted_file_from_idx(
    base_file_name: str, ext: str = ".ecx", durable: bool = False
) -> None:
    """.idx → sorted .ecx (ec_encoder.go:26 WriteSortedFileFromIdx).

    durable=True routes through util/durable.publish (tmp + fsync +
    rename + dirsync): the .ecx is the encode's commit record — if a
    crash leaves it visible, the shard files it indexes must be whole,
    so the generate verbs fsync shards first and publish this last
    (weedcrash ec-encode workload, docs/ANALYSIS.md v3)."""
    with open(base_file_name + ".idx", "rb") as f:
        idx_data = f.read()
    entries = compact_idx_entries(idx_data)
    if durable:
        from seaweedfs_tpu.util import durable as _durable

        tmp = base_file_name + ext + ".tmp"
        with open(tmp, "wb") as f:
            f.write(entries)
        _durable.publish(tmp, base_file_name + ext)
        return
    with open(base_file_name + ext, "wb") as f:
        f.write(entries)


def write_idx_file_from_ec_index(base_file_name: str) -> None:
    """.ecx (+ .ecj tombstones) → .idx, for decoding shards back to a
    normal volume (ec_decoder.go:17 WriteIdxFileFromEcIndex)."""
    with open(base_file_name + ".ecx", "rb") as f:
        ecx = f.read()
    out = bytearray(ecx)
    ecj_path = base_file_name + ".ecj"
    if os.path.exists(ecj_path):
        with open(ecj_path, "rb") as f:
            ecj = f.read()
        for off in range(0, len(ecj) - t.NEEDLE_ID_SIZE + 1, t.NEEDLE_ID_SIZE):
            key = t.bytes_to_needle_id(ecj[off : off + t.NEEDLE_ID_SIZE])
            out += idx_codec.pack_entry(key, 0, t.TOMBSTONE_FILE_SIZE)
    with open(base_file_name + ".idx", "wb") as f:
        f.write(bytes(out))


def find_dat_file_size(base_file_name: str, version: int) -> int:
    """Max (offset + record size) over live .ecx entries
    (ec_decoder.go:47 FindDatFileSize)."""
    from seaweedfs_tpu.storage.needle import get_actual_size

    with open(base_file_name + ".ecx", "rb") as f:
        ecx = f.read()
    dat_size = 0
    for key, offset, size in idx_codec.iter_entries(ecx):
        if size == t.TOMBSTONE_FILE_SIZE:
            continue
        end = t.units_to_offset(offset) + get_actual_size(size, version)
        dat_size = max(dat_size, end)
    return dat_size


def read_shard_intervals(
    base_file_name: str,
    offset: int,
    size: int,
    dat_size: int,
    large_block_size: int = LARGE_BLOCK_SIZE,
    small_block_size: int = SMALL_BLOCK_SIZE,
) -> bytes:
    """Read a .dat byte span back out of local shard files via the
    interval math — the single-host degraded-read building block."""
    out = bytearray()
    handles: dict[int, object] = {}
    try:
        for iv in locate.locate_data(
            large_block_size, small_block_size, dat_size, offset, size
        ):
            shard_id, shard_off = iv.to_shard_id_and_offset(
                large_block_size, small_block_size
            )
            f = handles.get(shard_id)
            if f is None:
                f = handles[shard_id] = open(base_file_name + to_ext(shard_id), "rb")
            f.seek(shard_off)
            chunk = f.read(iv.size)
            if len(chunk) < iv.size:
                chunk += bytes(iv.size - len(chunk))
            out += chunk
    finally:
        for f in handles.values():
            f.close()
    return bytes(out)
