"""EC striping interval math — (offset, size) spans → shard intervals.

Pure-math port of reference weed/storage/erasure_coding/ec_locate.go
(SURVEY.md §2.1 marks it "port verbatim"): a sealed volume is striped
row-major over 10 data shards in two tiers — 1 GB rows first, then 1 MB
rows — and reads translate byte spans of the original .dat into
per-shard (shard_id, shard_offset, size) intervals.

Includes the reference's row-count quirk (ec_locate.go:15): the number
of large-block rows encoded into an Interval is derived as
(dat_size + 10·small) // (large·10) so it can be recovered from a shard
size alone.
"""

from __future__ import annotations

from dataclasses import dataclass

DATA_SHARDS = 10
PARITY_SHARDS = 4
TOTAL_SHARDS = DATA_SHARDS + PARITY_SHARDS
LARGE_BLOCK_SIZE = 1024 * 1024 * 1024  # 1 GB
SMALL_BLOCK_SIZE = 1024 * 1024  # 1 MB


@dataclass(frozen=True)
class Interval:
    block_index: int
    inner_block_offset: int
    size: int
    is_large_block: bool
    large_block_rows_count: int

    def to_shard_id_and_offset(
        self, large_block_size: int = LARGE_BLOCK_SIZE, small_block_size: int = SMALL_BLOCK_SIZE
    ) -> tuple[int, int]:
        """(shard id, offset within that shard's .ec file)."""
        offset = self.inner_block_offset
        row_index = self.block_index // DATA_SHARDS
        if self.is_large_block:
            offset += row_index * large_block_size
        else:
            offset += (
                self.large_block_rows_count * large_block_size
                + row_index * small_block_size
            )
        return self.block_index % DATA_SHARDS, offset


def _locate_within_blocks(block_length: int, offset: int) -> tuple[int, int]:
    return offset // block_length, offset % block_length


def _locate_offset(
    large: int, small: int, dat_size: int, offset: int
) -> tuple[int, bool, int]:
    large_row_size = large * DATA_SHARDS
    # NOTE: dat_size an EXACT multiple of the large row size is a known
    # reference edge case: the encoder's strict-greater loop sends the
    # final full row through the small tier, while this floor division
    # counts it as a large row (ec_locate.go:52 vs ec_encoder.go:205) —
    # reads of that last row would map to the wrong shard offsets. Kept
    # bit-identical for wire compatibility; the volume layer never
    # seals at an exact multiple (superblock + 8B-padded needles).
    n_large_rows = dat_size // large_row_size
    if offset < n_large_rows * large_row_size:
        idx, inner = _locate_within_blocks(large, offset)
        return idx, True, inner
    idx, inner = _locate_within_blocks(small, offset - n_large_rows * large_row_size)
    return idx, False, inner


def locate_data(
    large: int, small: int, dat_size: int, offset: int, size: int
) -> list[Interval]:
    """Split [offset, offset+size) of the original .dat into striping
    intervals (ec_locate.go:11 LocateData)."""
    block_index, is_large, inner = _locate_offset(large, small, dat_size, offset)
    n_large_rows = (dat_size + DATA_SHARDS * small) // (large * DATA_SHARDS)

    intervals: list[Interval] = []
    while size > 0:
        block_remaining = (large if is_large else small) - inner
        take = min(size, block_remaining)
        intervals.append(
            Interval(
                block_index=block_index,
                inner_block_offset=inner,
                size=take,
                is_large_block=is_large,
                large_block_rows_count=n_large_rows,
            )
        )
        if size <= block_remaining:
            return intervals
        size -= take
        block_index += 1
        if is_large and block_index == n_large_rows * DATA_SHARDS:
            is_large = False
            block_index = 0
        inner = 0
    return intervals
